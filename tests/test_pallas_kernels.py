"""Pallas kernel tier numerics vs XLA reference compositions
(interpret mode on the CPU test backend; same kernels compile on TPU).

Reference analogs: paddle/phi/kernels/fusion/gpu/* fused kernels and the
flash-attn dynload path (paddle/phi/kernels/gpu/flash_attn_kernel.cu);
test strategy per SURVEY §4 (OpTest numeric checking vs reference impl).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.incubate.nn.pallas import flash_attn as pfa
from paddle_tpu.incubate.nn.pallas import norms as pnorms


def _ref_attention(q, k, v, causal):
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    s = qh.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if causal:
        m = jnp.tril(jnp.ones((logits.shape[-2], logits.shape[-1]), bool))
        logits = jnp.where(m, logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", w, vh), 1, 2)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward(self, causal):
        rng = np.random.RandomState(0)
        b, s, h, d = 1, 256, 2, 64
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        out = pfa.flash_attention(q, k, v, causal=causal)
        ref = _ref_attention(q, k, v, causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads(self, causal):
        rng = np.random.RandomState(1)
        b, s, h, d = 1, 256, 2, 64
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        g = jax.grad(loss(lambda q, k, v: pfa.flash_attention(
            q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: _ref_attention(
            q, k, v, causal)), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g, gr):
            np.testing.assert_allclose(a, b_, atol=1e-4, rtol=1e-4)

    def test_gqa(self):
        rng = np.random.RandomState(2)
        b, s, hq, hkv, d = 1, 256, 4, 2, 64
        q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
        out = pfa.flash_attention(q, k, v, causal=True)
        kr = jnp.repeat(k, 2, axis=2)
        vr = jnp.repeat(v, 2, axis=2)
        ref = _ref_attention(q, kr, vr, True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_kv_longer_than_q(self):
        """Bottom-right-aligned causal mask (chunked prefill): must match
        the XLA fallback's tril(..., sk - sq) alignment."""
        rng = np.random.RandomState(4)
        b, h, d = 1, 2, 64
        sq, sk = 128, 256
        q = jnp.asarray(rng.randn(b, sq, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
        out = pfa.flash_attention(q, k, v, causal=True)
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * d ** -0.5
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        logits = jnp.where(mask, logits, -1e30)
        w = jax.nn.softmax(logits, axis=-1)
        ref = jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", w, vh), 1, 2)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
        # grads flow through the offset mask too
        g = jax.grad(lambda q, k, v: (pfa.flash_attention(
            q, k, v, causal=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        assert all(np.isfinite(np.asarray(x)).all() for x in g)

    def test_bf16(self):
        rng = np.random.RandomState(3)
        b, s, h, d = 1, 128, 2, 128
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, s, h, d), jnp.bfloat16)
        out = pfa.flash_attention(q, k, v, causal=True)
        ref = _ref_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                             v.astype(jnp.float32), True)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref,
                                   atol=3e-2, rtol=3e-2)


class TestPallasNorms:
    def test_rms_norm(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(6, 96, 256), jnp.float32)
        w = jnp.asarray(rng.randn(256), jnp.float32)
        out = pnorms.rms_norm(x, w)
        ref = (x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6)) * w
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_rms_norm_bias_grad(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 256), jnp.float32)
        w = jnp.asarray(rng.randn(256), jnp.float32)
        b = jnp.asarray(rng.randn(256), jnp.float32)
        out = pnorms.rms_norm(x, w, b)
        ref = (x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6)) * w + b
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
        g = jax.grad(lambda x: pnorms.rms_norm(x, w, b).sum())(x)
        gr = jax.grad(lambda x: (((x / jnp.sqrt(
            jnp.mean(x * x, -1, keepdims=True) + 1e-6)) * w) + b).sum())(x)
        np.testing.assert_allclose(g, gr, atol=1e-5, rtol=1e-5)

    def test_layer_norm(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(8, 128), jnp.float32)
        w = jnp.asarray(rng.randn(128), jnp.float32)
        b = jnp.asarray(rng.randn(128), jnp.float32)
        out = pnorms.layer_norm(x, w, b)
        mu = x.mean(-1, keepdims=True)
        xc = x - mu
        ref = xc / jnp.sqrt((xc * xc).mean(-1, keepdims=True) + 1e-5) * w + b
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


class TestFusedOpsDispatch:
    def test_fused_rms_norm_pallas_path(self):
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn.functional import fused_ops
        from paddle_tpu.incubate.nn.functional import fused_rms_norm

        x = pt.to_tensor(np.random.RandomState(0).randn(2, 8, 256)
                         .astype(np.float32))
        w = pt.to_tensor(np.ones(256, np.float32))
        xn = x.numpy()
        ref = xn / np.sqrt((xn * xn).mean(-1, keepdims=True) + 1e-6)
        # exercise BOTH branches: forced Pallas dispatch and XLA fallback
        fused_ops._FORCE_PALLAS = True
        try:
            out_pallas = fused_rms_norm(x, w)
        finally:
            fused_ops._FORCE_PALLAS = False
        out_xla = fused_rms_norm(x, w)
        np.testing.assert_allclose(out_pallas.numpy(), ref, atol=1e-5,
                                   rtol=1e-5)
        np.testing.assert_allclose(out_xla.numpy(), ref, atol=1e-5,
                                   rtol=1e-5)

    def test_fused_rms_norm_residual(self):
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn.functional import fused_rms_norm

        rng = np.random.RandomState(1)
        x = pt.to_tensor(rng.randn(2, 4, 128).astype(np.float32))
        r = pt.to_tensor(rng.randn(2, 4, 128).astype(np.float32))
        w = pt.to_tensor(np.ones(128, np.float32))
        out, new_resid = fused_rms_norm(x, w, residual=r)
        s = x.numpy() + r.numpy()
        np.testing.assert_allclose(new_resid.numpy(), s, atol=1e-6)
        ref = s / np.sqrt((s * s).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-5, rtol=1e-5)


class TestFusedFlashBackward:
    """Single-pass fused backward (VERDICT r4 next #8): dk/dv/dq from
    one (j, i) sweep sharing the s and dp matmuls; must bit-match the
    two-kernel split in interpret mode and respect the scratch cap."""

    def _grads(self, fn, s, bq, bk, causal, d=64, bh=2, seed=0):
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn.pallas import flash_attn as F

        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((bh, s, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((bh, s, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((bh, s, d)), jnp.float32)
        do = jnp.asarray(rng.standard_normal((bh, s, d)), jnp.float32)
        scale = d ** -0.5
        out, lse = F._flash_fwd(q, k, v, causal, scale, bq, bk, True)
        return fn(q, k, v, out, lse, do, causal, scale, bq, bk,
                  s // bq, s // bk, True)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("s,bq,bk", [(256, 128, 128), (256, 128, 64),
                                         (512, 256, 128)])
    def test_fused_matches_split(self, causal, s, bq, bk):
        from paddle_tpu.incubate.nn.pallas import flash_attn as F

        fused = self._grads(F._flash_bwd_fused, s, bq, bk, causal)
        split = self._grads(F._flash_bwd_split, s, bq, bk, causal)
        for name, a, b in zip("dq dk dv".split(), fused, split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5,
                                       err_msg=name)

    def test_scratch_cap_falls_back_to_split(self):
        """Sequences whose dq scratch would blow VMEM use the split
        path; cross-length (sq != sk) always does."""
        import jax.numpy as jnp

        from paddle_tpu.incubate.nn.pallas import flash_attn as F

        old = F._FUSED_BWD_MAX_SEQ_D
        try:
            F._FUSED_BWD_MAX_SEQ_D = 0     # force the fallback
            rng = np.random.default_rng(1)
            q = jnp.asarray(rng.standard_normal((2, 256, 64)),
                            jnp.float32)
            do = jnp.asarray(rng.standard_normal((2, 256, 64)),
                             jnp.float32)
            scale = 64 ** -0.5
            out, lse = F._flash_fwd(q, q, q, True, scale, 128, 128, True)
            got = F._flash_bwd(q, q, q, out, lse, do, True, scale,
                               128, 128, True)
            F._FUSED_BWD_MAX_SEQ_D = old
            want = F._flash_bwd(q, q, q, out, lse, do, True, scale,
                                128, 128, True)
            for a, b in zip(got, want):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-5)
        finally:
            F._FUSED_BWD_MAX_SEQ_D = old
