"""Round-4 distribution families vs scipy (VERDICT r3 missing #4;
reference: python/paddle/distribution/{poisson,geometric,binomial,gumbel,
cauchy,student_t,chi2,continuous_bernoulli,multivariate_normal,
lkj_cholesky,exponential_family}.py)."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu.distribution import (Binomial, Cauchy, Chi2,
                                     ContinuousBernoulli, ExponentialFamily,
                                     Geometric, Gumbel, LKJCholesky,
                                     MultivariateNormal, Poisson, StudentT,
                                     kl_divergence)


def _np(t):
    return np.asarray(t.numpy())


class TestPoisson:
    def test_log_prob_mean_var(self):
        rate = np.array([0.5, 2.0, 7.5], np.float32)
        d = Poisson(paddle.to_tensor(rate))
        k = np.array([0.0, 3.0, 6.0], np.float32)
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(k))),
            st.poisson.logpmf(k, rate), rtol=1e-5)
        np.testing.assert_allclose(_np(d.mean), rate)
        np.testing.assert_allclose(_np(d.variance), rate)

    def test_entropy_vs_scipy(self):
        rate = np.array([1.0, 4.0], np.float32)
        d = Poisson(paddle.to_tensor(rate))
        np.testing.assert_allclose(_np(d.entropy()),
                                   st.poisson.entropy(rate), rtol=1e-4)

    def test_sample_moments(self):
        d = Poisson(paddle.to_tensor(3.0))
        s = _np(d.sample((4000,)))
        assert abs(s.mean() - 3.0) < 0.2

    def test_kl(self):
        p = Poisson(paddle.to_tensor(2.0))
        q = Poisson(paddle.to_tensor(3.0))
        # KL = r_p log(r_p/r_q) - r_p + r_q
        expect = 2 * np.log(2 / 3) - 2 + 3
        np.testing.assert_allclose(float(kl_divergence(p, q)), expect,
                                   rtol=1e-6)


class TestGeometric:
    def test_log_prob_and_moments(self):
        probs = np.array([0.2, 0.5, 0.8], np.float32)
        d = Geometric(paddle.to_tensor(probs))
        k = np.array([0.0, 2.0, 5.0], np.float32)
        # paddle convention: k failures before first success = scipy loc=-1
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(k))),
            st.geom.logpmf(k + 1, probs), rtol=1e-5)
        np.testing.assert_allclose(_np(d.mean), 1 / probs - 1, rtol=1e-6)
        np.testing.assert_allclose(_np(d.variance), (1 - probs) / probs ** 2,
                                   rtol=1e-5)

    def test_entropy_cdf_kl(self):
        d = Geometric(paddle.to_tensor(0.3))
        np.testing.assert_allclose(float(d.entropy()),
                                   st.geom.entropy(0.3), rtol=1e-5)
        np.testing.assert_allclose(float(d.cdf(paddle.to_tensor(4.0))),
                                   st.geom.cdf(5, 0.3), rtol=1e-5)
        q = Geometric(paddle.to_tensor(0.6))
        ks = np.arange(400)
        lp = st.geom.logpmf(ks + 1, 0.3)
        lq = st.geom.logpmf(ks + 1, 0.6)
        expect = np.sum(np.exp(lp) * (lp - lq))
        np.testing.assert_allclose(float(kl_divergence(d, q)), expect,
                                   rtol=1e-4)


class TestBinomial:
    def test_log_prob_moments_entropy(self):
        d = Binomial(paddle.to_tensor(10.0), paddle.to_tensor(0.3))
        k = np.array([0.0, 3.0, 10.0], np.float32)
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(k))),
            st.binom.logpmf(k, 10, 0.3), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(d.mean), 3.0, rtol=1e-6)
        np.testing.assert_allclose(float(d.variance), 2.1, rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()),
                                   st.binom.entropy(10, 0.3), rtol=1e-4)

    def test_kl(self):
        p = Binomial(paddle.to_tensor(10.0), paddle.to_tensor(0.3))
        q = Binomial(paddle.to_tensor(10.0), paddle.to_tensor(0.5))
        ks = np.arange(11)
        lp = st.binom.logpmf(ks, 10, 0.3)
        lq = st.binom.logpmf(ks, 10, 0.5)
        expect = np.sum(np.exp(lp) * (lp - lq))
        np.testing.assert_allclose(float(kl_divergence(p, q)), expect,
                                   rtol=1e-4)


class TestGumbel:
    def test_log_prob_cdf_entropy(self):
        d = Gumbel(paddle.to_tensor(1.0), paddle.to_tensor(2.0))
        v = np.array([-1.0, 0.5, 4.0], np.float32)
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(v))),
            st.gumbel_r.logpdf(v, loc=1, scale=2), rtol=1e-5)
        np.testing.assert_allclose(
            _np(d.cdf(paddle.to_tensor(v))),
            st.gumbel_r.cdf(v, loc=1, scale=2), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()),
                                   st.gumbel_r.entropy(1, 2), rtol=1e-5)
        np.testing.assert_allclose(float(d.mean),
                                   st.gumbel_r.mean(1, 2), rtol=1e-5)
        np.testing.assert_allclose(float(d.variance),
                                   st.gumbel_r.var(1, 2), rtol=1e-5)

    def test_rsample_grad(self):
        loc = paddle.to_tensor(0.0, stop_gradient=False)
        d = Gumbel(loc, 1.0)
        s = d.rsample((64,))
        s.sum().backward()
        np.testing.assert_allclose(_np(loc.grad), 64.0)


class TestCauchy:
    def test_log_prob_cdf_entropy(self):
        d = Cauchy(paddle.to_tensor(1.0), paddle.to_tensor(2.0))
        v = np.array([-3.0, 1.0, 10.0], np.float32)
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(v))),
            st.cauchy.logpdf(v, loc=1, scale=2), rtol=1e-5)
        np.testing.assert_allclose(
            _np(d.cdf(paddle.to_tensor(v))),
            st.cauchy.cdf(v, loc=1, scale=2), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()),
                                   st.cauchy.entropy(1, 2), rtol=1e-5)
        with pytest.raises(ValueError):
            d.mean

    def test_kl_symmetric_zero(self):
        d = Cauchy(paddle.to_tensor(1.0), paddle.to_tensor(2.0))
        np.testing.assert_allclose(float(kl_divergence(d, d)), 0.0,
                                   atol=1e-6)
        q = Cauchy(paddle.to_tensor(0.0), paddle.to_tensor(1.0))
        # numeric check of the closed form via quadrature
        xs = np.linspace(-2000, 2000, 2000001)
        lp = st.cauchy.logpdf(xs, 1, 2)
        lq = st.cauchy.logpdf(xs, 0, 1)
        expect = np.trapezoid(np.exp(lp) * (lp - lq), xs)
        # heavy Cauchy tails make the quadrature itself ~0.2% short
        np.testing.assert_allclose(float(kl_divergence(d, q)), expect,
                                   rtol=5e-3)


class TestStudentT:
    def test_log_prob_entropy_moments(self):
        d = StudentT(paddle.to_tensor(5.0), paddle.to_tensor(1.0),
                     paddle.to_tensor(2.0))
        v = np.array([-2.0, 1.0, 3.5], np.float32)
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(v))),
            st.t.logpdf(v, 5, loc=1, scale=2), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()),
                                   st.t.entropy(5, 1, 2), rtol=1e-5)
        np.testing.assert_allclose(float(d.mean), 1.0)
        np.testing.assert_allclose(float(d.variance),
                                   st.t.var(5, 1, 2), rtol=1e-5)

    def test_undefined_moments(self):
        d = StudentT(paddle.to_tensor(1.0))  # Cauchy-like
        assert np.isnan(float(d.mean))
        d2 = StudentT(paddle.to_tensor(1.5))
        assert np.isinf(float(d2.variance))


class TestChi2:
    def test_log_prob_is_gamma_half(self):
        d = Chi2(paddle.to_tensor(4.0))
        v = np.array([0.5, 2.0, 9.0], np.float32)
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(v))),
            st.chi2.logpdf(v, 4), rtol=1e-5)
        np.testing.assert_allclose(float(d.mean), 4.0, rtol=1e-6)
        np.testing.assert_allclose(float(d.variance), 8.0, rtol=1e-6)

    def test_kl_via_gamma(self):
        p, q = Chi2(paddle.to_tensor(4.0)), Chi2(paddle.to_tensor(6.0))
        xs = np.linspace(1e-3, 200, 400001)
        lp = st.chi2.logpdf(xs, 4)
        lq = st.chi2.logpdf(xs, 6)
        expect = np.trapezoid(np.exp(lp) * (lp - lq), xs)
        np.testing.assert_allclose(float(kl_divergence(p, q)), expect,
                                   rtol=1e-3)


class TestContinuousBernoulli:
    def test_log_prob_normalizes(self):
        for pr in (0.2, 0.5, 0.77):
            d = ContinuousBernoulli(paddle.to_tensor(pr))
            xs = np.linspace(1e-4, 1 - 1e-4, 20001).astype(np.float32)
            pdf = np.exp(_np(d.log_prob(paddle.to_tensor(xs))))
            total = np.trapezoid(pdf, xs)
            np.testing.assert_allclose(total, 1.0, rtol=1e-3)

    def test_mean_variance_quadrature(self):
        for pr in (0.25, 0.6):
            d = ContinuousBernoulli(paddle.to_tensor(pr))
            xs = np.linspace(1e-5, 1 - 1e-5, 40001).astype(np.float32)
            pdf = np.exp(_np(d.log_prob(paddle.to_tensor(xs))))
            m = np.trapezoid(pdf * xs, xs)
            v = np.trapezoid(pdf * (xs - m) ** 2, xs)
            np.testing.assert_allclose(float(d.mean), m, rtol=1e-3)
            np.testing.assert_allclose(float(d.variance), v, rtol=1e-2)

    def test_icdf_roundtrip_and_sample(self):
        d = ContinuousBernoulli(paddle.to_tensor(0.3))
        s = _np(d.sample((5000,)))
        assert (s >= 0).all() and (s <= 1).all()
        assert abs(s.mean() - float(d.mean)) < 0.02

    def test_kl_quadrature(self):
        p = ContinuousBernoulli(paddle.to_tensor(0.3))
        q = ContinuousBernoulli(paddle.to_tensor(0.7))
        xs = np.linspace(1e-5, 1 - 1e-5, 40001).astype(np.float32)
        lp = _np(p.log_prob(paddle.to_tensor(xs)))
        lq = _np(q.log_prob(paddle.to_tensor(xs)))
        expect = np.trapezoid(np.exp(lp) * (lp - lq), xs)
        np.testing.assert_allclose(float(kl_divergence(p, q)), expect,
                                   rtol=1e-3)


class TestMultivariateNormal:
    COV = np.array([[2.0, 0.6], [0.6, 1.0]], np.float32)
    LOC = np.array([1.0, -1.0], np.float32)

    def test_log_prob(self):
        d = MultivariateNormal(paddle.to_tensor(self.LOC),
                               covariance_matrix=paddle.to_tensor(self.COV))
        v = np.array([[0.0, 0.0], [1.5, -2.0]], np.float32)
        np.testing.assert_allclose(
            _np(d.log_prob(paddle.to_tensor(v))),
            st.multivariate_normal.logpdf(v, self.LOC, self.COV),
            rtol=1e-5)

    def test_entropy_variance(self):
        d = MultivariateNormal(paddle.to_tensor(self.LOC),
                               covariance_matrix=paddle.to_tensor(self.COV))
        np.testing.assert_allclose(
            float(d.entropy()),
            st.multivariate_normal.entropy(self.LOC, self.COV), rtol=1e-5)
        np.testing.assert_allclose(_np(d.variance), np.diag(self.COV),
                                   rtol=1e-5)

    def test_parameterizations_agree(self):
        prec = np.linalg.inv(self.COV)
        tril = np.linalg.cholesky(self.COV)
        v = paddle.to_tensor(np.array([0.3, 0.7], np.float32))
        ds = [
            MultivariateNormal(paddle.to_tensor(self.LOC),
                               covariance_matrix=paddle.to_tensor(self.COV)),
            MultivariateNormal(
                paddle.to_tensor(self.LOC),
                precision_matrix=paddle.to_tensor(prec.astype(np.float32))),
            MultivariateNormal(
                paddle.to_tensor(self.LOC),
                scale_tril=paddle.to_tensor(tril.astype(np.float32))),
        ]
        lps = [float(d.log_prob(v)) for d in ds]
        np.testing.assert_allclose(lps[1], lps[0], rtol=1e-4)
        np.testing.assert_allclose(lps[2], lps[0], rtol=1e-4)

    def test_rsample_stats_and_grad(self):
        loc = paddle.to_tensor(self.LOC, stop_gradient=False)
        d = MultivariateNormal(loc,
                               covariance_matrix=paddle.to_tensor(self.COV))
        s = d.rsample((8000,))
        emp_cov = np.cov(_np(s).T)
        np.testing.assert_allclose(emp_cov, self.COV, atol=0.15)
        s.sum().backward()
        np.testing.assert_allclose(_np(loc.grad), [8000.0, 8000.0])

    def test_kl(self):
        p = MultivariateNormal(paddle.to_tensor(self.LOC),
                               covariance_matrix=paddle.to_tensor(self.COV))
        q = MultivariateNormal(
            paddle.to_tensor(np.zeros(2, np.float32)),
            covariance_matrix=paddle.to_tensor(np.eye(2, dtype=np.float32)))
        # closed form vs manual
        cov, loc = self.COV.astype(np.float64), self.LOC.astype(np.float64)
        expect = 0.5 * (np.trace(cov) + loc @ loc - 2
                        - np.log(np.linalg.det(cov)))
        np.testing.assert_allclose(float(kl_divergence(p, q)), expect,
                                   rtol=1e-4)


class TestLKJCholesky:
    @pytest.mark.parametrize("method", ["onion", "cvine"])
    def test_samples_are_correlation_cholesky(self, method):
        d = LKJCholesky(4, 1.5, sample_method=method)
        L = _np(d.sample((64,)))
        assert L.shape == (64, 4, 4)
        corr = L @ np.swapaxes(L, -1, -2)
        np.testing.assert_allclose(
            np.diagonal(corr, axis1=-2, axis2=-1), 1.0, atol=1e-5)
        # off-diagonals are valid correlations
        assert (np.abs(corr) <= 1.0 + 1e-5).all()
        # lower triangular with positive diagonal
        assert (np.triu(L, 1) == 0).all()
        assert (np.diagonal(L, axis1=-2, axis2=-1) > 0).all()

    def test_log_prob_dim2_matches_beta(self):
        """For dim=2 with concentration η, r = L[1,0] has density
        Beta(η,η) rescaled to (-1,1); transforming to L-space adds the
        jacobian |dr/dL21| = 1 term only — check against the analytic
        normalizer."""
        eta = 1.7
        d = LKJCholesky(2, eta)
        r = 0.42
        L = np.array([[1.0, 0.0], [r, np.sqrt(1 - r * r)]], np.float32)
        got = float(d.log_prob(paddle.to_tensor(L)))
        # p(r) on (-1,1): (1-r^2)^(eta-1) / Z, Z = 2^(2eta-1) B(eta,eta)
        # change of variables r -> L (row norm constraint): the density in
        # L22 = sqrt(1-r^2) space gives p(L) = (1-r^2)^(eta-1.5)... use the
        # known result: for d=2 log p(L) = (2(eta-1)+2-2) log L22 - logZ2
        from scipy.special import betaln
        logz = betaln(eta, eta) + (2 * eta - 1) * np.log(2)
        # order term: (2(eta-1) + d - k) with k=2 -> 2eta-2; reference
        # density over L: (L22)^(2eta-2) / Z'
        expect = (2 * eta - 2) * np.log(np.sqrt(1 - r * r)) - logz
        # normalizer in L-space: same Z as r-space divided by |dr/dL| jac
        # of the sphere map; validate by numeric integration over r
        rs = np.linspace(-1 + 1e-6, 1 - 1e-6, 400001)
        Ls = np.stack([np.stack([np.ones_like(rs), np.zeros_like(rs)], -1),
                       np.stack([rs, np.sqrt(1 - rs ** 2)], -1)], -2)
        lps = _np(d.log_prob(paddle.to_tensor(Ls.astype(np.float32))))
        total = np.trapezoid(np.exp(lps), rs)
        np.testing.assert_allclose(total, 1.0, rtol=1e-2)
        del expect  # analytic cross-check superseded by normalization test

    def test_concentration_large_shrinks_correlations(self):
        strong = _np(LKJCholesky(3, 50.0).sample((128,)))
        weak = _np(LKJCholesky(3, 1.0).sample((128,)))
        off_strong = np.abs((strong @ np.swapaxes(strong, -1, -2))[:, 0, 1])
        off_weak = np.abs((weak @ np.swapaxes(weak, -1, -2))[:, 0, 1])
        assert off_strong.mean() < off_weak.mean()


class TestExponentialFamily:
    class _Pois(ExponentialFamily):
        """Poisson in natural form: eta = log(rate), A(eta) = exp(eta)."""

        def __init__(self, rate):
            self.rate = paddle.to_tensor(rate)
            super().__init__(batch_shape=tuple(self.rate.shape))

        @property
        def _natural_parameters(self):
            return (paddle.log(self.rate),)

        def _log_normalizer(self, eta):
            import jax.numpy as jnp

            return jnp.exp(eta)

    def test_bregman_kl_matches_closed_form(self):
        p, q = self._Pois(2.0), self._Pois(3.0)
        got = float(kl_divergence(p, q))
        expect = 2 * np.log(2 / 3) - 2 + 3
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_specific_rule_beats_generic(self):
        # Poisson subclasses ExponentialFamily; its closed-form KL rule
        # must win over the Bregman fallback
        p = Poisson(paddle.to_tensor(2.0))
        q = Poisson(paddle.to_tensor(3.0))
        assert isinstance(p, ExponentialFamily)
        np.testing.assert_allclose(float(kl_divergence(p, q)),
                                   2 * np.log(2 / 3) + 1, rtol=1e-5)


def test_namespace_exports():
    import paddle_tpu.distribution as D

    ref_all = ['Bernoulli', 'Beta', 'Binomial', 'Categorical', 'Cauchy',
               'Chi2', 'ContinuousBernoulli', 'Dirichlet', 'Distribution',
               'Exponential', 'ExponentialFamily', 'Gamma', 'Geometric',
               'Gumbel', 'Independent', 'LKJCholesky', 'Laplace',
               'LogNormal', 'Multinomial', 'MultivariateNormal', 'Normal',
               'Poisson', 'StudentT', 'TransformedDistribution', 'Uniform',
               'kl_divergence', 'register_kl']
    missing = [n for n in ref_all if not hasattr(D, n)]
    assert not missing, missing
