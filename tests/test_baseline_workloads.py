"""Miniature versions of the five BASELINE.json workloads (BASELINE.md):
 #1 MNIST+LeNet single device, #2 ResNet DP, #3 BERT sharding stage-2,
 #4 GPT hybrid 1F1B pipeline, #5 Llama semi-auto (dp x mp mesh + recompute).
Each trains for a few steps and the loss must fall."""
import os

import numpy as np
import pytest

import paddle_tpu as pt


# --------------------------------------------------------------- #1 MNIST
def test_baseline1_mnist_lenet():
    os.environ["PADDLE_TPU_SYNTH_SAMPLES"] = "256"
    try:
        from paddle_tpu.vision.datasets import MNIST
        from paddle_tpu.vision.models import LeNet

        ds = MNIST(mode="train", download=False)
        loader = pt.io.DataLoader(ds, batch_size=64, shuffle=True)
        model = LeNet()
        opt = pt.optimizer.Adam(parameters=model.parameters(),
                                learning_rate=1e-3)
        loss_fn = pt.nn.CrossEntropyLoss()
        first = last = None
        for epoch in range(4):
            for x, y in loader:
                logits = model(x)
                loss = loss_fn(logits, y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                first = first if first is not None else float(loss)
                last = float(loss)
        assert last < first, (first, last)
    finally:
        del os.environ["PADDLE_TPU_SYNTH_SAMPLES"]


# --------------------------------------------------------------- #2 ResNet DP
def _resnet_dp_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu.vision.models import resnet18

    dist.init_parallel_env(backend="cpu")
    r = dist.get_rank()
    pt.seed(0)
    model = pt.DataParallel(resnet18(num_classes=4))
    opt = pt.optimizer.SGD(parameters=model.parameters(),
                           learning_rate=0.01)
    rng = np.random.RandomState(r)
    loss_fn = pt.nn.CrossEntropyLoss()
    first = last = None
    for _ in range(3):
        x = pt.to_tensor(rng.randn(2, 3, 32, 32).astype(np.float32))
        y = pt.to_tensor(rng.randint(0, 4, (2,)).astype(np.int32))
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
    # ranks hold identical params after synced updates
    import hashlib

    h = hashlib.sha1(b"".join(
        p.numpy().tobytes() for p in model.parameters())).hexdigest()
    from paddle_tpu.distributed.store import create_or_get_global_tcp_store

    store = create_or_get_global_tcp_store()
    store.set(f"resnet_hash_{r}", h)
    assert store.get("resnet_hash_0").decode() == h


def test_baseline2_resnet_dp():
    from paddle_tpu.distributed.spawn import spawn

    spawn(_resnet_dp_worker, nprocs=2)


# --------------------------------------------------------------- #3 BERT s2
def _bert_sharding_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.models import (BertForPreTraining,
                                   BertPretrainingCriterion, bert_tiny)

    dist.init_parallel_env(backend="cpu")
    pt.seed(5)
    cfg = bert_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    model = BertForPreTraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=5e-3,
                             parameters=model.parameters())
    model_w, opt, _ = group_sharded_parallel(model, opt, "os_g")
    crit = BertPretrainingCriterion(cfg.vocab_size)
    rng = np.random.RandomState(0)  # same data both ranks (sync check)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16))
                       .astype(np.int32))
    mlm = np.full((2, 16), -100, np.int64)
    mlm[:, :4] = rng.randint(0, cfg.vocab_size, (2, 4))
    nsp = pt.to_tensor(rng.randint(0, 2, (2,)).astype(np.int32))
    first = last = None
    for _ in range(4):
        scores, rel = model_w(ids)
        loss = crit(scores, rel, pt.to_tensor(mlm), nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first, (first, last)


def test_baseline3_bert_sharding_stage2():
    from paddle_tpu.distributed.spawn import spawn

    spawn(_bert_sharding_worker, nprocs=2)


# --------------------------------------------------------------- #4 GPT PP
def _gpt_pp_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.meta_parallel import (PipelineLayer,
                                                            PipelineParallel)
    from paddle_tpu.models.gpt import GPTConfig, GPTBlock

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    pt.seed(3)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_position_embeddings=32, dropout=0.0,
                    attention_dropout=0.0)

    class EmbedIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(cfg.vocab_size, cfg.hidden_size)

        def forward(self, ids):
            return self.emb(ids)

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(cfg.hidden_size, cfg.vocab_size)

        def forward(self, h):
            return self.proj(h)

    layers = ([EmbedIn()] + [GPTBlock(cfg) for _ in range(4)] + [Head()])

    def loss_fn(logits, labels):
        return pt.nn.functional.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]),
            labels.reshape([-1])).mean()

    pipe = PipelineLayer(layers, loss_fn=loss_fn)
    model = PipelineParallel(pipe, hcg, strategy)
    opt = pt.optimizer.AdamW(learning_rate=3e-3,
                             parameters=pipe.parameters())
    rng = np.random.RandomState(0)
    ids = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16))
                       .astype(np.int32))
    labels = pt.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16))
                          .astype(np.int32))
    losses = []
    for _ in range(6):
        l = model.train_batch((ids, labels), opt)
        if l is not None:
            losses.append(float(l))
    if hcg.is_last_stage():
        assert losses[-1] < losses[0], losses


def test_baseline4_gpt_pipeline_1f1b():
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    from paddle_tpu.distributed.spawn import spawn

    spawn(_gpt_pp_worker, nprocs=2)


# --------------------------------------------------------------- #5 Llama
def test_baseline5_llama_semi_auto_recompute():
    import jax

    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from paddle_tpu.distributed import ProcessMesh

    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["dp", "sp", "mp"])
    pt.seed(9)
    cfg = llama_tiny(recompute=True)
    model = LlamaForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=3e-3,
                             parameters=model.parameters())
    step = TrainStep(model, opt, mesh=mesh, grad_clip_norm=1.0,
                     batch_specs=[("dp", "sp"), ("dp", "sp")])
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    first = float(step(ids, labels))
    for _ in range(5):
        last = float(step(ids, labels))
    assert last < first, (first, last)
