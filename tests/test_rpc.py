"""RPC over TCPStore (reference analog: test/rpc/test_rpc*.py)."""
import numpy as np


def _sq(x):
    return x * x


def _add(a, b=0):
    return a + b


def _boom():
    raise ValueError("intentional")


def _rpc_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import os

    from paddle_tpu.distributed import rpc

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    rpc.init_rpc(f"worker{rank}")
    infos = rpc.get_all_worker_infos()
    assert {w.name for w in infos} == {"worker0", "worker1"}

    peer = f"worker{1 - rank}"
    # sync call
    assert rpc.rpc_sync(peer, _sq, args=(7,)) == 49
    # async + kwargs
    fut = rpc.rpc_async(peer, _add, args=(1,), kwargs={"b": 41})
    assert fut.result(timeout=30) == 42
    # numpy payload
    arr = np.arange(6.0)
    out = rpc.rpc_sync(peer, _sq, args=(arr,))
    np.testing.assert_array_equal(out, arr * arr)
    # remote exception propagates
    try:
        rpc.rpc_sync(peer, _boom)
        raise AssertionError("expected remote error")
    except RuntimeError as e:
        assert "intentional" in str(e)
    # self-call
    assert rpc.rpc_sync(f"worker{rank}", _sq, args=(3,)) == 9
    rpc.shutdown()


def test_rpc_two_workers():
    from paddle_tpu.distributed.spawn import spawn

    spawn(_rpc_worker, nprocs=2)


def _resend_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.resilience import faults

    obs.enable()
    rpc.init_rpc("worker0")
    # ``rpc.resend`` drill: the first request post is silently lost in
    # transit; the retransmit schedule re-posts it on backoff and the
    # server dedups by call_id, so the call completes exactly once.
    faults.configure("rpc.post:drop@1")
    try:
        assert rpc.rpc_sync("worker0", _sq, args=(5,), timeout=30.0) == 25
        assert len(faults.injected()) == 1
        resends = obs.registry.counter(
            "resilience.retries", tags={"site": "rpc.resend"}).value
        assert resends >= 1
    finally:
        faults.reset()
        rpc.shutdown()


def test_rpc_resend_recovers_lost_request():
    from paddle_tpu.distributed.spawn import spawn

    spawn(_resend_worker, nprocs=1)
