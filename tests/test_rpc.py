"""RPC over TCPStore (reference analog: test/rpc/test_rpc*.py)."""
import numpy as np


def _sq(x):
    return x * x


def _add(a, b=0):
    return a + b


def _boom():
    raise ValueError("intentional")


def _rpc_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import os

    from paddle_tpu.distributed import rpc

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    rpc.init_rpc(f"worker{rank}")
    infos = rpc.get_all_worker_infos()
    assert {w.name for w in infos} == {"worker0", "worker1"}

    peer = f"worker{1 - rank}"
    # sync call
    assert rpc.rpc_sync(peer, _sq, args=(7,)) == 49
    # async + kwargs
    fut = rpc.rpc_async(peer, _add, args=(1,), kwargs={"b": 41})
    assert fut.result(timeout=30) == 42
    # numpy payload
    arr = np.arange(6.0)
    out = rpc.rpc_sync(peer, _sq, args=(arr,))
    np.testing.assert_array_equal(out, arr * arr)
    # remote exception propagates
    try:
        rpc.rpc_sync(peer, _boom)
        raise AssertionError("expected remote error")
    except RuntimeError as e:
        assert "intentional" in str(e)
    # self-call
    assert rpc.rpc_sync(f"worker{rank}", _sq, args=(3,)) == 9
    rpc.shutdown()


def test_rpc_two_workers():
    from paddle_tpu.distributed.spawn import spawn

    spawn(_rpc_worker, nprocs=2)
