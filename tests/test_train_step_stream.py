"""Real-data compiled training: run_steps_stream consumes one fresh batch
slice per scanned step with per-step LR, and ChunkPrefetcher assembles
chunks on a background thread (VERDICT r2 next #4; reference analog: the
DataLoader feeding every executor step, python/paddle/io/reader.py:262 +
fluid/framework/data_feed.cc)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu.jit import ChunkPrefetcher, TrainStep


def _mlp(seed=0):
    pt.seed(seed)
    return pt.nn.Sequential(pt.nn.Linear(6, 16), pt.nn.Tanh(),
                            pt.nn.Linear(16, 1))


def _loss_fn(model, x, y):
    return ((model(x) - y) ** 2).mean()


def _batches(k, n=8):
    rng = np.random.RandomState(42)
    return [(rng.randn(n, 6).astype(np.float32),
             rng.randn(n, 1).astype(np.float32)) for _ in range(k)]


def test_stream_matches_stepwise():
    """run_steps_stream over stacked per-step batches == the same batches
    fed one __call__ at a time (same LR, no dropout)."""
    data = _batches(6)

    m1 = _mlp()
    o1 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m1.parameters())
    s1 = TrainStep(m1, o1, loss_fn=_loss_fn)
    for x, y in data:
        last1 = s1(x, y)

    m2 = _mlp()
    o2 = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m2.parameters())
    s2 = TrainStep(m2, o2, loss_fn=_loss_fn)
    xs = np.stack([x for x, _ in data])
    ys = np.stack([y for _, y in data])
    last2 = s2.run_steps_stream(len(data), xs, ys)

    np.testing.assert_allclose(float(last1), float(last2), rtol=1e-5)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(np.asarray(p1._data),
                                   np.asarray(p2._data), rtol=1e-5,
                                   atol=1e-6)


def test_stream_per_step_lr_scheduler():
    """The chunk consumes one scheduler LR per step and advances the host
    scheduler, matching a step-by-step loop with scheduler.step()."""
    data = _batches(4)
    sched_kwargs = dict(learning_rate=0.05, step_size=2, gamma=0.1)

    m1 = _mlp(1)
    sch1 = pt.optimizer.lr.StepDecay(**sched_kwargs)
    o1 = pt.optimizer.SGD(learning_rate=sch1, parameters=m1.parameters())
    s1 = TrainStep(m1, o1, loss_fn=_loss_fn)
    for x, y in data:
        s1(x, y)
        sch1.step()

    m2 = _mlp(1)
    sch2 = pt.optimizer.lr.StepDecay(**sched_kwargs)
    o2 = pt.optimizer.SGD(learning_rate=sch2, parameters=m2.parameters())
    s2 = TrainStep(m2, o2, loss_fn=_loss_fn)
    xs = np.stack([x for x, _ in data])
    ys = np.stack([y for _, y in data])
    s2.run_steps_stream(len(data), xs, ys)

    # host scheduler advanced by the chunk length
    assert abs(float(sch2()) - float(sch1())) < 1e-12
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(np.asarray(p1._data),
                                   np.asarray(p2._data), rtol=1e-5,
                                   atol=1e-6)


def test_chunk_prefetcher_chunks_and_order():
    data = _batches(7, n=4)
    chunks = list(ChunkPrefetcher(iter(data), n=3))
    assert len(chunks) == 2  # trailing partial group dropped
    for ci, chunk in enumerate(chunks):
        xs, ys = chunk
        assert xs.shape == (3, 4, 6) and ys.shape == (3, 4, 1)
        for j in range(3):
            np.testing.assert_array_equal(xs[j], data[ci * 3 + j][0])


def test_stream_with_prefetcher_trains():
    rng = np.random.RandomState(0)
    W = rng.randn(6, 1).astype(np.float32)

    def gen():
        r = np.random.RandomState(1)
        for _ in range(12):
            x = r.randn(16, 6).astype(np.float32)
            yield x, x @ W

    m = _mlp(2)
    o = pt.optimizer.AdamW(learning_rate=1e-2, parameters=m.parameters())
    s = TrainStep(m, o, loss_fn=_loss_fn)
    losses = []
    for xs, ys in ChunkPrefetcher(gen(), n=4):
        losses.append(float(s.run_steps_stream(4, xs, ys)))
    assert len(losses) == 3
    assert losses[-1] < losses[0]


def test_stream_rejects_bad_shapes():
    import pytest

    m = _mlp(3)
    o = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    s = TrainStep(m, o, loss_fn=_loss_fn)
    xs = np.zeros((2, 4, 6), np.float32)
    ys = np.zeros((2, 4, 1), np.float32)
    with pytest.raises(ValueError):
        s.run_steps_stream(3, xs, ys)
    with pytest.raises(ValueError):
        s.run_steps_stream(2, xs, ys, lrs=np.zeros((3,), np.float32))


def test_stream_sharded_mesh():
    """run_steps_stream under a dp x mp mesh: the stacked batch keeps a
    replicated leading step axis while inner dims follow batch_specs."""
    import jax
    import pytest

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny

    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["dp", "sp", "mp"])
    pt.seed(4)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step = TrainStep(model, opt, mesh=mesh, grad_clip_norm=1.0,
                     batch_specs=[("dp", "sp"), ("dp", "sp")])
    rng = np.random.RandomState(3)
    n = 3
    ids = rng.randint(0, cfg.vocab_size, (n, 4, 16)).astype(np.int32)
    first = float(step(ids[0], ids[0]))
    loss = step.run_steps_stream(n, ids, ids)
    assert np.isfinite(float(loss))


def test_chunk_prefetcher_terminal_and_close():
    data = _batches(6, n=2)
    pf = ChunkPrefetcher(iter(data), n=3)
    assert len(list(pf)) == 2
    import pytest

    with pytest.raises(StopIteration):
        next(pf)  # sticky terminal, no deadlock

    pf2 = ChunkPrefetcher(iter(_batches(50, n=2)), n=2, depth=1)
    next(pf2)
    pf2.close()  # abandoning early releases the fill thread
    pf2._thread.join(5)
    assert not pf2._thread.is_alive()
    with pytest.raises(StopIteration):
        next(pf2)
