"""Tier-1 parity gate for the TP/DP computation–collective overlap layer
(paddle_tpu/fusion/overlap_mm.py + distributed/tp_overlap.py).

Contracts enforced here:

* decomposed == monolithic BIT-exact (loss and every grad) for both
  primitives (``all_gather_matmul``, ``matmul_reduce_scatter``) and the
  GSPMD-level ``chunked_mm`` at chunk counts {1, 2, 4};
* the 2-device shard_map ring implementations are bitwise equal to the
  serial gather-then-matmul / matmul-then-psum_scatter compositions
  (loss, dx, dw); at 4 devices the reduce-scatter sums associate in ring
  order, so those are pinned by a tight allclose (the gather side stays
  bitwise — pure data movement);
* the decomposed path traces exactly once over repeated jit steps
  (zero steady-state recompiles);
* quantized-GEMM overlap: chunked int8/fp8 == monolithic ``qmm``
  bitwise (per-token/per-channel scales are chunk-independent), and the
  overlapped quantized matmul stays within the PR-7 drift bound vs full
  precision;
* model-level overlap-on == off bitwise (GPT/Llama, incl. int8), i.e.
  ``PADDLE_TPU_TP_OVERLAP=off`` restores pre-PR numerics byte-for-byte;
* 2-process eager parity: the overlap PyLayers behind
  Column/RowParallelLinear and the sequence-parallel linears match the
  serial collectives bitwise (loss and every grad) at mp=2;
* ParallelCrossEntropy is loss_chunks-count invariant (bitwise).
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import fusion
from paddle_tpu.fusion import overlap_mm, quant


def _rand(shape, seed, scale=1.0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape) * scale, jnp.float32)


def _loss_grads(fn, *args):
    """(loss, grads) of sum(fn(*args)) — raw jax, f32."""
    val, grads = jax.value_and_grad(
        lambda *a: jnp.sum(fn(*a)), argnums=tuple(range(len(args))))(*args)
    return np.asarray(val), tuple(np.asarray(g) for g in grads)


def _assert_bitwise(ref, got, label=""):
    loss_r, grads_r = ref
    loss_g, grads_g = got
    assert np.array_equal(loss_r, loss_g), (label, loss_r, loss_g)
    for i, (a, b) in enumerate(zip(grads_r, grads_g)):
        assert np.array_equal(a, b), (label, f"grad[{i}]")


# ------------------------------------------------------------------ knob
def test_tp_overlap_env_knob(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TP_OVERLAP", "off")
    assert overlap_mm.mode() == "off" and not overlap_mm.enabled()
    monkeypatch.setenv("PADDLE_TPU_TP_OVERLAP", "auto")
    assert overlap_mm.mode() == "on"
    monkeypatch.setenv("PADDLE_TPU_TP_OVERLAP", "pallas")
    assert overlap_mm.mode() == "pallas"
    # pallas ring steps need a TPU backend; CPU falls back to ppermute
    assert overlap_mm.impl() == "ppermute"
    monkeypatch.setenv("PADDLE_TPU_TP_OVERLAP", "sideways")
    with pytest.raises(ValueError):
        overlap_mm.mode()
    # override beats the env for the scope of the context
    monkeypatch.setenv("PADDLE_TPU_TP_OVERLAP", "off")
    with overlap_mm.override(tp_overlap="on"):
        assert overlap_mm.enabled()
    assert not overlap_mm.enabled()
    monkeypatch.setenv("PADDLE_TPU_TP_OVERLAP_CHUNKS", "8")
    assert overlap_mm.default_chunks() == 8
    with overlap_mm.override(chunks=3):
        assert overlap_mm.default_chunks() == 3


# -------------------------------------- decomposed == monolithic (local)
@pytest.mark.parametrize("chunks", [1, 2, 4])
def test_local_primitives_bitwise(chunks):
    """Single-device degenerate paths of both primitives and chunked_mm
    are bitwise equal to the plain matmul — loss, dx and dw."""
    x = _rand((2, 8, 16), seed=0)
    w = _rand((16, 12), seed=1, scale=0.1)
    ref = _loss_grads(jnp.matmul, x, w)
    for name, fn in (
        ("all_gather_matmul",
         lambda a, b: overlap_mm.all_gather_matmul(a, b, chunks=chunks)),
        ("matmul_reduce_scatter",
         lambda a, b: overlap_mm.matmul_reduce_scatter(a, b,
                                                       chunks=chunks)),
        ("chunked_mm",
         lambda a, b: overlap_mm.chunked_mm(a, b, chunks=chunks)),
    ):
        _assert_bitwise(ref, _loss_grads(fn, x, w),
                        label=f"{name} chunks={chunks}")


# --------------------------------------------- shard_map ring vs serial
def _mesh(n):
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]), ("mp",))


def _serial_agmm(mesh, axis="mp"):
    from jax.sharding import PartitionSpec as P

    def body(xl, wl):
        return jnp.matmul(jax.lax.all_gather(xl, axis, tiled=True), wl)

    return overlap_mm._shard_map(
        body, mesh, (P(axis, None, None), P(None, axis)), P(None, None, axis))


def _serial_mmrs(mesh, axis="mp"):
    from jax.sharding import PartitionSpec as P

    def body(xl, wl):
        return jax.lax.psum_scatter(jnp.matmul(xl, wl), axis,
                                    scatter_dimension=0, tiled=True)

    return overlap_mm._shard_map(
        body, mesh, (P(None, None, axis), P(axis, None)), P(axis, None, None))


@pytest.mark.parametrize("chunks", [1, 2, 4])
def test_sharded_agmm_2dev_bitwise(chunks):
    """Ring all_gather_matmul == gather-then-matmul at mp=2: loss, dx and
    dw all bitwise (every partial sum has exactly two terms, and two-term
    sums commute without rounding differences in the ring order)."""
    mesh = _mesh(2)
    x = _rand((4, 6, 16), seed=2)
    w = _rand((16, 8), seed=3, scale=0.1)
    ref = _loss_grads(_serial_agmm(mesh), x, w)
    got = _loss_grads(
        lambda a, b: overlap_mm.sharded_all_gather_matmul(
            a, b, mesh=mesh, chunks=chunks), x, w)
    _assert_bitwise(ref, got, label=f"agmm mp=2 chunks={chunks}")


@pytest.mark.parametrize("chunks", [1, 2])
def test_sharded_mmrs_2dev_bitwise(chunks):
    mesh = _mesh(2)
    x = _rand((4, 6, 16), seed=4)
    w = _rand((16, 8), seed=5, scale=0.1)
    ref = _loss_grads(_serial_mmrs(mesh), x, w)
    got = _loss_grads(
        lambda a, b: overlap_mm.sharded_matmul_reduce_scatter(
            a, b, mesh=mesh, chunks=chunks), x, w)
    _assert_bitwise(ref, got, label=f"mmrs mp=2 chunks={chunks}")


def test_sharded_parity_4dev():
    """At mp=4 the ring accumulates reduce-scatter sums in shift order,
    so sums of >2 partials are allclose (float association), while the
    gather side stays bitwise — it is pure data movement."""
    mesh = _mesh(4)
    x = _rand((8, 4, 16), seed=6)
    w = _rand((16, 8), seed=7, scale=0.1)

    ref = _loss_grads(_serial_agmm(mesh), x, w)
    got = _loss_grads(
        lambda a, b: overlap_mm.sharded_all_gather_matmul(
            a, b, mesh=mesh, chunks=2), x, w)
    # forward (and hence loss) and dw involve the gathered operand only
    assert np.array_equal(ref[0], got[0])
    assert np.array_equal(ref[1][1], got[1][1])
    np.testing.assert_allclose(ref[1][0], got[1][0], rtol=1e-6, atol=1e-7)

    ref = _loss_grads(_serial_mmrs(mesh), x, w)
    got = _loss_grads(
        lambda a, b: overlap_mm.sharded_matmul_reduce_scatter(
            a, b, mesh=mesh, chunks=2), x, w)
    np.testing.assert_allclose(ref[0], got[0], rtol=1e-6)
    for g_r, g_g in zip(ref[1], got[1]):
        np.testing.assert_allclose(g_r, g_g, rtol=1e-6, atol=1e-7)


# ----------------------------------------------------- zero recompiles
def test_overlap_zero_recompile():
    """The decomposed path is shape-static: repeated jit steps reuse one
    trace (chunk loops are unrolled at trace time, no data-dependent
    control flow)."""
    mesh = _mesh(2)
    traces = []

    @jax.jit
    def step(x, w, wr):
        traces.append(0)
        h = overlap_mm.sharded_all_gather_matmul(x, w, mesh=mesh, chunks=2)
        y = overlap_mm.sharded_matmul_reduce_scatter(jnp.tanh(h), wr,
                                                     mesh=mesh, chunks=2)
        return jnp.sum(overlap_mm.chunked_mm(y, wr.T, chunks=2))

    x = _rand((4, 6, 16), seed=8)
    w = _rand((16, 8), seed=9, scale=0.1)
    wr = _rand((8, 16), seed=10, scale=0.1)
    outs = [float(step(x, w, wr)) for _ in range(3)]
    assert len(traces) == 1, "overlap path retraced in steady state"
    assert outs[0] == outs[1] == outs[2]


# -------------------------------------------------- quantized overlap
@pytest.mark.parametrize("qmode", ["int8", "fp8"])
def test_quant_overlap_bitwise_and_drift(qmode):
    """Chunked quantized GEMM == monolithic qmm bitwise at every chunk
    count (per-token activation / per-channel weight scales never cross
    a chunk boundary), and stays within the PR-7 forward drift bound of
    the full-precision matmul."""
    if qmode == "fp8" and not quant.fp8_supported():
        pytest.skip("no fp8 dtypes in this jax build")
    x = _rand((3, 8, 32), seed=11)
    w = _rand((32, 24), seed=12, scale=0.05)
    ref = _loss_grads(lambda a, b: quant.qmm(a, b, qmode), x, w)
    for chunks in (1, 2, 4):
        got = _loss_grads(
            lambda a, b: overlap_mm.chunked_mm(a, b, chunks=chunks,
                                               quant_mode=qmode), x, w)
        _assert_bitwise(ref, got, label=f"qmm {qmode} chunks={chunks}")
    full = np.asarray(jnp.matmul(x, w))
    got_fwd = np.asarray(overlap_mm.chunked_mm(x, w, chunks=4,
                                               quant_mode=qmode))
    bound = 2e-2 if qmode == "int8" else 6e-2
    assert np.linalg.norm(got_fwd - full) / np.linalg.norm(full) < bound


# ------------------------------------------- model-level on == off
# Model dims are chosen so every chunked GEMM keeps K <= 256: the host-CPU
# backend under the 8-fake-device test config reschedules the K reduction
# of very large-K GEMMs per M tile (observed at K >= 384), which makes
# M-chunking non-bitwise there — a backend thread-blocking artifact, not a
# property of the decomposition (the MXU tile path and the 2-rank ring are
# M-independent; see the sharded tests above, which are bitwise).
def _gpt_small(**kw):
    from paddle_tpu.models.gpt import GPTConfig

    return GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=256,
                     max_position_embeddings=64, dropout=0.0,
                     attention_dropout=0.0, **kw)


def _llama_small(**kw):
    from paddle_tpu.models.llama import LlamaConfig

    return LlamaConfig(vocab_size=512, hidden_size=64, num_layers=2,
                       num_heads=4, num_kv_heads=2, intermediate_size=128,
                       max_position_embeddings=64, **kw)


def _batch(vocab, b=2, s=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = pt.to_tensor(rng.integers(0, vocab, (b, s)), dtype="int64")
    labels = pt.to_tensor(rng.integers(0, vocab, (b, s)), dtype="int64")
    return ids, labels


def _model_run(make_model, tp_mode, ids, labels, chunks=None, quant="off"):
    pt.seed(0)
    m = make_model()
    with fusion.override(fusion="on", quant_mode=quant), \
            overlap_mm.override(tp_overlap=tp_mode, chunks=chunks):
        loss = m(ids, labels=labels)
        loss.backward()
    grads = {n: np.asarray(p.grad._data)
             for n, p in m.named_parameters() if p.grad is not None}
    return np.asarray(loss._data), grads


def _assert_model_bitwise(res_a, res_b):
    loss_a, grads_a = res_a
    loss_b, grads_b = res_b
    assert np.array_equal(loss_a, loss_b), (loss_a, loss_b)
    assert grads_a.keys() == grads_b.keys()
    for n in grads_a:
        assert np.array_equal(grads_a[n], grads_b[n]), n


@pytest.mark.parametrize("quant", ["off", "int8"])
def test_gpt_overlap_on_matches_off_bitwise(quant):
    """overlap engaged (forced chunks) == PADDLE_TPU_TP_OVERLAP=off on
    the same tiny GPT: loss and every grad bitwise — the off switch
    restores pre-PR numerics byte-for-byte."""
    ids, labels = _batch(512)
    mk = lambda: pt.models.GPTForCausalLM(_gpt_small())  # noqa: E731
    off = _model_run(mk, "off", ids, labels, quant=quant)
    for chunks in (2, 4):
        _assert_model_bitwise(
            _model_run(mk, "on", ids, labels, chunks=chunks, quant=quant),
            off)


def test_llama_overlap_on_matches_off_bitwise():
    ids, labels = _batch(512)
    mk = lambda: pt.models.LlamaForCausalLM(_llama_small())  # noqa: E731
    _assert_model_bitwise(
        _model_run(mk, "on", ids, labels, chunks=2),
        _model_run(mk, "off", ids, labels))


# ------------------------------------------------- 2-process eager parity
def _eager_parity_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.mp_layers import (ColumnParallelLinear,
                                                        RowParallelLinear)
    from paddle_tpu.distributed.fleet.sequence_parallel_utils import (
        ColumnSequenceParallelLinear, RowSequenceParallelLinear)
    from paddle_tpu.fusion import overlap_mm

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    mp_rank = hcg.get_model_parallel_rank()

    d, h = 8, 16
    half = h // 2
    rng = np.random.RandomState(13)
    Wc = rng.randn(d, h).astype(np.float32) * 0.3
    bc = rng.randn(h).astype(np.float32) * 0.1
    Wr = rng.randn(h, d).astype(np.float32) * 0.3
    br = rng.randn(d).astype(np.float32) * 0.1
    X = rng.randn(4, 6, d).astype(np.float32)

    # ---- tensor-parallel Column -> Row (mp_layers PyLayer path)
    def run_mp(mode):
        col = ColumnParallelLinear(d, h, has_bias=True, gather_output=False)
        row = RowParallelLinear(h, d, has_bias=False,
                                input_is_parallel=True)
        col.weight.set_value(Wc[:, mp_rank * half:(mp_rank + 1) * half])
        col.bias.set_value(bc[mp_rank * half:(mp_rank + 1) * half])
        row.weight.set_value(Wr[mp_rank * half:(mp_rank + 1) * half, :])
        with overlap_mm.override(tp_overlap=mode):
            loss = (row(col(pt.to_tensor(X)).tanh()) ** 2).mean()
            loss.backward()
        grads = [np.asarray(p.grad._data)
                 for p in list(col.parameters()) + list(row.parameters())]
        return np.asarray(loss._data), grads

    loss_on, g_on = run_mp("on")
    loss_off, g_off = run_mp("off")
    assert np.array_equal(loss_on, loss_off), (loss_on, loss_off)
    for i, (a, b) in enumerate(zip(g_on, g_off)):
        assert np.array_equal(a, b), f"mp grad[{i}]"

    # ---- sequence-parallel Column -> Row (gather/scatter on seq dim)
    s = 8
    Xsp = rng.randn(s, 2, d).astype(np.float32)
    x_local = Xsp[mp_rank * (s // 2):(mp_rank + 1) * (s // 2)]

    def run_sp(mode):
        col = ColumnSequenceParallelLinear(d, h, has_bias=True,
                                           gather_output=False)
        row = RowSequenceParallelLinear(h, d, has_bias=True,
                                        input_is_parallel=True)
        col.weight.set_value(Wc[:, mp_rank * half:(mp_rank + 1) * half])
        col.bias.set_value(bc[mp_rank * half:(mp_rank + 1) * half])
        row.weight.set_value(Wr[mp_rank * half:(mp_rank + 1) * half, :])
        row.bias.set_value(br)
        with overlap_mm.override(tp_overlap=mode):
            loss = (row(col(pt.to_tensor(x_local)).tanh()) ** 2).mean()
            loss.backward()
        grads = [np.asarray(p.grad._data)
                 for p in list(col.parameters()) + list(row.parameters())]
        return np.asarray(loss._data), grads

    loss_on, g_on = run_sp("on")
    loss_off, g_off = run_sp("off")
    assert np.array_equal(loss_on, loss_off), (loss_on, loss_off)
    for i, (a, b) in enumerate(zip(g_on, g_off)):
        assert np.array_equal(a, b), f"sp grad[{i}]"

    if hcg.get_model_parallel_rank() == 0:
        print("TP OVERLAP EAGER PARITY OK", flush=True)


def test_eager_overlap_matches_serial_2proc():
    """mp=2 over 2 processes: the decomposed PyLayers behind the fleet
    Column/Row linears and the sequence-parallel linears are bitwise
    equal to the serial collective compositions (loss and every grad)."""
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    from paddle_tpu.distributed.spawn import spawn

    spawn(_eager_parity_worker, nprocs=2)


# ------------------------------------- ParallelCrossEntropy chunking
def _pce_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.mp_layers import ParallelCrossEntropy

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    mp_rank = hcg.get_model_parallel_rank()

    vocab, per = 16, 8
    rng = np.random.RandomState(17)
    logits = rng.randn(4, 6, vocab).astype(np.float32)
    labels = rng.randint(0, vocab, (4, 6)).astype(np.int64)
    labels[0, 0] = -100  # exercise ignore_index through the chunked pick
    local = logits[..., mp_rank * per:(mp_rank + 1) * per]

    losses = {}
    for chunks in (1, 2, 4):
        ce = ParallelCrossEntropy(loss_chunks=chunks)
        loss = ce(pt.to_tensor(local), pt.to_tensor(labels))
        losses[chunks] = np.asarray(loss._data)
    for chunks in (2, 4):
        assert np.array_equal(losses[1], losses[chunks]), chunks
    if mp_rank == 0:
        print("PCE CHUNK INVARIANCE OK", flush=True)


def test_parallel_cross_entropy_chunk_invariance_2proc():
    """Vocab-sharded CE through fusion/chunked.py: the loss is bitwise
    identical across loss_chunks counts (per-token math never crosses a
    chunk boundary)."""
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    from paddle_tpu.distributed.spawn import spawn

    spawn(_pce_worker, nprocs=2)
