"""Test config: force CPU backend with 8 virtual devices so sharding /
multi-chip tests run hermetically (SURVEY §4: the fake-device strategy —
reference analog test/custom_runtime/test_custom_cpu_plugin.py:23)."""
import os

# the axon TPU plugin overrides JAX_PLATFORMS; jax_platforms config wins
os.environ["JAX_PLATFORM_NAME"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow')")


@pytest.hookimpl(trylast=True)
def pytest_runtest_logreport(report):
    # CI wraps the suite in a hard timeout; with stdout block-buffered
    # (pipe/file), a killed run silently drops up to 8 KB of progress
    # output. Flush after every test so the log reflects actual progress.
    import sys

    sys.stdout.flush()
    sys.stderr.flush()


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu

    paddle_tpu.seed(1234)
    np.random.seed(1234)
    yield
