"""sparse.nn layers vs dense references (VERDICT r3 missing #6;
reference: python/paddle/sparse/nn/layer/{conv,pooling,norm,activation}.py
over phi/kernels/sparse/ rulebook conv)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import sparse


def _random_coo_ndhwc(rng, shape, density=0.2):
    """Random sparse NDHWC tensor; returns (SparseCooTensor, dense np)."""
    dense = np.zeros(shape, np.float32)
    mask = rng.rand(*shape[:-1]) < density
    vals = rng.randn(mask.sum(), shape[-1]).astype(np.float32)
    dense[mask] = vals
    idx = np.stack(np.nonzero(mask))
    coo = sparse.sparse_coo_tensor(idx, vals, shape)
    return coo, dense


def _dense_conv(dense, w, stride, padding, ndim):
    """lax reference conv on NDHWC/NHWC layouts."""
    dn = jax.lax.conv_dimension_numbers(
        dense.shape, w.shape,
        ("NDHWC", "DHWIO", "NDHWC") if ndim == 3
        else ("NHWC", "HWIO", "NHWC"))
    return np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(dense), jnp.asarray(w),
        window_strides=(stride,) * ndim,
        padding=[(padding, padding)] * ndim, dimension_numbers=dn))


class TestSparseConv:
    @pytest.mark.parametrize("stride,padding", [(1, 1), (2, 1), (1, 0)])
    def test_conv3d_matches_dense(self, stride, padding):
        rng = np.random.RandomState(0)
        shape = (1, 5, 6, 7, 3)
        coo, dense = _random_coo_ndhwc(rng, shape)
        conv = sparse.nn.Conv3D(3, 4, kernel_size=3, stride=stride,
                                padding=padding, bias_attr=False)
        out = conv(coo)
        w = np.asarray(conv.weight._data)  # [kd,kh,kw,cin,cout]
        ref = _dense_conv(dense, w, stride, padding, 3)
        np.testing.assert_allclose(out.to_dense().numpy(), ref,
                                   rtol=1e-4, atol=1e-4)

    def test_conv2d_matches_dense(self):
        rng = np.random.RandomState(1)
        shape = (2, 8, 8, 2)
        coo, dense = _random_coo_ndhwc(rng, shape, density=0.3)
        conv = sparse.nn.Conv2D(2, 5, kernel_size=3, stride=1, padding=1,
                                bias_attr=False)
        out = conv(coo)
        w = np.asarray(conv.weight._data)
        ref = _dense_conv(dense, w, 1, 1, 2)
        np.testing.assert_allclose(out.to_dense().numpy(), ref,
                                   rtol=1e-4, atol=1e-4)

    def test_subm_conv3d_preserves_sites_and_values(self):
        """Submanifold conv: output sites == input sites; at each site the
        value equals the dense conv restricted to that site."""
        rng = np.random.RandomState(2)
        shape = (1, 5, 5, 5, 2)
        coo, dense = _random_coo_ndhwc(rng, shape, density=0.15)
        conv = sparse.nn.SubmConv3D(2, 3, kernel_size=3, padding=1,
                                    bias_attr=False)
        out = conv(coo)
        assert out.indices().numpy().shape == coo.indices().numpy().shape
        w = np.asarray(conv.weight._data)
        ref = _dense_conv(dense, w, 1, 1, 3)
        out_d = out.to_dense().numpy()
        in_mask = np.abs(dense).sum(-1) > 0
        np.testing.assert_allclose(out_d[in_mask], ref[in_mask],
                                   rtol=1e-4, atol=1e-4)
        # off-site outputs are zero (submanifold property)
        assert np.abs(out_d[~in_mask]).max() == 0.0

    def test_bias_and_batch(self):
        rng = np.random.RandomState(3)
        coo, dense = _random_coo_ndhwc(rng, (2, 4, 4, 4, 2))
        conv = sparse.nn.Conv3D(2, 3, kernel_size=2)
        out = conv(coo)
        w = np.asarray(conv.weight._data)
        b = np.asarray(conv.bias._data)
        ref = _dense_conv(dense, w, 1, 0, 3) + b
        out_d = out.to_dense().numpy()
        # sparse conv leaves un-activated sites at zero (no bias spray);
        # compare on active output sites only
        active = np.abs(out_d).sum(-1) > 0
        np.testing.assert_allclose(out_d[active], ref[active], rtol=1e-4,
                                   atol=1e-4)


class TestSparsePoolNorm:
    def test_maxpool3d_matches_dense(self):
        rng = np.random.RandomState(4)
        coo, dense = _random_coo_ndhwc(rng, (1, 4, 4, 4, 3), density=0.5)
        pool = sparse.nn.MaxPool3D(kernel_size=2, stride=2)
        out = pool(coo).to_dense().numpy()
        ref = np.asarray(jax.lax.reduce_window(
            jnp.asarray(dense), -jnp.inf, jax.lax.max,
            (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID"))
        ref = np.where(np.isfinite(ref), np.maximum(ref, 0.0)
                       if False else ref, 0.0)
        # empty windows: sparse yields 0; dense yields max of zeros = 0
        ref = np.maximum(ref, 0.0) * (ref > 0) + np.minimum(ref, 0.0) * (
            ref < 0)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_batchnorm_values(self):
        rng = np.random.RandomState(5)
        coo, dense = _random_coo_ndhwc(rng, (1, 4, 4, 4, 6))
        bn = sparse.nn.BatchNorm(6)
        out = bn(coo)
        vals = coo.values().numpy()
        mu, var = vals.mean(0), vals.var(0)
        expect = (vals - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(out.values().numpy(), expect,
                                   rtol=1e-4, atol=1e-4)

    def test_sync_batchnorm_single_device_equals_batchnorm(self):
        rng = np.random.RandomState(6)
        coo, _ = _random_coo_ndhwc(rng, (1, 3, 3, 3, 4))
        a = sparse.nn.BatchNorm(4)(coo).values().numpy()
        b = sparse.nn.SyncBatchNorm(4)(coo).values().numpy()
        np.testing.assert_allclose(a, b, rtol=1e-5)


def test_sparse_ops_under_jit():
    """VERDICT asks the sparse surface be exercised under jit: run a
    values-space pipeline inside jax.jit via BCOO."""
    from jax.experimental import sparse as jsparse

    rng = np.random.RandomState(7)
    dense = np.zeros((6, 8), np.float32)
    dense[rng.rand(6, 8) < 0.4] = 1.5

    @jax.jit
    def pipeline(m):
        bc = jsparse.BCOO.fromdense(m, nse=32)
        y = jsparse.BCOO((jnp.maximum(bc.data, 0.0) * 2.0, bc.indices),
                         shape=bc.shape)
        return (y @ jnp.ones((m.shape[1], 4))), y.todense()

    mv, d2 = pipeline(jnp.asarray(dense))
    np.testing.assert_allclose(np.asarray(d2), np.maximum(dense, 0) * 2,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(mv),
                               (np.maximum(dense, 0) * 2) @ np.ones((8, 4)),
                               rtol=1e-5)
