"""hapi Model.fit/evaluate/predict + callbacks (reference analog:
test/legacy_test/test_model.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy


class _SynthDataset(Dataset):
    """Linearly separable 2-class problem."""

    def __init__(self, n=128):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8)
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], np.array([self.y[i]])

    def __len__(self):
        return len(self.x)


def _model():
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = Model(net)
    m.prepare(
        optimizer=pt.optimizer.Adam(parameters=net.parameters(),
                                    learning_rate=1e-2),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    return m


class TestModelFit:
    def test_fit_improves_accuracy(self, capsys):
        m = _model()
        ds = _SynthDataset()
        m.fit(ds, epochs=10, batch_size=32, verbose=0)
        logs = m.evaluate(ds, batch_size=32, verbose=0)
        acc = logs["acc"]
        assert acc > 0.9, f"accuracy after fit: {acc}"

    def test_train_eval_batch(self):
        m = _model()
        x = np.random.randn(16, 8).astype(np.float32)
        y = np.random.randint(0, 2, (16, 1))
        loss1, _ = m.train_batch([x], [y])
        assert isinstance(loss1[0], float)
        lossE, accE = m.eval_batch([x], [y])
        assert 0.0 <= accE[0] <= 1.0

    def test_predict(self):
        m = _model()
        ds = _SynthDataset(32)
        out = m.predict(ds, batch_size=8, stack_outputs=True, verbose=0)
        assert out[0].shape == (32, 2)

    def test_save_load_roundtrip(self, tmp_path):
        m = _model()
        ds = _SynthDataset(32)
        m.fit(ds, epochs=1, batch_size=16, verbose=0)
        path = str(tmp_path / "ckpt" / "model")
        m.save(path)
        m2 = _model()
        m2.load(path)
        x = np.random.randn(4, 8).astype(np.float32)
        np.testing.assert_allclose(m.predict_batch([x])[0],
                                   m2.predict_batch([x])[0], rtol=1e-6)

    def test_early_stopping(self):
        # lr=0 -> loss never improves, so patience=0 stops at the 2nd eval
        net = nn.Linear(8, 2)
        m = Model(net)
        m.prepare(optimizer=pt.optimizer.SGD(parameters=net.parameters(),
                                             learning_rate=0.0),
                  loss=nn.CrossEntropyLoss())
        ds = _SynthDataset(64)
        es = EarlyStopping(monitor="loss", mode="min", patience=0,
                           verbose=0, save_best_model=False)
        m.fit(ds, eval_data=ds, epochs=10, batch_size=32, verbose=0,
              callbacks=[es], eval_freq=1)
        assert m.stop_training

    def test_num_iters_cap(self):
        m = _model()
        seen = []

        class Counter(pt.hapi.Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.append(step)

        m.fit(_SynthDataset(128), epochs=10, batch_size=16, verbose=0,
              num_iters=3, callbacks=[Counter()])
        assert len(seen) == 3

    def test_summary(self, capsys):
        m = _model()
        info = m.summary()
        assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2
        assert "Total params" in capsys.readouterr().out
