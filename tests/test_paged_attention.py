"""Paged attention decode kernel vs numpy reference (reference analog:
test/legacy_test/test_block_multihead_attention.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.incubate.nn.pallas.paged_attention import (
    _xla_paged_attention, paged_attention, paged_kv_write)


def _np_reference(q, k_pages, v_pages, block_tables, context_lens, scale):
    bsz, n_heads, d = q.shape
    n_kv, _, page, _ = k_pages.shape
    group = n_heads // n_kv
    out = np.zeros_like(q, dtype=np.float32)
    for b in range(bsz):
        L = int(context_lens[b])
        n_pages_used = (L + page - 1) // page
        for h in range(n_heads):
            kv_h = h // group
            ks, vs = [], []
            for pi in range(n_pages_used):
                pid = int(block_tables[b, pi])
                ks.append(k_pages[kv_h, pid])
                vs.append(v_pages[kv_h, pid])
            K = np.concatenate(ks, axis=0)[:L]
            V = np.concatenate(vs, axis=0)[:L]
            s = (q[b, h].astype(np.float32) @ K.T.astype(np.float32)) * scale
            w = np.exp(s - s.max())
            w = w / w.sum()
            out[b, h] = w @ V.astype(np.float32)
    return out


def _setup(bsz=2, n_heads=4, n_kv=2, d=64, page=128, pages_per_seq=3,
           seed=0):
    rng = np.random.RandomState(seed)
    total_pages = bsz * pages_per_seq + 1
    q = rng.randn(bsz, n_heads, d).astype(np.float32)
    k_pages = rng.randn(n_kv, total_pages, page, d).astype(np.float32)
    v_pages = rng.randn(n_kv, total_pages, page, d).astype(np.float32)
    # distinct pages per sequence (page 0 left unused)
    bt = (1 + np.arange(bsz * pages_per_seq)
          .reshape(bsz, pages_per_seq)).astype(np.int32)
    lens = np.array([page * pages_per_seq - 7, page + 3][:bsz],
                    dtype=np.int32)
    return q, k_pages, v_pages, bt, lens


class TestPagedAttention:
    def test_kernel_matches_numpy(self):
        q, kp, vp, bt, lens = _setup()
        scale = q.shape[-1] ** -0.5
        out = paged_attention(jnp.asarray(q), jnp.asarray(kp),
                              jnp.asarray(vp), jnp.asarray(bt),
                              jnp.asarray(lens), interpret=True,
                              use_kernel=True)
        ref = _np_reference(q, kp, vp, bt, lens, scale)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_xla_path_matches_numpy(self):
        q, kp, vp, bt, lens = _setup(n_heads=8, n_kv=8, d=32, page=16,
                                     pages_per_seq=2, seed=3)
        scale = q.shape[-1] ** -0.5
        out = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kp),
                                   jnp.asarray(vp), jnp.asarray(bt),
                                   jnp.asarray(lens), scale)
        ref = _np_reference(q, kp, vp, bt, lens, scale)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_gqa_grouping(self):
        # group=4: kernel and XLA paths agree
        q, kp, vp, bt, lens = _setup(n_heads=8, n_kv=2, seed=5)
        out_k = paged_attention(jnp.asarray(q), jnp.asarray(kp),
                                jnp.asarray(vp), jnp.asarray(bt),
                                jnp.asarray(lens), interpret=True,
                                use_kernel=True)
        out_x = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kp),
                                     jnp.asarray(vp), jnp.asarray(bt),
                                     jnp.asarray(lens),
                                     q.shape[-1] ** -0.5)
        np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_x),
                                   rtol=2e-4, atol=2e-4)

    def test_short_context_masks_tail(self):
        # context shorter than one page: tail tokens must not contribute
        q, kp, vp, bt, lens = _setup(bsz=1, pages_per_seq=2)
        lens = np.array([5], dtype=np.int32)
        out = paged_attention(jnp.asarray(q), jnp.asarray(kp),
                              jnp.asarray(vp), jnp.asarray(bt),
                              jnp.asarray(lens), interpret=True,
                              use_kernel=True)
        ref = _np_reference(q, kp, vp, bt, lens, q.shape[-1] ** -0.5)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)


class TestPagedKVWrite:
    def test_append_roundtrip(self):
        q, kp, vp, bt, lens = _setup(bsz=2, n_kv=2, d=64, page=128,
                                     pages_per_seq=3)
        rng = np.random.RandomState(9)
        k_new = rng.randn(2, 2, 64).astype(np.float32)
        v_new = rng.randn(2, 2, 64).astype(np.float32)
        kp2, vp2 = paged_kv_write(jnp.asarray(kp), jnp.asarray(vp),
                                  jnp.asarray(k_new), jnp.asarray(v_new),
                                  jnp.asarray(bt), jnp.asarray(lens))
        kp2, vp2 = np.asarray(kp2), np.asarray(vp2)
        for b in range(2):
            pos = int(lens[b])
            pid = int(bt[b, pos // 128])
            slot = pos % 128
            np.testing.assert_array_equal(kp2[:, pid, slot, :], k_new[b])
            np.testing.assert_array_equal(vp2[:, pid, slot, :], v_new[b])
        # attention over the extended context sees the new token
        lens2 = lens + 1
        out = paged_attention(jnp.asarray(q), jnp.asarray(kp2),
                              jnp.asarray(vp2), jnp.asarray(bt),
                              jnp.asarray(lens2), interpret=True,
                              use_kernel=True)
        ref = _np_reference(q, kp2, vp2, bt, lens2, 64 ** -0.5)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)
