"""Paged attention decode kernel vs numpy reference (reference analog:
test/legacy_test/test_block_multihead_attention.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.incubate.nn.pallas.paged_attention import (
    _dequant, _xla_paged_attention, paged_attention, paged_kv_write,
    quantize_kv_pages, ragged_paged_attention)


def _np_reference(q, k_pages, v_pages, block_tables, context_lens, scale):
    bsz, n_heads, d = q.shape
    n_kv, _, page, _ = k_pages.shape
    group = n_heads // n_kv
    out = np.zeros_like(q, dtype=np.float32)
    for b in range(bsz):
        L = int(context_lens[b])
        n_pages_used = (L + page - 1) // page
        for h in range(n_heads):
            kv_h = h // group
            ks, vs = [], []
            for pi in range(n_pages_used):
                pid = int(block_tables[b, pi])
                ks.append(k_pages[kv_h, pid])
                vs.append(v_pages[kv_h, pid])
            K = np.concatenate(ks, axis=0)[:L]
            V = np.concatenate(vs, axis=0)[:L]
            s = (q[b, h].astype(np.float32) @ K.T.astype(np.float32)) * scale
            w = np.exp(s - s.max())
            w = w / w.sum()
            out[b, h] = w @ V.astype(np.float32)
    return out


def _setup(bsz=2, n_heads=4, n_kv=2, d=64, page=128, pages_per_seq=3,
           seed=0):
    rng = np.random.RandomState(seed)
    total_pages = bsz * pages_per_seq + 1
    q = rng.randn(bsz, n_heads, d).astype(np.float32)
    k_pages = rng.randn(n_kv, total_pages, page, d).astype(np.float32)
    v_pages = rng.randn(n_kv, total_pages, page, d).astype(np.float32)
    # distinct pages per sequence (page 0 left unused)
    bt = (1 + np.arange(bsz * pages_per_seq)
          .reshape(bsz, pages_per_seq)).astype(np.int32)
    lens = np.array([page * pages_per_seq - 7, page + 3][:bsz],
                    dtype=np.int32)
    return q, k_pages, v_pages, bt, lens


class TestPagedAttention:
    def test_kernel_matches_numpy(self):
        q, kp, vp, bt, lens = _setup()
        scale = q.shape[-1] ** -0.5
        out = paged_attention(jnp.asarray(q), jnp.asarray(kp),
                              jnp.asarray(vp), jnp.asarray(bt),
                              jnp.asarray(lens), interpret=True,
                              use_kernel=True)
        ref = _np_reference(q, kp, vp, bt, lens, scale)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_xla_path_matches_numpy(self):
        q, kp, vp, bt, lens = _setup(n_heads=8, n_kv=8, d=32, page=16,
                                     pages_per_seq=2, seed=3)
        scale = q.shape[-1] ** -0.5
        out = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kp),
                                   jnp.asarray(vp), jnp.asarray(bt),
                                   jnp.asarray(lens), scale)
        ref = _np_reference(q, kp, vp, bt, lens, scale)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)

    def test_gqa_grouping(self):
        # group=4: kernel and XLA paths agree
        q, kp, vp, bt, lens = _setup(n_heads=8, n_kv=2, seed=5)
        out_k = paged_attention(jnp.asarray(q), jnp.asarray(kp),
                                jnp.asarray(vp), jnp.asarray(bt),
                                jnp.asarray(lens), interpret=True,
                                use_kernel=True)
        out_x = _xla_paged_attention(jnp.asarray(q), jnp.asarray(kp),
                                     jnp.asarray(vp), jnp.asarray(bt),
                                     jnp.asarray(lens),
                                     q.shape[-1] ** -0.5)
        np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_x),
                                   rtol=2e-4, atol=2e-4)

    def test_short_context_masks_tail(self):
        # context shorter than one page: tail tokens must not contribute
        q, kp, vp, bt, lens = _setup(bsz=1, pages_per_seq=2)
        lens = np.array([5], dtype=np.int32)
        out = paged_attention(jnp.asarray(q), jnp.asarray(kp),
                              jnp.asarray(vp), jnp.asarray(bt),
                              jnp.asarray(lens), interpret=True,
                              use_kernel=True)
        ref = _np_reference(q, kp, vp, bt, lens, q.shape[-1] ** -0.5)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)


class TestPagedKVWrite:
    def test_append_roundtrip(self):
        q, kp, vp, bt, lens = _setup(bsz=2, n_kv=2, d=64, page=128,
                                     pages_per_seq=3)
        rng = np.random.RandomState(9)
        k_new = rng.randn(2, 2, 64).astype(np.float32)
        v_new = rng.randn(2, 2, 64).astype(np.float32)
        kp2, vp2 = paged_kv_write(jnp.asarray(kp), jnp.asarray(vp),
                                  jnp.asarray(k_new), jnp.asarray(v_new),
                                  jnp.asarray(bt), jnp.asarray(lens))
        kp2, vp2 = np.asarray(kp2), np.asarray(vp2)
        for b in range(2):
            pos = int(lens[b])
            pid = int(bt[b, pos // 128])
            slot = pos % 128
            np.testing.assert_array_equal(kp2[:, pid, slot, :], k_new[b])
            np.testing.assert_array_equal(vp2[:, pid, slot, :], v_new[b])
        # attention over the extended context sees the new token
        lens2 = lens + 1
        out = paged_attention(jnp.asarray(q), jnp.asarray(kp2),
                              jnp.asarray(vp2), jnp.asarray(bt),
                              jnp.asarray(lens2), interpret=True,
                              use_kernel=True)
        ref = _np_reference(q, kp2, vp2, bt, lens2, 64 ** -0.5)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-4)


class TestEmptySlots:
    """Regression: a slot with ``context_lens == 0`` (inactive or
    freshly-joined in the serving engine) must return exact zeros, not
    whatever the uninitialized pages its stale block table points at
    contain — and never NaN (the all-masked softmax)."""

    def _empty_setup(self):
        q, kp, vp, bt, lens = _setup(bsz=2, n_heads=4, n_kv=2, d=32,
                                     page=16, pages_per_seq=2, seed=7)
        # slot 1 is empty but its block table is garbage, including ids
        # beyond the pool (the engine never sanitizes dead rows)
        bt = bt.copy()
        bt[1] = [9999, -3]
        lens = np.array([19, 0], dtype=np.int32)
        # poison the pool so any leak through the mask is visible
        kp = kp + 100.0
        vp = vp + 100.0
        return q, kp, vp, bt, lens

    def test_kernel_empty_slot_zeros(self):
        q, kp, vp, bt, lens = self._empty_setup()
        out = np.asarray(paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens), interpret=True,
            use_kernel=True))
        assert np.isfinite(out).all()
        np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))
        # the live row is still computed correctly next to the dead one
        ref = _np_reference(q[:1], kp, vp, bt[:1], lens[:1],
                            q.shape[-1] ** -0.5)
        np.testing.assert_allclose(out[0], ref[0], rtol=2e-4, atol=2e-4)

    def test_xla_empty_slot_zeros(self):
        q, kp, vp, bt, lens = self._empty_setup()
        out = np.asarray(paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens), use_kernel=False))
        assert np.isfinite(out).all()
        np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))

    def test_all_slots_empty(self):
        q, kp, vp, bt, lens = self._empty_setup()
        lens = np.zeros(2, dtype=np.int32)
        for kern in (True, False):
            out = np.asarray(paged_attention(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(lens), interpret=True,
                use_kernel=kern))
            np.testing.assert_array_equal(out, np.zeros_like(out))


class TestPagedKVWriteChunk:
    def test_chunk_write_matches_scalar_writes(self):
        from paddle_tpu.incubate.nn.pallas.paged_attention import \
            paged_kv_write_chunk
        rng = np.random.RandomState(4)
        n_kv, pages, page, d = 2, 6, 8, 16
        kp = np.zeros((n_kv, pages, page, d), np.float32)
        vp = np.zeros((n_kv, pages, page, d), np.float32)
        k_new = rng.randn(1, 5, n_kv, d).astype(np.float32)
        v_new = rng.randn(1, 5, n_kv, d).astype(np.float32)
        bt = np.array([[2, 4, 0]], np.int32)
        pos = np.array([[6, 7, 8, 9, 10]], np.int32)  # spans 2 pages
        kp2, vp2 = paged_kv_write_chunk(
            jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(k_new),
            jnp.asarray(v_new), jnp.asarray(bt), jnp.asarray(pos))
        kp2, vp2 = np.asarray(kp2), np.asarray(vp2)
        for g in range(5):
            p = int(pos[0, g])
            pid = int(bt[0, p // page])
            np.testing.assert_array_equal(kp2[:, pid, p % page],
                                          k_new[0, g])
            np.testing.assert_array_equal(vp2[:, pid, p % page],
                                          v_new[0, g])
        # untouched slots stay zero
        assert np.abs(kp2).sum() == pytest.approx(
            np.abs(k_new).sum(), rel=1e-6)

    def test_negative_positions_are_dropped(self):
        from paddle_tpu.incubate.nn.pallas.paged_attention import \
            paged_kv_write_chunk
        rng = np.random.RandomState(5)
        n_kv, pages, page, d = 1, 3, 4, 8
        kp = np.zeros((n_kv, pages, page, d), np.float32)
        vp = np.zeros((n_kv, pages, page, d), np.float32)
        k_new = rng.randn(2, 1, n_kv, d).astype(np.float32)
        v_new = rng.randn(2, 1, n_kv, d).astype(np.float32)
        bt = np.array([[1, 2], [2, 0]], np.int32)
        pos = np.array([[-1], [3]], np.int32)     # row 0 inactive
        kp2, vp2 = paged_kv_write_chunk(
            jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(k_new),
            jnp.asarray(v_new), jnp.asarray(bt), jnp.asarray(pos))
        kp2 = np.asarray(kp2)
        np.testing.assert_array_equal(kp2[:, 1], 0.0)  # dropped write
        np.testing.assert_array_equal(kp2[0, 2, 3], k_new[1, 0, 0])


class TestInt8Pages:
    def test_quantized_pool_attention_close(self):
        q, kp, vp, bt, lens = _setup(n_heads=4, n_kv=2, d=32, page=16,
                                     pages_per_seq=2, seed=11)
        qkp = quantize_kv_pages(jnp.asarray(kp))
        qvp = quantize_kv_pages(jnp.asarray(vp))
        out = np.asarray(paged_attention(
            jnp.asarray(q), qkp, qvp, jnp.asarray(bt),
            jnp.asarray(lens)))
        ref = _np_reference(q, kp, vp, bt, lens, q.shape[-1] ** -0.5)
        np.testing.assert_allclose(out, ref, rtol=0.15, atol=0.15)

    def test_quantized_empty_slot_zeros(self):
        q, kp, vp, bt, lens = _setup(bsz=2, n_kv=2, d=32, page=16,
                                     pages_per_seq=2, seed=12)
        lens = np.array([10, 0], dtype=np.int32)
        out = np.asarray(paged_attention(
            jnp.asarray(q), quantize_kv_pages(jnp.asarray(kp)),
            quantize_kv_pages(jnp.asarray(vp)), jnp.asarray(bt),
            jnp.asarray(lens)))
        assert np.isfinite(out).all()
        np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))


class TestQuantizeRoundTrip:
    """Direct bound on the int8 page codec: symmetric per-(row, head)
    absmax quantization reconstructs every element within half a
    quantization step (s = absmax / 127)."""

    def test_round_trip_error_bound(self):
        rng = np.random.RandomState(21)
        pages = (rng.randn(2, 5, 8, 16) * 3.0).astype(np.float32)
        qp = quantize_kv_pages(jnp.asarray(pages))
        deq = np.asarray(_dequant(qp["q8"], qp["s"]))
        s_row = np.abs(pages).max(axis=-1) / 127.0
        bound = 0.5 * s_row[..., None] + 1e-6
        assert (np.abs(deq - pages) <= bound).all()
        # scales are the advertised absmax/127 (clamped away from 0)
        np.testing.assert_allclose(np.asarray(qp["s"]),
                                   np.maximum(s_row, 1e-8), rtol=1e-6)

    def test_round_trip_tiny_rows(self):
        # all-zero rows must survive (scale clamp, not 0/0)
        pages = np.zeros((1, 2, 4, 8), np.float32)
        qp = quantize_kv_pages(jnp.asarray(pages))
        deq = np.asarray(_dequant(qp["q8"], qp["s"]))
        np.testing.assert_array_equal(deq, 0.0)


def _np_ragged_reference(q, k_pages, v_pages, block_tables, context_lens,
                         query_lens, scale):
    """Loop-based reference: token j of row r attends causally to KV
    positions < context_lens[r] - query_lens[r] + j + 1. Padding tokens
    (beyond the packed rows) are zeros."""
    n_tokens, n_heads, d = q.shape
    n_kv, _, page, _ = k_pages.shape
    group = n_heads // n_kv
    out = np.zeros_like(q, dtype=np.float32)
    starts = np.concatenate([[0], np.cumsum(query_lens)[:-1]])
    for r in range(len(query_lens)):
        for j in range(int(query_lens[r])):
            t = int(starts[r]) + j
            L = int(context_lens[r]) - int(query_lens[r]) + j + 1
            if L <= 0:
                continue
            n_pages_used = (L + page - 1) // page
            for h in range(n_heads):
                kv_h = h // group
                rows = [k_pages[kv_h, int(block_tables[r, pi])]
                        for pi in range(n_pages_used)]
                K = np.concatenate(rows, axis=0)[:L]
                rows = [v_pages[kv_h, int(block_tables[r, pi])]
                        for pi in range(n_pages_used)]
                V = np.concatenate(rows, axis=0)[:L]
                s = (q[t, h].astype(np.float32)
                     @ K.T.astype(np.float32)) * scale
                w = np.exp(s - s.max())
                w = w / w.sum()
                out[t, h] = w @ V.astype(np.float32)
    return out


def _ragged_setup(query_lens, context_lens, n_heads=4, n_kv=2, d=32,
                  page=16, pages_per_seq=4, n_pad=0, seed=0):
    rng = np.random.RandomState(seed)
    n_rows = len(query_lens)
    total_pages = n_rows * pages_per_seq + 1
    n_tokens = int(np.sum(query_lens)) + n_pad
    q = rng.randn(n_tokens, n_heads, d).astype(np.float32)
    kp = rng.randn(n_kv, total_pages, page, d).astype(np.float32)
    vp = rng.randn(n_kv, total_pages, page, d).astype(np.float32)
    bt = (1 + np.arange(n_rows * pages_per_seq)
          .reshape(n_rows, pages_per_seq)).astype(np.int32)
    ql = np.asarray(query_lens, np.int32)
    cl = np.asarray(context_lens, np.int32)
    return q, kp, vp, bt, cl, ql


class TestRaggedPagedAttention:
    """Tentpole kernel: mixed prefill+decode rows in one launch, across
    the query_lens mixes the serving engine produces (all-decode,
    all-prefill, mixed, empty rows with context_lens == 0)."""

    MIXES = {
        "all_decode": ([1, 1, 1], [9, 33, 17]),
        "all_prefill": ([7, 20, 5], [7, 20, 5]),
        "mixed": ([1, 12, 1, 6], [25, 12, 40, 30]),
        "empty_rows": ([1, 0, 8, 0], [14, 0, 8, 0]),
    }

    def _run(self, name, **kw):
        ql, cl = self.MIXES[name]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, seed=13)
        out = np.asarray(ragged_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(cl), jnp.asarray(ql), **kw))
        ref = _np_ragged_reference(q, kp, vp, bt, cl, ql,
                                   q.shape[-1] ** -0.5)
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("mix", sorted(MIXES))
    def test_xla_matches_numpy(self, mix):
        self._run(mix, use_kernel=False)

    @pytest.mark.parametrize("mix", sorted(MIXES))
    def test_kernel_matches_numpy(self, mix):
        self._run(mix, interpret=True, use_kernel=True)

    def test_padding_tokens_are_zero(self):
        ql, cl = self.MIXES["mixed"]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, n_pad=5, seed=14)
        for kw in ({"use_kernel": False},
                   {"interpret": True, "use_kernel": True}):
            out = np.asarray(ragged_paged_attention(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(cl), jnp.asarray(ql), **kw))
            ref = _np_ragged_reference(q, kp, vp, bt, cl, ql,
                                       q.shape[-1] ** -0.5)
            np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
            np.testing.assert_array_equal(out[int(np.sum(ql)):], 0.0)

    def test_all_decode_matches_decode_kernel(self):
        # a ragged batch of pure decode rows is exactly the existing
        # decode attention (row r == batch b, lens == context_lens)
        ql, cl = self.MIXES["all_decode"]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, seed=15)
        out_r = np.asarray(ragged_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(cl), jnp.asarray(ql),
            interpret=True, use_kernel=True))
        out_d = np.asarray(paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(cl), interpret=True,
            use_kernel=True))
        np.testing.assert_allclose(out_r, out_d, rtol=2e-4, atol=2e-4)

    def test_explicit_row_of_matches_derived(self):
        ql, cl = self.MIXES["mixed"]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, n_pad=3, seed=16)
        starts = np.concatenate([[0], np.cumsum(ql)[:-1]]).astype(np.int32)
        row_of = np.full(q.shape[0], -1, np.int32)
        for r in range(len(ql)):
            row_of[starts[r]:starts[r] + ql[r]] = r
        out_a = np.asarray(ragged_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(cl), jnp.asarray(ql),
            use_kernel=False))
        out_b = np.asarray(ragged_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(cl), jnp.asarray(ql),
            q_starts=jnp.asarray(starts), row_of=jnp.asarray(row_of),
            use_kernel=False))
        np.testing.assert_array_equal(out_a, out_b)

    def test_gqa_grouping(self):
        ql = [1, 9, 1]
        cl = [22, 9, 31]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, n_heads=8, n_kv=2,
                                              seed=17)
        out = np.asarray(ragged_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(cl), jnp.asarray(ql),
            interpret=True, use_kernel=True))
        ref = _np_ragged_reference(q, kp, vp, bt, cl, ql,
                                   q.shape[-1] ** -0.5)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


class TestRaggedInt8Pages:
    def test_xla_int8_close_to_fp(self):
        ql = [1, 10, 1, 4]
        cl = [18, 10, 27, 33]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, seed=18)
        qkp = quantize_kv_pages(jnp.asarray(kp))
        qvp = quantize_kv_pages(jnp.asarray(vp))
        out = np.asarray(ragged_paged_attention(
            jnp.asarray(q), qkp, qvp, jnp.asarray(bt), jnp.asarray(cl),
            jnp.asarray(ql)))
        ref = _np_ragged_reference(q, kp, vp, bt, cl, ql,
                                   q.shape[-1] ** -0.5)
        np.testing.assert_allclose(out, ref, rtol=0.15, atol=0.15)

    def test_kernel_int8_matches_xla_int8(self):
        # kernel and XLA paths share the _dequant rule -> tight agreement
        ql = [1, 10, 1, 4]
        cl = [18, 10, 27, 33]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, seed=19)
        qkp = quantize_kv_pages(jnp.asarray(kp))
        qvp = quantize_kv_pages(jnp.asarray(vp))
        out_k = np.asarray(ragged_paged_attention(
            jnp.asarray(q), qkp, qvp, jnp.asarray(bt), jnp.asarray(cl),
            jnp.asarray(ql), interpret=True, use_kernel=True))
        out_x = np.asarray(ragged_paged_attention(
            jnp.asarray(q), qkp, qvp, jnp.asarray(bt), jnp.asarray(cl),
            jnp.asarray(ql), use_kernel=False))
        np.testing.assert_allclose(out_k, out_x, rtol=2e-4, atol=2e-4)

    def test_int8_empty_rows_zero(self):
        ql = [1, 0, 3]
        cl = [12, 0, 3]
        q, kp, vp, bt, cl, ql = _ragged_setup(ql, cl, n_pad=2, seed=20)
        out = np.asarray(ragged_paged_attention(
            jnp.asarray(q), quantize_kv_pages(jnp.asarray(kp)),
            quantize_kv_pages(jnp.asarray(vp)), jnp.asarray(bt),
            jnp.asarray(cl), jnp.asarray(ql)))
        assert np.isfinite(out).all()
        np.testing.assert_array_equal(out[int(np.sum(ql)):], 0.0)
