"""MoE layer + gates (reference analog: test/collective/test_moe_api.py and
incubate/distributed/models/moe tests)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.distributed.models.moe import (
    ClipGradForMOEByGlobalNorm, GShardGate, MoELayer, NaiveGate, SwitchGate)

def _expert(d_model, d_hidden):
    return nn.Sequential(
        nn.Linear(d_model, d_hidden), nn.GELU(), nn.Linear(d_hidden, d_model))

class TestGates:
    def test_gshard_shapes_and_loss(self):
        g = GShardGate(16, num_expert=4, world_size=1)
        x = pt.randn([32, 16])
        cw, dm = g(x)
        S, E = 32, 4
        assert cw.shape[0] == S and cw.shape[1] == E
        assert dm.shape == cw.shape
        # each token contributes at most weight 1 in total
        tot = cw.numpy().sum(axis=(1, 2))
        assert (tot <= 1.0 + 1e-5).all()
        assert g.get_loss() is not None

    def test_gshard_balanced_no_second_choice_drop(self):
        # capacity must include the top-k multiplier (ADVICE r1): with
        # perfectly balanced routing, every first AND second choice fits.
        import jax.numpy as jnp

        E, S, d = 4, 32, 16
        g = GShardGate(d, num_expert=E, world_size=1, random_routing=False)
        # rig logits so token i's top-2 experts are (i%E, (i+1)%E) — balanced
        logits = np.full((S, E), -10.0, np.float32)
        for i in range(S):
            logits[i, i % E] = 5.0
            logits[i, (i + 1) % E] = 4.0
        # drive the gate with exact logits via an identity weight
        g.gate.bias._data = jnp.zeros_like(g.gate.bias._data)
        g.gate.weight._data = jnp.eye(d, E, dtype=g.gate.weight._data.dtype)
        x = Tensor(jnp.pad(logits, ((0, 0), (0, d - E))))
        cw, dm = g(x, training=True)
        # every token keeps exactly 2 dispatch slots (no capacity drops)
        per_token = (dm.numpy() > 0).sum(axis=(1, 2))
        assert (per_token == 2).all(), per_token

    def test_switch_top1(self):
        g = SwitchGate(16, num_expert=4, world_size=1, topk=1)
        x = pt.randn([32, 16])
        cw, dm = g(x, training=False)
        # top-1: at most one slot per token
        per_token = (dm.numpy() > 0).sum(axis=(1, 2))
        assert (per_token <= 1).all()
        assert g.get_loss() is not None

    def test_naive_topk(self):
        g = NaiveGate(16, num_expert=4, world_size=1, topk=2)
        x = pt.randn([8, 16])
        idx, val = g(x)
        assert idx.shape == [8, 2]
        assert val.shape == [8, 2]

class TestMoELayer:
    def test_forward_backward_gshard(self):
        d = 16
        layer = MoELayer(d_model=d, experts=[_expert(d, 32) for _ in range(4)],
                         gate="gshard")
        x = pt.randn([2, 8, d])
        x.stop_gradient = False
        y = layer(x)
        assert y.shape == [2, 8, d]
        loss = y.sum() + layer.gate.get_loss() * 0.01
        loss.backward()
        for p in layer.parameters():
            assert p.grad is not None, p.name
            assert np.isfinite(p.grad.numpy()).all()

    def test_forward_switch(self):
        d = 16
        layer = MoELayer(d_model=d, experts=[_expert(d, 32) for _ in range(2)],
                         gate="switch", top_k=1)
        y = layer(pt.randn([4, 4, d]))
        assert y.shape == [4, 4, d]

    def test_naive_matches_dense_mixture(self):
        d = 8
        experts = [nn.Linear(d, d) for _ in range(2)]
        layer = MoELayer(d_model=d, experts=experts, gate="naive", top_k=2)
        x = pt.randn([4, d])
        y = layer(x).numpy()
        # manual: softmax over top-2 of gate logits weights both experts
        logits = layer.gate.gate(x).numpy()

        e_out = np.stack([e(x).numpy() for e in experts], axis=1)
        top2 = np.argsort(-logits, axis=-1)[:, :2]
        vals = np.take_along_axis(logits, top2, axis=-1)
        w = np.exp(vals - vals.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        ref = np.zeros_like(y)
        for s in range(4):
            for k in range(2):
                ref[s] += w[s, k] * e_out[s, top2[s, k]]
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)

    def test_capacity_drops_tokens(self):
        d = 8
        layer = MoELayer(d_model=d, experts=[nn.Linear(d, d)], gate="switch",
                         top_k=1)
        # with 1 expert every token routes there; capacity 1.2*S/1 >= S so
        # no drop: output should equal expert(x) * gate_prob (=1 for top-1)
        x = pt.randn([8, d])
        y = layer(x)
        assert np.isfinite(y.numpy()).all()

class TestMoEGradClip:
    def test_clip(self):
        d = 4
        from paddle_tpu.nn.layer.layers import Parameter

        p_dense = Parameter(pt.randn([d]))
        p_exp = Parameter(pt.randn([d]))
        p_exp.no_sync = True
        g1 = Tensor(np.full((d,), 10.0, np.float32))
        g2 = Tensor(np.full((d,), 10.0, np.float32))
        clip = ClipGradForMOEByGlobalNorm(1.0)
        out = clip([(p_dense, g1), (p_exp, g2)])
        total = sum(float((g._data ** 2).sum()) for _, g in out) ** 0.5
        assert abs(total - 1.0) < 1e-3


class TestJitMoEGPT:
    def test_moe_gpt_trains_and_jits(self):
        import jax

        import paddle_tpu as pt
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny

        pt.seed(0)
        cfg = gpt_tiny(moe_num_experts=4, dropout=0.0,
                       attention_dropout=0.0)
        model = GPTForCausalLM(cfg)
        opt = pt.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
        step = TrainStep(model, opt, grad_clip_norm=1.0)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32)
        labels = rng.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32)
        first = float(step(ids, labels))
        for _ in range(6):
            last = float(step(ids, labels))
        assert last < first, (first, last)

    def test_moe_gpt_spmd_mesh_with_ep(self):
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        import paddle_tpu as pt
        from paddle_tpu.distributed import ProcessMesh
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import GPTForCausalLM, gpt_tiny

        mesh = ProcessMesh(np.arange(8).reshape(2, 4),
                           dim_names=["dp", "ep"])
        pt.seed(1)
        cfg = gpt_tiny(moe_num_experts=4, dropout=0.0,
                       attention_dropout=0.0)
        model = GPTForCausalLM(cfg)
        opt = pt.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
        step = TrainStep(model, opt, mesh=mesh, grad_clip_norm=1.0,
                         batch_specs=[("dp",), ("dp",)])
        # expert weights sharded over ep (TrainStep's device-put arrays)
        for name, arr in zip((n for n, _ in model.named_parameters()),
                             step.param_arrays):
            if name.endswith("w1"):
                ss = arr.sharding.shard_shape(arr.shape)
                assert ss[0] == arr.shape[0] // 4, (name, ss)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
        labels = rng.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
        first = float(step(ids, labels))
        for _ in range(4):
            last = float(step(ids, labels))
        assert last < first, (first, last)
