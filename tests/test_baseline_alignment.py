"""Cross-mode numeric alignment for the BASELINE workloads (VERDICT r1
next #10; reference analogs: test/auto_parallel/hybrid_strategy/
semi_auto_llama.py acc-align variants, dygraph_group_sharded_stage2.py
DP-vs-sharded equality, test_dist_base.py loss comparison)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt


# ------------------------------------------------- #1 MNIST: eager vs jit
def test_mnist_lenet_eager_vs_jit_and_ckpt_resume(tmp_path):
    from paddle_tpu.vision.models import LeNet

    rng = np.random.RandomState(0)
    xs = [rng.randn(8, 1, 28, 28).astype(np.float32) for _ in range(6)]
    ys = [rng.randint(0, 10, (8,)).astype(np.int32) for _ in range(6)]
    loss_fn = pt.nn.CrossEntropyLoss()

    def train(model, opt, steps, jit=False):
        fwd = pt.jit.to_static(model) if jit else model
        losses = []
        for x, y in zip(xs[:steps], ys[:steps]):
            loss = loss_fn(fwd(pt.to_tensor(x)), pt.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    pt.seed(1)
    m1 = LeNet()
    o1 = pt.optimizer.Adam(parameters=m1.parameters(), learning_rate=1e-3)
    eager = train(m1, o1, 6)

    pt.seed(1)
    m2 = LeNet()
    o2 = pt.optimizer.Adam(parameters=m2.parameters(), learning_rate=1e-3)
    jitted = train(m2, o2, 6, jit=True)
    np.testing.assert_allclose(eager, jitted, rtol=1e-4, atol=1e-5)

    # checkpoint resume alignment: 3 steps + save + 3 steps ==
    # load + same 3 steps
    pt.seed(1)
    m3 = LeNet()
    o3 = pt.optimizer.Adam(parameters=m3.parameters(), learning_rate=1e-3)
    for x, y in zip(xs[:3], ys[:3]):
        loss = loss_fn(m3(pt.to_tensor(x)), pt.to_tensor(y))
        loss.backward()
        o3.step()
        o3.clear_grad()
    path = str(tmp_path / "lenet.pdparams")
    pt.save(m3.state_dict(), path)
    pt.save(o3.state_dict(), str(tmp_path / "opt.pdopt"))
    tail_a = []
    for x, y in zip(xs[3:], ys[3:]):
        loss = loss_fn(m3(pt.to_tensor(x)), pt.to_tensor(y))
        loss.backward()
        o3.step()
        o3.clear_grad()
        tail_a.append(float(loss))

    m4 = LeNet()
    m4.set_state_dict(pt.load(path))
    o4 = pt.optimizer.Adam(parameters=m4.parameters(), learning_rate=1e-3)
    o4.set_state_dict(pt.load(str(tmp_path / "opt.pdopt")))
    tail_b = []
    for x, y in zip(xs[3:], ys[3:]):
        loss = loss_fn(m4(pt.to_tensor(x)), pt.to_tensor(y))
        loss.backward()
        o4.step()
        o4.clear_grad()
        tail_b.append(float(loss))
    np.testing.assert_allclose(tail_a, tail_b, rtol=1e-5, atol=1e-6)


# --------------------------------------- #2 ResNet: 2-proc DP == 1 proc
def _dp_cnn():
    """BatchNorm-free CNN: DP == single-process holds exactly (BN's
    per-rank batch statistics break bitwise equality by design — the
    reference's analog tests use Sync BN or tolerance there)."""
    from paddle_tpu import nn

    return nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2),
        nn.Conv2D(8, 16, 3, padding=1), nn.ReLU(),
        nn.AdaptiveAvgPool2D(1), nn.Flatten(), nn.Linear(16, 4))


def _dp_align_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.store import create_or_get_global_tcp_store

    dist.init_parallel_env(backend="cpu")
    r = dist.get_rank()
    pt.seed(0)
    model = pt.DataParallel(_dp_cnn())
    opt = pt.optimizer.SGD(parameters=model.parameters(),
                           learning_rate=0.01)
    loss_fn = pt.nn.CrossEntropyLoss()
    rng = np.random.RandomState(42)   # GLOBAL batch, identical all ranks
    losses = []
    for _ in range(3):
        gx = rng.randn(4, 3, 32, 32).astype(np.float32)
        gy = rng.randint(0, 4, (4,)).astype(np.int32)
        x = pt.to_tensor(gx[r * 2:(r + 1) * 2])    # rank shard
        y = pt.to_tensor(gy[r * 2:(r + 1) * 2])
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    store = create_or_get_global_tcp_store()
    import pickle

    store.set(f"dp_losses_{r}", pickle.dumps(losses))
    if r == 0:
        # single-process baseline on the FULL batch, same seed
        pt.seed(0)
        ref = _dp_cnn()
        ropt = pt.optimizer.SGD(parameters=ref.parameters(),
                                learning_rate=0.01)
        rng2 = np.random.RandomState(42)
        ref_losses = []
        for _ in range(3):
            gx = rng2.randn(4, 3, 32, 32).astype(np.float32)
            gy = rng2.randint(0, 4, (4,)).astype(np.int32)
            loss = loss_fn(ref(pt.to_tensor(gx)), pt.to_tensor(gy))
            loss.backward()
            ropt.step()
            ropt.clear_grad()
            ref_losses.append(float(loss))
        store.wait(["dp_losses_1"])
        l0 = pickle.loads(store.get("dp_losses_0"))
        l1 = pickle.loads(store.get("dp_losses_1"))
        # DP mean loss across ranks == single-proc full-batch loss
        merged = [(a + b) / 2 for a, b in zip(l0, l1)]
        np.testing.assert_allclose(merged, ref_losses, rtol=2e-4,
                                   atol=2e-4)
    dist.barrier()


def test_baseline2_dp_matches_single_process():
    from paddle_tpu.distributed.spawn import spawn

    spawn(_dp_align_worker, nprocs=2)


# ------------------------------- #3 BERT: sharded stage-2 == unsharded DP
def _bert_s2_align_worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import pickle

    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.distributed.store import create_or_get_global_tcp_store
    from paddle_tpu.models import (BertForPreTraining,
                                   BertPretrainingCriterion, bert_tiny)

    dist.init_parallel_env(backend="cpu")
    r = dist.get_rank()
    cfg = bert_tiny(hidden_dropout=0.0, attention_dropout=0.0)
    crit = BertPretrainingCriterion(cfg.vocab_size)
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    mlm = np.full((2, 16), -100, np.int64)
    mlm[:, :4] = rng.randint(0, cfg.vocab_size, (2, 4))
    nsp_np = rng.randint(0, 2, (2,)).astype(np.int32)

    def run(shard: bool):
        pt.seed(5)
        model = BertForPreTraining(cfg)
        opt = pt.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
        if shard:
            model_w, opt, _ = group_sharded_parallel(model, opt, "os_g")
        else:
            model_w = pt.DataParallel(model)
        losses = []
        for _ in range(3):
            scores, rel = model_w(pt.to_tensor(ids_np))
            loss = crit(scores, rel, pt.to_tensor(mlm),
                        pt.to_tensor(nsp_np))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    sharded = run(True)
    dp = run(False)
    np.testing.assert_allclose(sharded, dp, rtol=2e-4, atol=2e-4)
    store = create_or_get_global_tcp_store()
    store.set(f"bert_ok_{r}", b"1")
    store.wait([f"bert_ok_{1 - r}"])


def test_baseline3_sharded_matches_dp():
    from paddle_tpu.distributed.spawn import spawn

    spawn(_bert_s2_align_worker, nprocs=2)


# -------------------------- #5 Llama semi-auto: dygraph == mesh TrainStep
def test_baseline5_llama_dygraph_vs_semiauto():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from paddle_tpu.distributed import ProcessMesh
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny

    rng = np.random.RandomState(0)
    data = [(rng.randint(0, 1024, (4, 32)).astype(np.int32),
             rng.randint(0, 1024, (4, 32)).astype(np.int32))
            for _ in range(4)]

    # dygraph eager single-device
    pt.seed(9)
    m1 = LlamaForCausalLM(llama_tiny())
    o1 = pt.optimizer.AdamW(learning_rate=3e-3,
                            parameters=m1.parameters())
    eager = []
    for ids, lab in data:
        loss = m1(pt.to_tensor(ids), labels=pt.to_tensor(lab))
        loss.backward()
        o1.step()
        o1.clear_grad()
        eager.append(float(loss))

    # semi-auto: dp x sp x mp mesh, compiled step
    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["dp", "sp", "mp"])
    pt.seed(9)
    m2 = LlamaForCausalLM(llama_tiny())
    o2 = pt.optimizer.AdamW(learning_rate=3e-3,
                            parameters=m2.parameters())
    step = TrainStep(m2, o2, mesh=mesh)
    semi = [float(step(ids, lab)) for ids, lab in data]
    np.testing.assert_allclose(eager, semi, rtol=2e-2, atol=2e-2)
