"""Program-level pass tier (VERDICT r3 missing #5 / weak #5; reference:
python/paddle/distributed/passes/pass_base.py,
auto_parallel_{amp,recompute}.py,
pipeline_scheduler_pass/{pipeline_fthenb,pipeline_1f1b}.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.distributed.passes import (Pipeline1F1BPass,
                                           PipelineFThenBPass, PassManager,
                                           StagedProgram, new_pass)


@pytest.fixture
def static_mode():
    paddle.enable_static()
    prog = static.Program()
    with static.program_guard(prog):
        yield prog
    paddle.disable_static()


class TestPassRegistry:
    def test_new_pass_and_unknown(self):
        p = new_pass("auto_parallel_amp", {"dtype": "bfloat16"})
        assert p.name == "auto_parallel_amp"
        assert p.get_attr("dtype") == "bfloat16"
        with pytest.raises(ValueError, match="unknown pass"):
            new_pass("nope")


class TestProgramPasses:
    def _capture(self):
        from paddle_tpu import nn

        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 8)
        y = paddle.matmul(lin(x), lin.weight)
        # softmax (black-list) stays f32; the weighted sum is amp-sensitive
        out = (paddle.nn.functional.softmax(y) * y).sum()
        return x, out

    def test_amp_pass_casts_matmuls(self, static_mode):
        import jax.numpy as jnp

        x, out = self._capture()
        feed = {"x": np.random.RandomState(0).randn(4, 8)
                .astype(np.float32)}
        exe = static.Executor()
        base = exe.run(feed=feed, fetch_list=[out])[0]
        pm = PassManager([new_pass("auto_parallel_amp",
                                   {"dtype": "bfloat16"})])
        (out_amp,) = pm.apply([out])
        got = exe.run(feed=feed, fetch_list=[out_amp])[0]
        # bf16 matmuls: close to but not bit-equal with the f32 program
        np.testing.assert_allclose(got, base, rtol=2e-2)
        assert not np.array_equal(got, base), \
            "amp pass did not change numerics — cast not applied"

    def test_recompute_pass_preserves_values(self, static_mode):
        x, out = self._capture()
        feed = {"x": np.random.RandomState(1).randn(4, 8)
                .astype(np.float32)}
        exe = static.Executor()
        base = exe.run(feed=feed, fetch_list=[out])[0]
        (out_rc,) = PassManager(
            [new_pass("auto_parallel_recompute")]).apply([out])
        got = exe.run(feed=feed, fetch_list=[out_rc])[0]
        np.testing.assert_allclose(got, base, rtol=1e-6)

    def test_passes_compose_and_grads_flow(self, static_mode):
        x, out = self._capture()
        pm = PassManager([new_pass("auto_parallel_recompute"),
                          new_pass("auto_parallel_amp")])
        (out2,) = pm.apply([out])
        (gx,) = static.gradients([out2], [x])
        exe = static.Executor()
        feed = {"x": np.ones((4, 8), np.float32)}
        vals = exe.run(feed=feed, fetch_list=[out2, gx])
        assert np.isfinite(vals[0]).all() and np.isfinite(vals[1]).all()


class TestPipelineSchedulePasses:
    def _program(self, devices=None):
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        w1 = jnp.asarray(rng.randn(8, 16).astype(np.float32) * 0.3)
        w2 = jnp.asarray(rng.randn(16, 4).astype(np.float32) * 0.3)

        def stage0(p, x):
            return jnp.tanh(x @ p)

        def stage1(p, x):
            return x @ p

        def loss_fn(y, label):
            return jnp.mean((y - label) ** 2)

        return StagedProgram([stage0, stage1], [w1, w2], loss_fn,
                             devices=devices), (w1, w2), loss_fn

    def _reference(self, prog, mbs, labels):
        import jax
        import jax.numpy as jnp

        # uncommitted copies: prog.params may be pinned to distinct devices
        ref_params = tuple(jnp.asarray(np.asarray(p))
                           for p in prog.params)

        def total(params):
            w1, w2 = params
            losses = []
            for x, lab in zip(mbs, labels):
                y = jnp.tanh(x @ w1) @ w2
                losses.append(jnp.mean((y - lab) ** 2))
            return sum(losses) / len(losses)

        loss, grads = jax.value_and_grad(total)(ref_params)
        return loss, grads

    def _data(self, M=4):
        rng = np.random.RandomState(1)
        mbs = [np.asarray(rng.randn(2, 8), np.float32) for _ in range(M)]
        labels = [np.asarray(rng.randn(2, 4), np.float32)
                  for _ in range(M)]
        return mbs, labels

    @pytest.mark.parametrize("sched_cls", [PipelineFThenBPass,
                                           Pipeline1F1BPass])
    def test_schedule_matches_reference_grads(self, sched_cls):
        prog, _, _ = self._program()
        mbs, labels = self._data()
        loss, grads, jobs = sched_cls().apply(prog, mbs, labels)
        ref_loss, ref_grads = self._reference(prog, mbs, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for g, rg in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                       rtol=1e-4, atol=1e-6)

    def test_fthenb_and_1f1b_identical_numerics_different_order(self):
        prog, _, _ = self._program()
        mbs, labels = self._data()
        l1, g1, jobs_f = PipelineFThenBPass().apply(prog, mbs, labels)
        l2, g2, jobs_1 = Pipeline1F1BPass().apply(prog, mbs, labels)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)
        assert jobs_f != jobs_1
        # FThenB: every F precedes every B
        last_f = max(i for i, j in enumerate(jobs_f) if j[0] == "F")
        first_b = min(i for i, j in enumerate(jobs_f) if j[0] == "B")
        assert last_f < first_b
        # 1F1B: some backward runs before the final forward (early drain)
        last_f1 = max(i for i, j in enumerate(jobs_1) if j[0] == "F")
        first_b1 = min(i for i, j in enumerate(jobs_1) if j[0] == "B")
        assert first_b1 < last_f1

    def test_1f1b_bounded_live_activations(self):
        """The schedule property the pass exists for: the first stage
        never holds more than S in-flight micro-batches under 1F1B,
        but holds all M under FThenB."""
        S_, M_ = 2, 6
        prog, _, _ = self._program()
        mbs, labels = self._data(M_)

        def max_inflight(jobs, stage):
            live = cur = 0
            for kind, s, m in jobs:
                if s != stage:
                    continue
                cur += 1 if kind == "F" else -1
                live = max(live, cur)
            return live

        _, _, jobs_f = PipelineFThenBPass().apply(prog, mbs, labels)
        _, _, jobs_1 = Pipeline1F1BPass().apply(prog, mbs, labels)
        assert max_inflight(jobs_f, 0) == M_
        assert max_inflight(jobs_1, 0) <= S_ + 1

    def test_schedule_on_cpu_mesh_devices(self):
        """Stage placement on distinct devices of the 8-dev CPU mesh."""
        import jax

        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs multi-device host")
        prog, _, _ = self._program(devices=[devs[0], devs[1]])
        mbs, labels = self._data()
        loss, grads, _ = Pipeline1F1BPass().apply(prog, mbs, labels)
        ref_loss, ref_grads = self._reference(prog, mbs, labels)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        assert grads[0].devices() == {devs[0]}
        assert grads[1].devices() == {devs[1]}


class TestDecomposition:
    def test_decompose_rewrites_and_matches(self, static_mode):
        import paddle_tpu.decomposition as decomp

        x = static.data("x", [4, 8], "float32")
        y = paddle.nn.functional.softmax(x * 2)
        out = (y * y).sum()
        exe = static.Executor()
        feed = {"x": np.random.RandomState(0).randn(4, 8)
                .astype(np.float32)}
        base = exe.run(feed=feed, fetch_list=[out])[0]
        (out_d,) = decomp.decompose([out], ops=["softmax"])
        got = exe.run(feed=feed, fetch_list=[out_d])[0]
        np.testing.assert_allclose(got, base, rtol=1e-5)

    def test_custom_rule_registration(self, static_mode):
        import jax.numpy as jnp

        import paddle_tpu.decomposition as decomp

        @decomp.register_decomp("tanh")
        def tanh_rule(a):
            e2 = jnp.exp(2 * a)
            return (e2 - 1) / (e2 + 1)

        try:
            assert decomp.get_decomp_rule("tanh") is tanh_rule
            x = static.data("xx", [3], "float32")
            out = paddle.tanh(x)
            (out_d,) = decomp.decompose([out], ops=["tanh"])
            got = static.Executor().run(
                feed={"xx": np.array([0.1, -0.5, 2.0], np.float32)},
                fetch_list=[out_d])[0]
            np.testing.assert_allclose(got, np.tanh([0.1, -0.5, 2.0]),
                                       rtol=1e-5)
        finally:
            decomp._RULES.pop("tanh", None)

    def test_mismatched_rule_falls_back(self, static_mode):
        """An axis-reduced mean does not match the global-mean rule's
        signature — the original op must be kept, values unchanged."""
        import paddle_tpu.decomposition as decomp

        x = static.data("xm", [4, 8], "float32")
        out = x.mean(axis=1).sum()
        feed = {"xm": np.random.RandomState(2).randn(4, 8)
                .astype(np.float32)}
        exe = static.Executor()
        base = exe.run(feed=feed, fetch_list=[out])[0]
        (out_d,) = decomp.decompose([out])
        got = exe.run(feed=feed, fetch_list=[out_d])[0]
        np.testing.assert_allclose(got, base, rtol=1e-6)


class TestStrategyPassComposition:
    """VERDICT r4 next #7: sharding + gradient-merge in the program-pass
    tier; AMP pass lists generated from the eager amp lists; Engine
    composes strategy passes through PassManager."""

    def test_amp_pass_lists_match_eager(self):
        from paddle_tpu import amp as amp_mod
        from paddle_tpu.distributed.passes import AMPPass

        white, black = AMPPass()._lists()
        assert white == amp_mod.WHITE_LIST
        assert black == amp_mod.BLACK_LIST - amp_mod.WHITE_LIST
        # custom lists compose exactly like eager auto_cast
        p = AMPPass().set_attr("custom_white_list", {"softmax"})
        w2, b2 = p._lists()
        assert "softmax" in w2 and "softmax" not in b2

    def test_amp_custom_white_changes_numerics(self, static_mode):
        from paddle_tpu import nn

        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 8)
        out = paddle.nn.functional.softmax(lin(x) * 37.0).sum()
        feed = {"x": np.random.RandomState(2).randn(4, 8)
                .astype(np.float32)}
        exe = static.Executor()
        base = exe.run(feed=feed, fetch_list=[out])[0]
        (o1,) = PassManager([new_pass("auto_parallel_amp")]).apply([out])
        got1 = exe.run(feed=feed, fetch_list=[o1])[0]
        (o2,) = PassManager([new_pass(
            "auto_parallel_amp",
            {"custom_white_list": {"softmax"}})]).apply([out])
        got2 = exe.run(feed=feed, fetch_list=[o2])[0]
        np.testing.assert_allclose(got1, base, rtol=5e-2)
        np.testing.assert_allclose(got2, base, rtol=5e-2)
        # softmax whitelisted -> computed in bf16 -> different rounding
        assert not np.array_equal(got1, got2)

    def test_sharding_pass_annotates_params(self, static_mode):
        import jax
        from jax.sharding import Mesh

        from paddle_tpu import nn

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
        x = static.data("x", [8, 16], "float32")
        lin = nn.Linear(16, 16)
        out = (lin(x) ** 2).sum()
        feed = {"x": np.random.RandomState(3).randn(8, 16)
                .astype(np.float32)}
        exe = static.Executor()
        base = exe.run(feed=feed, fetch_list=[out])[0]
        (o_sh,) = PassManager([new_pass(
            "auto_parallel_sharding",
            {"stage": 3, "mesh": mesh})]).apply([out])
        got = exe.run(feed=feed, fetch_list=[o_sh])[0]
        np.testing.assert_allclose(got, base, rtol=1e-6)
        # the rewritten DAG contains shard_param constraint nodes
        names = set()

        def walk(node, seen):
            if id(node) in seen:
                return
            seen.add(id(node))
            from paddle_tpu.static import graph as G
            if isinstance(node, G.OpNode):
                names.add(node.name)
                for p in node.parents:
                    walk(p[0] if isinstance(p, tuple) else p, seen)

        walk(o_sh._sym_node[0], set())
        assert "shard_param" in names

    def test_configure_context(self):
        from paddle_tpu.distributed.passes import PassManager, new_pass

        pm = PassManager([
            new_pass("auto_parallel_amp", {"dtype": "bfloat16"}),
            new_pass("auto_parallel_sharding", {"stage": 2}),
            new_pass("auto_parallel_gradient_merge", {"k_steps": 4}),
        ])
        ctx = pm.configure().attrs
        assert ctx["amp"]["enable"] and ctx["amp"]["dtype"] == "bfloat16"
        assert ctx["fsdp_axis"] == "dp" and ctx["sharding_stage"] == 2
        assert ctx["accumulate_steps"] == 4

    def test_engine_composes_through_pass_manager(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu import nn, optimizer
        from paddle_tpu.distributed.auto_parallel.engine import (Engine,
                                                                 Strategy)

        st = Strategy()
        st.amp.enable = True
        st.gradient_merge.enable = True
        st.gradient_merge.k_steps = 2
        st.sharding.enable = True
        st.sharding.stage = 2
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 4))
        eng = Engine(model=model, loss=nn.CrossEntropyLoss(),
                     optimizer=optimizer.SGD(
                         learning_rate=0.1,
                         parameters=model.parameters()),
                     strategy=st)
        xs = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        ys = np.random.RandomState(1).randint(0, 4, (4, 1))
        eng.fit([(xs, ys)], epochs=1)
        assert eng.pass_manager is not None
        assert eng.pass_manager.names == [
            "auto_parallel_amp", "auto_parallel_sharding",
            "auto_parallel_gradient_merge"]
        assert len(eng.history["loss"]) >= 1
