"""Tier-1 wiring for tools/elastic_drill.py: the seeded 3-process
kill -> shrink -> rejoin -> re-expand chaos drill. The fast arm runs one
full drill (peer-sourced recovery inside the elastic timeout, epoch
timeline pinned, loss parity against the single-process reference); the
slow arm replays the whole drill twice and requires bit-identical
trajectories."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                "tools"))
import elastic_drill  # noqa: E402


def test_elastic_drill_kill_shrink_rejoin():
    summary = elastic_drill.main()
    # shrink resumed the very next step after the kill, from peers only
    assert summary["recoveries"]
    assert all(r["source"] == "peer" for r in summary["recoveries"])
    members = [e["members"] for e in summary["epoch_log"]]
    assert members[0] == [0, 1, 2]
    assert [0, 1] in members
    assert members[-1] == [0, 1, 2]
    assert summary["recovery_wall_s"] < elastic_drill.TIMEOUT_S


@pytest.mark.slow
def test_elastic_drill_deterministic_across_runs():
    assert elastic_drill.main_determinism() == 0
