"""Long-tail ops, fft, linalg namespace (reference analogs:
test/legacy_test per-op tests; OpTest numeric-reference strategy)."""
import numpy as np
import pytest
import scipy.special

import paddle_tpu as pt


def t(a):
    return pt.to_tensor(np.asarray(a, dtype=np.float32))


class TestExtras:
    def test_diagonal(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        np.testing.assert_array_equal(pt.diagonal(t(a)).numpy(),
                                      np.diagonal(a))
        np.testing.assert_array_equal(pt.diagonal(t(a), offset=1).numpy(),
                                      np.diagonal(a, 1))

    def test_logcumsumexp(self):
        a = np.random.randn(8).astype(np.float32)
        out = pt.logcumsumexp(t(a), axis=0).numpy()
        ref = np.logaddexp.accumulate(a)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_quantile(self):
        a = np.random.randn(40).astype(np.float32)
        np.testing.assert_allclose(pt.quantile(t(a), 0.3).numpy(),
                                   np.quantile(a, 0.3), rtol=1e-5)

    def test_mode(self):
        a = np.array([[1., 2., 2., 3.], [5., 5., 5., 1.]], np.float32)
        vals, idx = pt.mode(t(a))
        np.testing.assert_array_equal(vals.numpy(), [2.0, 5.0])

    def test_trapezoid(self):
        y = np.array([1., 2., 3.], np.float32)
        x = np.array([0., 1., 3.], np.float32)
        np.testing.assert_allclose(pt.trapezoid(t(y), t(x)).numpy(),
                                   np.trapezoid(y, x), rtol=1e-6)

    def test_renorm(self):
        a = np.random.randn(3, 4).astype(np.float32) * 10
        out = pt.renorm(t(a), p=2, axis=0, max_norm=1.0).numpy()
        norms = np.linalg.norm(out.reshape(3, -1), axis=1)
        assert (norms <= 1.0 + 1e-5).all()

    def test_frexp_ldexp(self):
        a = np.array([1.5, -3.0, 0.25], np.float32)
        m, e = pt.frexp(t(a))
        np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), a,
                                   rtol=1e-6)
        out = pt.ldexp(t(np.array([1.0, 1.0])), t(np.array([3, -1])))
        np.testing.assert_allclose(out.numpy(), [8.0, 0.5])

    def test_complex_helpers(self):
        r = np.array([[1., 2.]], np.float32)
        c = pt.as_complex(t(r))
        assert c.numpy().dtype == np.complex64
        back = pt.as_real(c)
        np.testing.assert_allclose(back.numpy(), r)
        p = pt.polar(t([2.0]), t([np.pi / 2]))
        np.testing.assert_allclose(p.numpy(), [2j], atol=1e-6)

    def test_special_functions(self):
        x = np.array([0.5, 1.5, 3.0], np.float32)
        np.testing.assert_allclose(pt.gammaln(t(x)).numpy(),
                                   scipy.special.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(pt.i0(t(x)).numpy(),
                                   scipy.special.i0(x), rtol=1e-5)
        np.testing.assert_allclose(pt.sinc(t(x)).numpy(),
                                   np.sinc(x), rtol=1e-5)
        np.testing.assert_allclose(
            pt.erfinv(t(np.array([0.5], np.float32))).numpy(),
            scipy.special.erfinv(0.5), rtol=1e-5)

    def test_isin(self):
        a = np.array([1, 2, 3, 4])
        out = pt.isin(pt.to_tensor(a), pt.to_tensor(np.array([2, 4])))
        np.testing.assert_array_equal(out.numpy(), [False, True, False, True])

    def test_vdot_baddbmm(self):
        a = np.random.randn(4).astype(np.float32)
        b = np.random.randn(4).astype(np.float32)
        np.testing.assert_allclose(pt.vdot(t(a), t(b)).numpy(),
                                   np.vdot(a, b), rtol=1e-5)
        i = np.random.randn(2, 3, 5).astype(np.float32)
        x = np.random.randn(2, 3, 4).astype(np.float32)
        y = np.random.randn(2, 4, 5).astype(np.float32)
        out = pt.baddbmm(t(i), t(x), t(y), beta=0.5, alpha=2.0).numpy()
        np.testing.assert_allclose(out, 0.5 * i + 2.0 * (x @ y), rtol=1e-4)

    def test_masked_scatter(self):
        a = np.zeros((2, 3), np.float32)
        mask = np.array([[1, 0, 1], [0, 1, 0]], bool)
        vals = np.array([10., 20., 30.], np.float32)
        out = pt.masked_scatter(t(a), pt.to_tensor(mask), t(vals)).numpy()
        np.testing.assert_array_equal(out, [[10, 0, 20], [0, 30, 0]])

    def test_unfold(self):
        a = np.arange(10, dtype=np.float32)
        out = pt.unfold(t(a), axis=0, size=4, step=2).numpy()
        assert out.shape == (4, 4)
        np.testing.assert_array_equal(out[1], [2, 3, 4, 5])
        b = np.arange(24, dtype=np.float32).reshape(2, 12)
        out2 = pt.unfold(t(b), axis=1, size=6, step=3).numpy()
        assert out2.shape == (2, 3, 6)
        np.testing.assert_array_equal(out2[0, 1], b[0, 3:9])


class TestFFT:
    def test_fft_roundtrip(self):
        a = np.random.randn(16).astype(np.float32)
        f = pt.fft.fft(t(a))
        back = pt.fft.ifft(f).numpy()
        np.testing.assert_allclose(back.real, a, atol=1e-5)

    def test_rfft_matches_numpy(self):
        a = np.random.randn(16).astype(np.float32)
        np.testing.assert_allclose(pt.fft.rfft(t(a)).numpy(),
                                   np.fft.rfft(a), atol=1e-4)

    def test_fft2_and_shift(self):
        a = np.random.randn(4, 4).astype(np.float32)
        np.testing.assert_allclose(pt.fft.fft2(t(a)).numpy(),
                                   np.fft.fft2(a), atol=1e-4)
        np.testing.assert_allclose(
            pt.fft.fftshift(t(np.arange(4, dtype=np.float32))).numpy(),
            np.fft.fftshift(np.arange(4.0)))

    def test_fftfreq(self):
        np.testing.assert_allclose(pt.fft.fftfreq(8, 0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5))

    def test_rfft_grad(self):
        a = t(np.random.randn(8).astype(np.float32))
        a.stop_gradient = False
        out = pt.fft.rfft(a)
        # abs^2 spectrum sum -> real loss
        loss = pt.as_real(out).pow(2).sum()
        loss.backward()
        assert a.grad is not None
        assert np.isfinite(a.grad.numpy()).all()


class TestLinalgNamespace:
    def test_cond(self):
        a = np.diag([1.0, 10.0]).astype(np.float32)
        np.testing.assert_allclose(pt.linalg.cond(t(a)).numpy(), 10.0,
                                   rtol=1e-5)

    def test_namespace_complete(self):
        for fn in ("svd", "qr", "cholesky", "solve", "inv", "det", "norm",
                   "eig", "eigh", "lstsq", "pinv", "matrix_power"):
            assert hasattr(pt.linalg, fn), fn
