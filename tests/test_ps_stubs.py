"""PS-mode API stubs: PS user code imports, role-detects, and fails at the
runtime boundary with migration guidance (VERDICT r1 next #9; SURVEY
§2.4.17 collective-first decision; reference the_one_ps.py)."""
import os

import pytest

from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.ps import (PaddleCloudRoleMaker, PSGuidanceError,
                                       Role, Table, UserDefinedRoleMaker)


def test_role_maker_env_detection(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVER_ID", "1")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "h1:80,h2:80")
    rm = PaddleCloudRoleMaker()
    assert rm.is_server() and not rm.is_worker()
    assert rm.server_index() == 1
    assert rm.server_num() == 2

    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    rm = PaddleCloudRoleMaker()
    assert rm.is_worker()


def test_ps_fleet_init_and_guided_failure():
    rm = UserDefinedRoleMaker(current_id=0, role=Role.WORKER, worker_num=2,
                              server_endpoints=["h1:80"])
    f = fleet.Fleet()
    f.init(role_maker=rm, is_collective=False)
    assert f.is_worker() and not f.is_server()
    with pytest.raises(PSGuidanceError, match="collective-first"):
        f.init_worker()
    with pytest.raises(PSGuidanceError, match="sharding"):
        f.init_server()
    with pytest.raises(PSGuidanceError):
        f.run_server()
    with pytest.raises(PSGuidanceError):
        f.stop_worker()


def test_table_data_plane_guided():
    t = Table()
    t.table_class = "MemorySparseTable"
    with pytest.raises(PSGuidanceError):
        t.pull([1, 2, 3])
    with pytest.raises(PSGuidanceError):
        t.push([1, 2, 3], None)
