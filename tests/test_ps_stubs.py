"""PS-mode surface: role detection, fleet wiring, and the failure
contract now that the data plane is REAL (r5; reference the_one_ps.py).
PS user code imports, role-detects, and — when the PS world cannot come
up — fails BOUNDED and loudly instead of hanging (the r1-era guidance
stubs raised immediately; the real runtime probes the rendezvous with a
timeout)."""
import os

import pytest

from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.ps import (PaddleCloudRoleMaker, PSGuidanceError,
                                       Role, SparseTable, Table,
                                       UserDefinedRoleMaker)


def test_role_maker_env_detection(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVER_ID", "1")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "h1:80,h2:80")
    rm = PaddleCloudRoleMaker()
    assert rm.is_server() and not rm.is_worker()
    assert rm.server_index() == 1
    assert rm.server_num() == 2

    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    rm = PaddleCloudRoleMaker()
    assert rm.is_worker()


def test_ps_fleet_init_wires_runtime_and_bounds_rendezvous():
    """fleet.init(is_collective=False) builds the PS runtime; a worker
    whose PS world never comes up times out loudly instead of hanging
    (the real-runtime analog of the old guided failure)."""
    rm = UserDefinedRoleMaker(current_id=0, role=Role.WORKER, worker_num=2,
                              server_endpoints=["h1:80"])
    f = fleet.Fleet()
    f.init(role_maker=rm, is_collective=False)
    assert f.is_worker() and not f.is_server()
    with pytest.raises(TimeoutError, match="rendezvous"):
        f.init_worker(timeout=1.5)


def test_ps_missing_servers_still_guided():
    """No server endpoints configured -> immediate guidance, not a
    rendezvous attempt."""
    from paddle_tpu.distributed.ps import TheOnePSRuntime

    rt = TheOnePSRuntime(UserDefinedRoleMaker(worker_num=1,
                                              server_endpoints=[]))
    with pytest.raises(PSGuidanceError, match="PSERVERS"):
        rt.init_worker()
    with pytest.raises(PSGuidanceError):
        rt.run_server()


def test_table_schema_materializes_data_plane():
    """Table is the schema; the data plane behind it is real (r4 verdict
    missing #6): a sparse table built from the schema pulls/pushes."""
    import numpy as np

    t = Table(table_id=3, kind="sparse", dim=4, optimizer="sgd", lr=1.0)
    assert t.table_class == "MemorySparseTable"
    tab = SparseTable(t.dim, optimizer=t.optimizer, lr=t.lr,
                      initializer="zeros")
    tab.push([7], np.ones((1, 4), np.float32))
    np.testing.assert_allclose(tab.pull([7])[0], -np.ones(4), rtol=1e-6)
