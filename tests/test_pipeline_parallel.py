"""Pipeline parallelism: 2-rank 1F1B + interleaved VPP, multi-process over
the CPU backend (reference analog: test/collective/fleet/
hybrid_parallel_pp_layer.py, hybrid_parallel_pp_interleave.py)."""
import os

import numpy as np
import pytest


def _pp_worker(mode):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.meta_parallel import (
        PipelineLayer, PipelineParallel, PipelineParallelWithInterleave,
        PipelineParallelZeroBubble)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    pt.seed(42)
    n_layers = 8 if mode == "interleave" else 4
    vpp = 2 if mode == "interleave" else None
    layers = [nn.Linear(8, 8) for _ in range(n_layers)]

    def loss_fn(out, label):
        return ((out - label) ** 2).mean()

    pipe = PipelineLayer(layers, loss_fn=loss_fn,
                         num_virtual_pipeline_stages=vpp)
    cls = {"1f1b": PipelineParallel,
           "interleave": PipelineParallelWithInterleave,
           "zb": PipelineParallelZeroBubble}[mode]
    model = cls(pipe, hcg, strategy)
    opt = pt.optimizer.SGD(parameters=pipe.parameters(), learning_rate=0.01)

    rng = np.random.RandomState(0)
    X = rng.randn(8, 8).astype(np.float32)
    Y = rng.randn(8, 8).astype(np.float32) * 0.1

    losses = []
    for step in range(8):
        loss = model.train_batch((pt.to_tensor(X), pt.to_tensor(Y)), opt)
        if loss is not None:
            losses.append(float(loss))
    if hcg.is_last_stage():
        assert losses[-1] < losses[0], losses
        # single-process reference: same layers sequentially
        pt.seed(42)
        ref_layers = [nn.Linear(8, 8) for _ in range(n_layers)]
        ref_opt = pt.optimizer.SGD(
            parameters=[p for l in ref_layers for p in l.parameters()],
            learning_rate=0.01)
        ref_losses = []
        for step in range(8):
            x = pt.to_tensor(X)
            for l in ref_layers:
                x = l(x)
            loss = ((x - pt.to_tensor(Y)) ** 2).mean()
            loss.backward()
            ref_opt.step()
            ref_opt.clear_grad()
            ref_losses.append(float(loss))
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=1e-5)


def _run(mode):
    # spawn must import this module; guard against jax platform leakage
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    from paddle_tpu.distributed.spawn import spawn

    spawn(_pp_worker, args=(mode,), nprocs=2)


def test_pipeline_1f1b_matches_single_process():
    _run("1f1b")


def test_pipeline_interleave_matches_single_process():
    _run("interleave")


def test_pipeline_zero_bubble_matches_single_process():
    _run("zb")
