"""Unit tests for tools/ptlint: one known-bad and one known-good
fixture per rule, plus suppression comments, baseline filtering/stale
detection, and CLI exit codes.

Fixtures are written under tmp_path and linted with ``root=tmp_path``,
so findings carry clean relative paths and the repo's own baseline
never interferes.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.ptlint import lint  # noqa: E402
from tools.ptlint.engine import (Finding, apply_baseline,  # noqa: E402
                                 collect_files, run_passes)

# minimal stand-in for paddle_tpu/observability/metrics_schema.py --
# the metric-names pass importlib-loads this file from the lint root
_SCHEMA_SRC = textwrap.dedent("""\
    from typing import NamedTuple, Optional, Tuple

    class MetricSpec(NamedTuple):
        kind: str
        unit: str
        desc: str
        buckets: Optional[Tuple[float, ...]] = None
        tags: Tuple[str, ...] = ()

    METRICS = {
        "train.steps": MetricSpec("counter", "steps", "steps run"),
    }
    SPANS = {"train.step": "one step"}
    """)


def _lint(tmp_path, files, select=None, with_schema=False):
    """Write ``files`` (relpath -> source) under tmp_path and return
    the new findings of the selected rules."""
    if with_schema:
        files = dict(files)
        files.setdefault("paddle_tpu/observability/metrics_schema.py",
                         _SCHEMA_SRC)
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    new, _, _ = lint([str(tmp_path)], root=str(tmp_path),
                     select=select, baseline_path=None)
    return new


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------ jit-purity
BAD_JIT_PURITY = """\
    import jax

    @jax.jit
    def step(x):
        print("stepping", x)
        return x * 2
    """

GOOD_JIT_PURITY = """\
    import jax

    @jax.jit
    def step(x):
        return x * 2

    def host_loop(xs):
        for x in xs:
            print("host-side logging is fine", x)
    """


def test_jit_purity_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_JIT_PURITY},
                select=["jit-purity"])
    assert _rules(new) == ["jit-purity"]
    assert any("print" in f.message for f in new)


def test_jit_purity_good(tmp_path):
    assert _lint(tmp_path, {"mod.py": GOOD_JIT_PURITY},
                 select=["jit-purity"]) == []


def test_jit_purity_transitive_callee(tmp_path):
    # the side effect sits in a helper only REACHABLE from a jit root
    src = """\
        import jax

        def helper(x):
            print("traced transitively")
            return x

        @jax.jit
        def step(x):
            return helper(x)
        """
    new = _lint(tmp_path, {"mod.py": src}, select=["jit-purity"])
    assert any("helper" in f.message and "print" in f.message
               for f in new)


# ------------------------------------------------------ recompile-hazard
BAD_RECOMPILE = """\
    import jax

    def f(x):
        return x

    def run(xs):
        for x in xs:
            y = jax.jit(f)(x)
        return y
    """

GOOD_RECOMPILE = """\
    import jax

    def f(x):
        return x

    jitted = jax.jit(f)

    def run(xs):
        for x in xs:
            y = jitted(x)
        return y
    """


def test_recompile_hazard_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_RECOMPILE},
                select=["recompile-hazard"])
    assert _rules(new) == ["recompile-hazard"]
    assert any("inside a loop" in f.message for f in new)


def test_recompile_hazard_good(tmp_path):
    assert _lint(tmp_path, {"mod.py": GOOD_RECOMPILE},
                 select=["recompile-hazard"]) == []


def test_recompile_hazard_unhashable_static(tmp_path):
    src = """\
        import jax

        def f(x, cfg):
            return x

        g = jax.jit(f, static_argnums=(1,))
        y = g(1, [1, 2, 3])
        """
    new = _lint(tmp_path, {"mod.py": src}, select=["recompile-hazard"])
    assert any("unhashable static argument" in f.message for f in new)


def test_recompile_hazard_shape_branch(tmp_path):
    src = """\
        import jax

        @jax.jit
        def f(x):
            if x.shape[0] > 1:
                return x * 2
            return x
        """
    new = _lint(tmp_path, {"mod.py": src}, select=["recompile-hazard"])
    assert any("branch on `.shape`" in f.message for f in new)


# ------------------------------------------- collective-consistency
BAD_COLLECTIVE = """\
    def sync(pg, x, rank):
        if rank == 0:
            pg.all_reduce(x)
        return x
    """

GOOD_COLLECTIVE = """\
    def sync(pg, x, rank):
        pg.all_reduce(x)
        if rank == 0:
            pg.broadcast(x, src=0)
        else:
            pg.broadcast(x, src=0)
        return x
    """


def test_collective_consistency_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_COLLECTIVE},
                select=["collective-consistency"])
    assert _rules(new) == ["collective-consistency"]
    assert any("rank-dependent" in f.message for f in new)


def test_collective_consistency_good(tmp_path):
    # unconditional + balanced both-branch collectives: consistent
    assert _lint(tmp_path, {"mod.py": GOOD_COLLECTIVE},
                 select=["collective-consistency"]) == []


def test_collective_swallowing_except(tmp_path):
    src = """\
        def sync(pg, x):
            try:
                pg.all_reduce(x)
            except Exception:
                pass
            return x
        """
    new = _lint(tmp_path, {"mod.py": src},
                select=["collective-consistency"])
    assert any("swallowing except" in f.message for f in new)


# --------------------------------------------------------- lock-discipline
BAD_LOCK = """\
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded by: _lock

        def get(self, k):
            return self._items.get(k)
    """

GOOD_LOCK = """\
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded by: _lock

        def get(self, k):
            with self._lock:
                return self._items.get(k)

        def flush(self):  # ptlint: holds=_lock
            self._items.clear()
    """


def test_lock_discipline_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_LOCK},
                select=["lock-discipline"])
    assert _rules(new) == ["lock-discipline"]
    assert any("outside `with self._lock`" in f.message for f in new)


def test_lock_discipline_good(tmp_path):
    # locked access + holds= helper are both clean
    assert _lint(tmp_path, {"mod.py": GOOD_LOCK},
                 select=["lock-discipline"]) == []


def test_lock_discipline_external_poke(tmp_path):
    files = {
        "owner.py": """\
            class Manager:
                def __init__(self):
                    self._free = []  # guarded by: caller (Engine._lock)
            """,
        "poker.py": """\
            def steal(manager):
                return manager._free.pop()
            """,
    }
    new = _lint(tmp_path, files, select=["lock-discipline"])
    assert any(f.path == "poker.py" and "Manager" in f.message
               for f in new)


# ------------------------------------------------------------ metric-names
def test_metric_names_bad(tmp_path):
    src = 'registry.counter("train.bogus").inc()\n'
    new = _lint(tmp_path, {"paddle_tpu/mod.py": src},
                select=["metric-names"], with_schema=True)
    assert any("train.bogus" in f.message and f.rule == "metric-names"
               for f in new)


def test_metric_names_good(tmp_path):
    src = ('registry.counter("train.steps").inc()\n'
           'with span("train.step"):\n    pass\n')
    new = _lint(tmp_path, {"paddle_tpu/mod.py": src},
                select=["metric-names"], with_schema=True)
    assert new == []


def test_metric_names_kind_mismatch(tmp_path):
    src = 'registry.gauge("train.steps").set(1)\n'
    new = _lint(tmp_path, {"paddle_tpu/mod.py": src},
                select=["metric-names"], with_schema=True)
    assert any("declared as a counter" in f.message for f in new)


# ----------------------------------------------------------- host-transfer
BAD_HOST_TRANSFER = """\
    import numpy as np

    import jax

    def stage(params, x):
        x = np.asarray(x)            # host copy of the boundary tensor
        scale = x.max().item()       # host sync
        return jax.device_get(x) * scale

    pipe = CompiledPipeline(stage, [], lambda e, h, y: h.sum(),
                            num_stages=2, num_micro=4)
    """

GOOD_HOST_TRANSFER = """\
    import numpy as np

    import jax.numpy as jnp

    def stage(params, x):
        return jnp.tanh(x @ params[0])

    def host_driver(batch):
        # orchestration code may touch host freely: not a stage body
        return np.asarray(batch).item()

    pipe = CompiledPipeline(stage, [], lambda e, h, y: h.sum(),
                            num_stages=2, num_micro=4)
    """


def test_host_transfer_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_HOST_TRANSFER},
                select=["host-transfer"])
    assert _rules(new) == ["host-transfer"]
    msgs = " ".join(f.message for f in new)
    assert "np.asarray" in msgs and ".item()" in msgs \
        and "jax.device_get" in msgs


def test_host_transfer_good(tmp_path):
    assert _lint(tmp_path, {"mod.py": GOOD_HOST_TRANSFER},
                 select=["host-transfer"]) == []


def test_host_transfer_transitive_callee(tmp_path):
    src = """\
        import numpy as np

        def _helper(x):
            return np.asarray(x)

        def stage(params, x):
            return _helper(x) * 2

        prog = StagedProgram([stage], [[]], None)
        """
    new = _lint(tmp_path, {"mod.py": src}, select=["host-transfer"])
    assert any("_helper" in f.message and "np.asarray" in f.message
               for f in new)


def test_host_transfer_rpc_payload(tmp_path):
    src = """\
        def stage_fn(params, x):
            rpc_async("peer", deliver, args=(x,))
            return x

        pipe = CompiledPipeline(stage_fn=stage_fn, stages=2)
        """
    new = _lint(tmp_path, {"mod.py": src}, select=["host-transfer"])
    assert any("rpc" in f.message for f in new)


# ------------------------------------------------------------ unfused-chain
BAD_UNFUSED_CHAIN = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def mlp(x, w, b, mask, scale):
        # 4-op inline epilogue: where + gelu + add + mul
        return jnp.where(mask, jax.nn.gelu(x @ w + b), 0.0) * scale

    @jax.jit
    def swiglu(x, wg, wu, r):
        # 3-op inline epilogue: silu + mul + add
        return jax.nn.silu(x @ wg) * (x @ wu) + r
    """

GOOD_UNFUSED_CHAIN = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def mlp(x, w, b):
        h = x @ w + b          # one elementwise op per statement
        return jax.nn.gelu(h)  # 2-op composition: under threshold

    @jax.jit
    def gate(x, wg, wu):
        return jax.nn.silu(x @ wg) * (x @ wu)  # the fused helper's own 2-op core

    def host_metrics(x, mask, scale):
        # not jit-traced: host-side chains are out of scope
        return jnp.where(mask, jax.nn.gelu(x + 1.0), 0.0) * scale
    """


def test_unfused_chain_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_UNFUSED_CHAIN},
                select=["unfused-chain"])
    assert _rules(new) == ["unfused-chain"]
    assert len(new) == 2
    msgs = " ".join(f.message for f in new)
    assert "linear_gelu" in msgs and "swiglu_linear" in msgs


def test_unfused_chain_good(tmp_path):
    assert _lint(tmp_path, {"mod.py": GOOD_UNFUSED_CHAIN},
                 select=["unfused-chain"]) == []


def test_unfused_chain_transitive_callee(tmp_path):
    src = """\
        import jax
        import jax.numpy as jnp

        def _epilogue(h, mask, scale):
            return jnp.where(mask, jax.nn.gelu(h + 1.0), 0.0) * scale

        @jax.jit
        def step(x, mask, scale):
            return _epilogue(x, mask, scale)
        """
    new = _lint(tmp_path, {"mod.py": src}, select=["unfused-chain"])
    assert any("_epilogue" in f.message for f in new)


def test_unfused_chain_fusion_package_exempt(tmp_path):
    # the fused implementations compose these ops by design
    assert _lint(tmp_path,
                 {"paddle_tpu/fusion/epilogues.py": BAD_UNFUSED_CHAIN},
                 select=["unfused-chain"]) == []


# ------------------------------------------------------- serial-collective
BAD_SERIAL_COLLECTIVE = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def row_parallel(x, w):
        # literal matmul nested in the collective call
        return jax.lax.psum(jnp.matmul(x, w), "mp")

    @jax.jit
    def scatter_out(x, w):
        # matmul bound by the immediately preceding statement
        h = jnp.matmul(x, w)
        return jax.lax.psum_scatter(h, "mp", scatter_dimension=0,
                                    tiled=True)
    """

GOOD_SERIAL_COLLECTIVE = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def with_work_between(x, w, b):
        h = jnp.matmul(x, w)
        h = jax.nn.gelu(h + b)     # real work hides the collective
        return jax.lax.psum(h, "mp")

    @jax.jit
    def gather_input(x, w):
        # collective feeds the matmul, not the other way around
        return jnp.matmul(jax.lax.all_gather(x, "mp", tiled=True), w)

    def host_side(x, w):
        # not jit-traced: out of scope
        return jax.lax.psum(jnp.matmul(x, w), "mp")
    """


def test_serial_collective_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_SERIAL_COLLECTIVE},
                select=["serial-collective"])
    assert _rules(new) == ["serial-collective"]
    assert len(new) == 2
    msgs = " ".join(f.message for f in new)
    assert "overlap_mm" in msgs and "matmul_reduce_scatter" in msgs


def test_serial_collective_good(tmp_path):
    assert _lint(tmp_path, {"mod.py": GOOD_SERIAL_COLLECTIVE},
                 select=["serial-collective"]) == []


def test_serial_collective_fusion_package_exempt(tmp_path):
    # the decomposed implementations are allowed their own ring steps
    assert _lint(tmp_path,
                 {"paddle_tpu/fusion/overlap_mm.py": BAD_SERIAL_COLLECTIVE},
                 select=["serial-collective"]) == []


# ------------------------------------------------------------- suppression
def test_line_suppression(tmp_path):
    src = """\
        import jax

        @jax.jit
        def step(x):
            print(x)  # ptlint: disable=jit-purity
            return x
        """
    assert _lint(tmp_path, {"mod.py": src}, select=["jit-purity"]) == []


def test_file_suppression(tmp_path):
    src = "# ptlint: disable-file=jit-purity\n" + textwrap.dedent(
        BAD_JIT_PURITY)
    assert _lint(tmp_path, {"mod.py": src}, select=["jit-purity"]) == []


def test_suppression_is_per_rule(tmp_path):
    # disabling an unrelated rule must NOT silence the finding
    src = """\
        import jax

        @jax.jit
        def step(x):
            print(x)  # ptlint: disable=recompile-hazard
            return x
        """
    new = _lint(tmp_path, {"mod.py": src}, select=["jit-purity"])
    assert _rules(new) == ["jit-purity"]


# ---------------------------------------------------------------- baseline
def _write_and_collect(tmp_path, src):
    (tmp_path / "mod.py").write_text(textwrap.dedent(src))
    files = collect_files([str(tmp_path)], str(tmp_path))
    return run_passes(files, str(tmp_path), ["jit-purity"])


def test_baseline_filters_known_findings(tmp_path):
    findings = _write_and_collect(tmp_path, BAD_JIT_PURITY)
    assert findings
    entries = [{"rule": f.rule, "path": f.path, "message": f.message}
               for f in findings]
    new, baselined, stale = apply_baseline(findings, entries)
    assert new == []
    assert len(baselined) == len(findings)
    assert stale == []


def test_baseline_survives_line_moves(tmp_path):
    # identity is (rule, path, message): adding lines above the finding
    # must not un-baseline it
    findings = _write_and_collect(tmp_path, BAD_JIT_PURITY)
    entries = [{"rule": f.rule, "path": f.path, "message": f.message}
               for f in findings]
    moved = _write_and_collect(tmp_path, "x = 1\ny = 2\n\n"
                               + textwrap.dedent(BAD_JIT_PURITY))
    assert any(f.line != findings[0].line for f in moved)
    new, baselined, _ = apply_baseline(moved, entries)
    assert new == []
    assert len(baselined) == len(moved)


def test_baseline_stale_entry_detected(tmp_path):
    findings = _write_and_collect(tmp_path, GOOD_JIT_PURITY)
    assert findings == []
    ghost = [{"rule": "jit-purity", "path": "mod.py",
              "message": "long-since-fixed finding"}]
    new, baselined, stale = apply_baseline(findings, ghost)
    assert (new, baselined) == ([], [])
    assert stale == ghost


def test_baseline_file_roundtrip(tmp_path):
    findings = _write_and_collect(tmp_path, BAD_JIT_PURITY)
    bl = tmp_path / "baseline.json"
    from tools.ptlint.engine import load_baseline, write_baseline

    write_baseline(str(bl), findings)
    entries = load_baseline(str(bl))
    new, baselined, stale = apply_baseline(findings, entries)
    assert new == [] and stale == []
    assert len(baselined) == len(findings)


# --------------------------------------------------------------- CLI
def _run_cli(args):
    return subprocess.run(
        [sys.executable, "-m", "tools.ptlint"] + args,
        cwd=ROOT, capture_output=True, text=True)


def test_cli_exit_zero_on_clean_fixture(tmp_path):
    p = tmp_path / "good.py"
    p.write_text(textwrap.dedent(GOOD_JIT_PURITY))
    r = _run_cli([str(p), "--no-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_exit_one_on_findings(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(BAD_JIT_PURITY))
    r = _run_cli([str(p), "--no-baseline"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "[jit-purity]" in r.stdout


def test_cli_exit_two_on_bad_usage(tmp_path):
    r = _run_cli([str(tmp_path / "no_such_file.py")])
    assert r.returncode == 2
    r = _run_cli(["--select", "not-a-rule"])
    assert r.returncode == 2


def test_cli_json_report(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(BAD_JIT_PURITY))
    r = _run_cli([str(p), "--no-baseline", "--json"])
    assert r.returncode == 1
    data = json.loads(r.stdout)
    assert data["findings"] and data["files_checked"] == 1
    assert data["findings"][0]["rule"] == "jit-purity"


def test_cli_list_rules():
    r = _run_cli(["--list-rules"])
    assert r.returncode == 0
    for rule in ("jit-purity", "recompile-hazard",
                 "collective-consistency", "lock-discipline",
                 "metric-names"):
        assert rule in r.stdout


def test_parse_error_is_reported(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    new, _, _ = lint([str(p)], root=str(tmp_path), baseline_path=None)
    assert any(f.rule == "parse-error" for f in new)


# =====================================================================
# protocol passes (PR 20): per-rule good/bad fixtures
# =====================================================================

# minimal stand-ins for the stdlib-only registries the protocol passes
# importlib-load from the lint root
_KEYSPACE_SRC = """\
    from typing import NamedTuple, Tuple

    class KeyNamespace(NamedTuple):
        name: str
        pattern: Tuple[str, ...]
        deletable: bool
        fenced: bool
        doc: str

    NAMESPACES = (
        KeyNamespace("beat", ("<ns>", "beat", "<member>"), True, True,
                     "heartbeat doc"),
        KeyNamespace("left", ("<ns>", "left", "<member>"), True, False,
                     "clean-leave marker"),
    )
    HELPERS = frozenset(n.name for n in NAMESPACES)

    def beat(ns, member):
        return "%s/beat/%s" % (ns, member)

    def left(ns, member):
        return "%s/left/%s" % (ns, member)

    def check_collisions():
        return []
    """

_FAULT_SITES_SRC = """\
    from typing import NamedTuple

    class Site(NamedTuple):
        name: str
        subsystem: str
        doc: str

    SITES = {"cp.lease": Site("cp.lease", "cp", "one lease write")}
    """

_KNOBS_SRC = """\
    from typing import Any, NamedTuple

    class Knob(NamedTuple):
        name: str
        type: str
        default: Any
        subsystem: str
        doc: str

    KNOBS = (Knob("PADDLE_TPU_FOO", "int", 1, "test", "a knob"),)

    def iter_knobs():
        return KNOBS
    """

_KEYSPACE_REL = "paddle_tpu/distributed/control_plane/keyspace.py"
_FAULT_SITES_REL = "paddle_tpu/distributed/resilience/fault_sites.py"
_KNOBS_REL = "paddle_tpu/config/knobs.py"


# --------------------------------------------------------- thread-escape
BAD_THREAD_ESCAPE = """\
    import threading

    class Pump:
        def __init__(self):
            self.items = []
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                self.items.append(1)

        def drain(self):
            out = list(self.items)
            self.items.clear()
            return out
    """

GOOD_THREAD_ESCAPE = """\
    import threading

    class Pump:
        def __init__(self):
            self.items = []
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                with self._lock:
                    self.items.append(1)

        def drain(self):
            with self._lock:
                out = list(self.items)
                self.items.clear()
            return out
    """


def test_thread_escape_bad(tmp_path):
    new = _lint(tmp_path, {"mod.py": BAD_THREAD_ESCAPE},
                select=["thread-escape"])
    assert _rules(new) == ["thread-escape"]
    assert any("items" in f.message for f in new)


def test_thread_escape_good(tmp_path):
    assert _lint(tmp_path, {"mod.py": GOOD_THREAD_ESCAPE},
                 select=["thread-escape"]) == []


# ----------------------------------------------------------- store-keys
BAD_STORE_KEYS = """\
    class Membership:
        def __init__(self, store, ns):
            self.store = store
            self.ns = ns

        def beat_key(self, rank):
            return f"{self.ns}/beat/{rank}"

        def mark_left(self, rank):
            self.store.set(f"{self.ns}/left/{rank}", b"1")
    """

GOOD_STORE_KEYS = """\
    from ..control_plane import keyspace as ks

    class Membership:
        def __init__(self, store, ns):
            self.store = store
            self.ns = ns

        def mark_left(self, rank):
            self.store.set(ks.left(self.ns, rank), b"1")
    """


def test_store_keys_bad(tmp_path):
    new = _lint(
        tmp_path,
        {_KEYSPACE_REL: _KEYSPACE_SRC,
         "paddle_tpu/distributed/elastic/member.py": BAD_STORE_KEYS},
        select=["store-keys"])
    assert _rules(new) == ["store-keys"]
    # both the inline key at the store op and the shadow builder
    assert len(new) >= 2


def test_store_keys_good(tmp_path):
    assert _lint(
        tmp_path,
        {_KEYSPACE_REL: _KEYSPACE_SRC,
         "paddle_tpu/distributed/elastic/member.py": GOOD_STORE_KEYS},
        select=["store-keys"]) == []


def test_store_keys_out_of_scope_file_ignored(tmp_path):
    # rendezvous/bootstrap tiers are deliberately out of scope
    assert _lint(
        tmp_path,
        {_KEYSPACE_REL: _KEYSPACE_SRC,
         "paddle_tpu/distributed/rendezvous.py": BAD_STORE_KEYS},
        select=["store-keys"]) == []


# ----------------------------------------------------- fence-discipline
BAD_FENCE = """\
    import json
    from . import keyspace as ks

    class LeaseTable:
        def __init__(self, store, ns):
            self.store = store
            self.ns = ns

        def write_beat(self, member):
            payload = {"t": 1.0}
            self.store.set(ks.beat(self.ns, member),
                           json.dumps(payload).encode())

        def read_left(self, member):
            return self.store.get(ks.left(self.ns, member))
    """

GOOD_FENCE = """\
    import json
    from . import keyspace as ks
    from .store_util import try_get

    class LeaseTable:
        def __init__(self, store, ns):
            self.store = store
            self.ns = ns

        def write_beat(self, member, gen):
            payload = {"t": 1.0, "gen": gen}
            self.store.set(ks.beat(self.ns, member),
                           json.dumps(payload).encode())

        def read_left(self, member):
            return try_get(self.store, ks.left(self.ns, member))
    """


def test_fence_discipline_bad(tmp_path):
    new = _lint(
        tmp_path,
        {_KEYSPACE_REL: _KEYSPACE_SRC,
         "paddle_tpu/distributed/control_plane/lease.py": BAD_FENCE},
        select=["fence-discipline"])
    assert _rules(new) == ["fence-discipline"]
    msgs = " ".join(f.message for f in new)
    assert "gen" in msgs          # unfenced write on the beat namespace
    assert "try_get" in msgs      # raw get on a deletable namespace


def test_fence_discipline_good(tmp_path):
    assert _lint(
        tmp_path,
        {_KEYSPACE_REL: _KEYSPACE_SRC,
         "paddle_tpu/distributed/control_plane/lease.py": GOOD_FENCE},
        select=["fence-discipline"]) == []


# ---------------------------------------------------------- fault-sites
BAD_FAULT_SITES = """\
    from ..resilience import faults

    def lease_write(store, key, doc):
        act = faults.check("cp.laese")
        if act is not None:
            faults.apply(act)
        store.set(key, doc)
    """

GOOD_FAULT_SITES = """\
    from ..resilience import faults

    def lease_write(store, key, doc):
        act = faults.check("cp.lease")
        if act is not None:
            faults.apply(act)
        store.set(key, doc)
    """

_DRILL_TEST_SRC = """\
    def test_lease_drop_drill():
        # exercises the cp.lease site: "cp.lease:drop@1"
        pass
    """


def test_fault_sites_bad_typo(tmp_path):
    new = _lint(
        tmp_path,
        {_FAULT_SITES_REL: _FAULT_SITES_SRC,
         "tests/test_drill.py": _DRILL_TEST_SRC,
         "paddle_tpu/distributed/control_plane/lease.py":
             BAD_FAULT_SITES},
        select=["fault-sites"])
    assert any("cp.laese" in f.message for f in new)


def test_fault_sites_bad_untested_site(tmp_path):
    # declared site, no tests/ reference -> dead registry row
    new = _lint(
        tmp_path,
        {_FAULT_SITES_REL: _FAULT_SITES_SRC,
         "paddle_tpu/distributed/control_plane/lease.py":
             GOOD_FAULT_SITES},
        select=["fault-sites"])
    assert any("referenced by no test" in f.message for f in new)


def test_fault_sites_good(tmp_path):
    assert _lint(
        tmp_path,
        {_FAULT_SITES_REL: _FAULT_SITES_SRC,
         "tests/test_drill.py": _DRILL_TEST_SRC,
         "paddle_tpu/distributed/control_plane/lease.py":
             GOOD_FAULT_SITES},
        select=["fault-sites"]) == []


# ------------------------------------------------------------ env-knobs
BAD_ENV_KNOBS = """\
    import os
    from ..config import knobs

    def tier():
        raw = os.environ.get(
            "PADDLE_TPU_FOO")
        typo = knobs.get_int("PADDLE_TPU_TYPO")
        return raw, typo
    """

GOOD_ENV_KNOBS = """\
    from ..config import knobs

    def tier():
        return knobs.get_int("PADDLE_TPU_FOO")
    """


def test_env_knobs_bad(tmp_path):
    new = _lint(
        tmp_path,
        {_KNOBS_REL: _KNOBS_SRC,
         "paddle_tpu/serving/tiers.py": BAD_ENV_KNOBS},
        select=["env-knobs"])
    msgs = " ".join(f.message for f in new)
    assert "raw environment read" in msgs
    assert "PADDLE_TPU_TYPO" in msgs


def test_env_knobs_good(tmp_path):
    assert _lint(
        tmp_path,
        {_KNOBS_REL: _KNOBS_SRC,
         "paddle_tpu/serving/tiers.py": GOOD_ENV_KNOBS},
        select=["env-knobs"]) == []


def test_env_knobs_dead_row(tmp_path):
    # declared but never read anywhere -> finding on the registry
    new = _lint(
        tmp_path,
        {_KNOBS_REL: _KNOBS_SRC,
         "paddle_tpu/serving/tiers.py": "X = 1\n"},
        select=["env-knobs"])
    assert any("never read" in f.message for f in new)


# ------------------------------- metric-names: schema-derived namespaces
_SCHEMA_NS_SRC = """\
    from typing import NamedTuple, Optional, Tuple

    class MetricSpec(NamedTuple):
        kind: str
        unit: str
        desc: str
        buckets: Optional[Tuple[float, ...]] = None
        tags: Tuple[str, ...] = ()

    class NamespaceSpec(NamedTuple):
        doc: str
        require_used: bool = True

    NAMESPACES = {
        "train": NamespaceSpec("training", require_used=False),
        "serving": NamespaceSpec("serving"),
    }
    METRICS = {
        "train.steps": MetricSpec("counter", "steps", "steps run"),
        "serving.requests": MetricSpec("counter", "reqs", "requests"),
        "typo.rows": MetricSpec("counter", "rows", "bad namespace"),
    }
    SPANS = {}
    """


def test_metric_names_namespace_table(tmp_path):
    new = _lint(
        tmp_path,
        {"paddle_tpu/observability/metrics_schema.py": _SCHEMA_NS_SRC,
         "mod.py": "X = 1\n"},
        select=["metric-names"])
    msgs = " ".join(f.message for f in new)
    # require_used namespace with a dead row -> finding; the
    # require_used=False namespace is declaration-only
    assert "serving.requests" in msgs
    assert "train.steps" not in msgs
    # a key whose namespace is missing from NAMESPACES -> finding
    assert "typo" in msgs


# ------------------------- lock-discipline: stale-annotation detection
STALE_GUARDED_BY = """\
    import threading

    class T:
        def __init__(self):
            self._lock = threading.Lock()
            self._tasks = {}  # guarded by: _mu

        def get(self, k):
            with self._lock:
                return self._tasks.get(k)
    """

STALE_HOLDS = """\
    import threading

    class T:
        def __init__(self):
            self._lock = threading.Lock()
            self._tasks = {}  # guarded by: _lock

        def _emit(self, k):  # ptlint: holds=_mu
            return self._tasks.get(k)
    """


def test_lock_discipline_stale_guarded_by(tmp_path):
    new = _lint(tmp_path, {"mod.py": STALE_GUARDED_BY},
                select=["lock-discipline"])
    assert any("stale" in f.message and "_mu" in f.message
               for f in new)


def test_lock_discipline_stale_holds(tmp_path):
    new = _lint(tmp_path, {"mod.py": STALE_HOLDS},
                select=["lock-discipline"])
    assert any("stale holds" in f.message for f in new)


# -------------------- property: holds= chains never false-positive
def test_holds_chains_never_flag_thread_escape(tmp_path):
    """Randomized property: a field only ever touched under the lock —
    lexically in the thread entry, via `# ptlint: holds=` declarations
    down arbitrary helper chains on the unthreaded side — must never
    be a thread-escape finding, whatever the chain shape."""
    import random

    rng = random.Random(0xA11CE)
    for trial in range(25):
        depth = rng.randint(1, 5)
        n_fields = rng.randint(1, 3)
        fields = [f"f{i}" for i in range(n_fields)]
        lines = ["import threading", "", "class C:",
                 "    def __init__(self):"]
        for f in fields:
            lines.append(f"        self.{f} = []")
        lines += ["        self._lock = threading.Lock()",
                  "        self._t = threading.Thread("
                  "target=self._loop, daemon=True)",
                  "        self._t.start()",
                  "",
                  "    def _loop(self):",
                  "        while True:",
                  "            with self._lock:"]
        for f in fields:
            lines.append(f"                self.{f}.append(1)")
        # unthreaded side: public() takes the lock, then a chain of
        # helpers each declaring holds=_lock; the deepest one mutates
        lines += ["", "    def public(self):",
                  "        with self._lock:",
                  "            self._h0()"]
        for d in range(depth):
            call = (f"self._h{d + 1}()" if d + 1 < depth else
                    "; ".join(f"self.{f}.append(2)" for f in fields))
            lines += ["", f"    def _h{d}(self):  "
                          "# ptlint: holds=_lock",
                      f"        {call}"]
        src = "\n".join(lines) + "\n"
        new = _lint(tmp_path / f"t{trial}", {"mod.py": src},
                    select=["thread-escape"])
        assert new == [], (
            f"trial {trial} (depth={depth}, fields={n_fields}) "
            "produced false positives:\n"
            + "\n".join(str(f) for f in new) + "\n---\n" + src)
