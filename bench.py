#!/usr/bin/env python
"""Flagship benchmark: GPT-3 single-chip full-training-step throughput.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": "tokens/s",
   "vs_baseline": MFU / 0.45}

vs_baseline is measured MFU over the north-star target (BASELINE.json:
>=45% MFU); >1.0 beats the target. The reference publishes no in-tree
numbers (BASELINE.md), so MFU-vs-north-star is the comparable scalar.

Headline config (round 3): GPT-3-1.3B, batch 8 x seq 1024, bf16 params,
AdamW with bf16 first moment + Adafactor-style factored second moment
(fp32 update math), fused chunked lm_head+CE (8 chunks), NO block
rematerialization — factoring the second moment frees the ~5.3GB that
remat was buying back, so the step does the true 6N FLOPs/token instead
of ~8N. Round-2 (full per-block remat, bf16 m, fp32 v) measured 0.397
MFU; this config measures ~0.62 on the same chip.

extra carries two sub-benches: a seq-2048 config (the round-2 weak spot:
0.30 then; ~0.56 now) and a STREAMING variant feeding fresh per-step
batches through run_steps_stream (proves the headline is reachable with a
live input pipeline, VERDICT r2 next #4).

MFU counts the standard 6N FLOPs/token.
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

from paddle_tpu.config import knobs as _knobs
from paddle_tpu.observability import stopwatch as _stopwatch


def _peak_flops(device):
    """Per-chip peak bf16 FLOP/s by TPU generation (public specs).
    Returns (flops, known: bool) — unknown TPU kinds fall back to the v5e
    number and are flagged so the MFU is never silently wrong."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v5 lite": 197e12,   # v5e
        "v5litepod": 197e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6e": 918e12,
        "v6 lite": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v, True
    if device.platform == "tpu":
        return 197e12, False
    return 0.0, True  # CPU: MFU not meaningful


def _build(pt, cfg, batch, seq, on_tpu, opt_kwargs):
    from paddle_tpu.jit import TrainStep

    pt.set_default_dtype("bfloat16" if on_tpu else "float32")
    try:
        model = pt.models.GPTForCausalLM(cfg)
    finally:
        pt.set_default_dtype("float32")
    opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                             parameters=model.parameters(), **opt_kwargs)
    step = TrainStep(model, opt, grad_clip_norm=1.0)
    rng = np.random.default_rng(0)
    ids = pt.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)),
                       dtype="int64")
    labels = pt.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)),
                          dtype="int64")
    return model, step, ids, labels


def _measure(step, ids, labels, iters):
    # run_steps chains N optimizer steps in ONE dispatch: the chip sits
    # behind a high-latency tunnel (~100ms/round-trip) and, on this
    # platform, block_until_ready can return before execution finishes —
    # a device->host scalar read (float()) is the only honest barrier.
    loss = step.run_steps(iters, ids, labels)   # warmup/compile
    float(loss)
    # telemetry stopwatch: identical perf_counter window (elapsed is
    # always measured); the observation lands in the registry only when
    # telemetry is enabled
    with _stopwatch("bench.train_window") as sw:
        loss = step.run_steps(iters, ids, labels)
        float(loss)                             # d2h barrier
    return sw.elapsed, loss


def _bench_decode(pt, cfg):
    """Serving decode tok/s: whole-generation compiled path, int8/int4
    weights + int8 KV (models/generation.py; reference surfaces:
    weight_only_linear int8/int4, masked_multihead_attention
    cache-quant args). Also one speculative-decode datapoint with its
    measured acceptance — on this RANDOM-INIT model acceptance is low,
    so the number is the mechanism's floor, not its trained-model
    value."""
    import numpy as np

    pt.set_default_dtype("bfloat16")
    try:
        model = pt.models.GPTForCausalLM(cfg)
    finally:
        pt.set_default_dtype("float32")
    model.eval()
    b, plen = 8, 128
    rng = np.random.default_rng(2)
    ids = pt.to_tensor(rng.integers(0, cfg.vocab_size, (b, plen))
                       .astype(np.int32))

    def timed_gen(new, **kw):
        out = model.generate(ids, max_new_tokens=new, **kw)
        _ = out.numpy()
        with _stopwatch("bench.decode_window") as sw:
            out = model.generate(ids, max_new_tokens=new, **kw)
            _ = out.numpy()
        return sw.elapsed

    res = {"batch": b, "prompt": plen}
    for tag, kw in (
            ("int8_kv8", {"weight_quant": "int8",
                          "kv_cache_quant": "int8"}),
            ("int4_kv8", {"weight_quant": "int4",
                          "kv_cache_quant": "int8"})):
        # two-point window 64 vs 192 new tokens: the delta isolates the
        # 128 decode steps at context 192..320 (per-step cost grows
        # with context, so both points must share the workload shape —
        # a wider second point would silently measure a heavier regime)
        t1 = timed_gen(64, **kw)
        t2 = timed_gen(192, **kw)
        per_step = (t2 - t1) / 128
        res[tag] = {"device_tokens_per_s": round(b / per_step, 1),
                    "ms_per_step": round(per_step * 1e3, 3)}

    # speculative decode: one raw datapoint + measured acceptance
    from paddle_tpu.models import speculative_generate

    kw = dict(weight_quant="int8", kv_cache_quant="int8", gamma=4,
              draft_layers=6, return_stats=True)
    out, _ = speculative_generate(model, ids, max_new_tokens=128, **kw)
    _ = out.numpy()
    with _stopwatch("bench.decode_window") as sw:
        out, st = speculative_generate(model, ids, max_new_tokens=128,
                                       **kw)
        _ = out.numpy()
    el = sw.elapsed
    res["speculative_int8"] = {
        "tokens_per_s_raw": round(b * 128 / el, 1),
        "mean_accepted": round(st["mean_accepted"], 3),
        "note": "random-init model: acceptance is the floor; exact-"
                "greedy contract is test-enforced",
    }
    del model
    return res


def _bench_moe():
    """Sorted-dispatch MoE FFN step (incubate/nn/pallas/moe_dispatch.py)
    on the chip — the driver-visible MoE entry (VERDICT r4 #5)."""
    import functools

    import jax
    import jax.lax as lax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.incubate.nn.pallas.moe_dispatch import moe_ffn_sorted

    S, M, DFF, E, K = 8192, 2048, 2816, 8, 2
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(S, M), jnp.bfloat16)
    probs = jax.nn.softmax(jnp.asarray(rng.randn(S, E), jnp.float32), -1)
    w1 = jnp.asarray(rng.randn(E, M, 2 * DFF) * 0.02, jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(E, DFF, M) * 0.02, jnp.bfloat16)

    # weights ride as jit ARGS — closure constants would be inlined
    # into the HLO upload (the tunnel rejects multi-MB compile bodies)
    @functools.partial(jax.jit, static_argnames="n")
    def chained(xx, pp, a, b2, n):
        def body(c, _):
            return moe_ffn_sorted(c, pp, a, b2, k=K).astype(c.dtype), \
                None

        out, _ = lax.scan(body, xx, None, length=n)
        return out

    def run(n):
        out = chained(x, probs, w1, w2, n=n)
        _ = np.asarray(out[:1, :1])
        with _stopwatch("bench.moe_window") as sw:
            out = chained(x, probs, w1, w2, n=n)
            _ = np.asarray(out[:1, :1])
        return sw.elapsed

    t1 = run(8)
    t3 = run(24)
    step = max(t3 - t1, 1e-9) / 16
    flops = 2 * S * K * M * 2 * DFF + 2 * S * K * DFF * M
    return {"tokens": S, "experts": E, "topk": K,
            "step_ms": round(step * 1e3, 3),
            "tflops": round(flops / step / 1e12, 2)}


def _bench_fusion(pt, on_tpu):
    """Operator-fusion sub-bench (paddle_tpu/fusion/): eager
    fused-vs-unfused step_ms per epilogue (one run_op region vs the
    op-by-op composition — same math, so the delta is dispatch count +
    intermediate HBM traffic), quantized-matmul on/off delta, and a
    tiny-GPT train-step fused-vs-``PADDLE_TPU_FUSION=off`` delta (the
    headline number above is the fused-on large-scale datapoint)."""
    import numpy as np

    import paddle_tpu.nn.functional as PF
    from paddle_tpu import fusion

    rng = np.random.default_rng(3)
    if on_tpu:
        B, D, H, reps = 4096, 2048, 8192, 20
    else:
        B, D, H, reps = 256, 256, 1024, 5

    def t(a):
        return pt.to_tensor(np.asarray(a, dtype=np.float32))

    x = t(rng.standard_normal((B, D)) * 0.1)
    w1 = t(rng.standard_normal((D, H)) * 0.02)
    b1 = t(np.zeros(H))
    wu = t(rng.standard_normal((D, H)) * 0.02)
    wn = t(np.ones(D))
    y = t(rng.standard_normal((B, D)) * 0.1)
    res_in = t(rng.standard_normal((B, D)) * 0.1)

    def timed(fn):
        fn().numpy()                     # warmup: compile eager kernels
        with _stopwatch("bench.fusion_window") as sw:
            out = None
            for _ in range(reps):
                out = fn()
            out.numpy()                  # d2h barrier
        return sw.elapsed / reps * 1e3

    pairs = {
        "bias_gelu": (
            lambda: fusion.linear_gelu(x, w1, b1),
            lambda: PF.gelu(PF.linear(x, w1, b1), approximate=True)),
        "swiglu": (
            lambda: fusion.swiglu_linear(x, w1, wu),
            lambda: PF.silu(pt.matmul(x, w1)) * pt.matmul(x, wu)),
        "add_rms_norm": (
            lambda: fusion.add_rms_norm(y, res_in, wn)[0],
            lambda: PF.rms_norm(res_in + y, weight=wn)),
        "dropout_add": (
            lambda: fusion.dropout_add(y, res_in, p=0.1, training=True),
            lambda: res_in + PF.dropout(y, p=0.1, training=True)),
    }
    out = {"mode": fusion.mode(), "mm_quant": fusion.mm_quant()}
    for name, (fused, unfused) in pairs.items():
        f_ms, u_ms = timed(fused), timed(unfused)
        out[name] = {"fused_ms": round(f_ms, 3),
                     "unfused_ms": round(u_ms, 3),
                     "speedup": round(u_ms / f_ms, 3) if f_ms else 0.0}

    dense_ms = timed(lambda: PF.linear(x, w1))
    quant = {"dense_ms": round(dense_ms, 3)}
    modes = ["int8"] + (["fp8"] if fusion.quant.fp8_supported() else [])
    for qm in modes:
        q_ms = timed(lambda qm=qm: fusion.quantized_linear(x, w1, mode=qm))
        quant[f"{qm}_ms"] = round(q_ms, 3)
        quant[f"{qm}_speedup"] = round(dense_ms / q_ms, 3) if q_ms else 0.0
    out["quant_matmul"] = quant

    # train-level fused-vs-off delta at tiny scale (bounded bench time)
    cfg = pt.models.gpt_tiny(dropout=0.0, attention_dropout=0.0)
    train = {}
    for tag, mode in (("fused", "on"), ("unfused", "off")):
        with fusion.override(fusion=mode, quant_mode="off"):
            _, stp, ids, labels = _build(pt, cfg, 2, 128, on_tpu, {})
            el, _ = _measure(stp, ids, labels, 2)
        train[f"{tag}_step_ms"] = round(el / 2 * 1e3, 2)
    train["speedup"] = round(
        train["unfused_step_ms"] / train["fused_step_ms"], 3) \
        if train["fused_step_ms"] else 0.0
    out["train_tiny"] = train
    return out


def _ragged_burst(pt, model, prompts, max_new, mode, slots, blocks,
                  trials=3):
    """Deterministic synchronous burst through one engine: submit every
    request up front (arrival stamped at submit), drive ``step()`` until
    drained, and read per-request TTFT straight off the request records
    (``first_token_at - arrival``). No threads, no sleeps — the same
    prompt set through ``ragged="on"`` vs ``"off"`` measures only the
    dispatch structure, which is what the ragged-vs-split comparison is
    about. Best-of-``trials`` on one warmed engine (the pool drains
    fully between bursts), so a single descheduled step doesn't decide
    the comparison."""
    import time

    eng = pt.serving.ServingEngine(model, ragged=mode, max_slots=slots,
                                   block_size=16, num_blocks=blocks,
                                   prefill_chunk=32)
    eng.warmup()                    # compiles paid outside the window
    best = None
    for _ in range(trials):
        rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.monotonic()
        steps = 0
        while eng.step():
            steps += 1
            assert steps < 100_000, "burst failed to drain"
        wall = time.monotonic() - t0
        ttfts, toks = [], 0
        for rid in rids:
            req = eng._requests[rid]
            ttfts.append(req.first_token_at - req.arrival)
            toks += len(req.generated)
            list(eng.stream(rid))   # drain queues so shutdown is clean
        run = {
            "tokens_per_s": round(toks / wall, 1) if wall else 0.0,
            "steps": steps, "wall_s": round(wall, 3),
            "ttft_p50_ms": round(
                1e3 * float(np.percentile(ttfts, 50)), 2),
            "ttft_p99_ms": round(
                1e3 * float(np.percentile(ttfts, 99)), 2),
        }
        if best is None or run["wall_s"] < best["wall_s"]:
            best = run
    eng.shutdown()
    return best


def _bench_serving_ragged(pt, cfg, model, on_tpu):
    """Ragged-vs-split sub-bench: the same deterministic burst (high
    arrival rate — everything arrives at t=0) through ``ragged="on"``
    and ``"off"`` engines across a max_slots sweep. Reports per-mode
    tokens/s and p50/p99 TTFT plus the aggregate speedup; the CPU smoke
    arm asserts the ragged path is no slower on either axis."""
    rng = np.random.default_rng(4321)
    if on_tpu:
        n_req, max_new, blocks, sweep = 32, 32, 2048, (4, 8, 16)
    else:
        # slots >= 4 so the decode tail can fill a useful fraction of
        # the fixed token budget — at 1-2 rows the padded XLA-fallback
        # step pays for tokens the split path never computes
        n_req, max_new, blocks, sweep = 8, 8, 256, (4, 8)
    prompts = [rng.integers(0, cfg.vocab_size,
                            int(rng.integers(8, 64))).tolist()
               for _ in range(n_req)]
    ragged = {"requests": n_req, "max_new_tokens": max_new,
              "seed": 4321, "sweep": {}}
    agg = {"on": [0.0, 0], "off": [0.0, 0]}   # wall_s, tokens
    p99s = {"on": [], "off": []}
    for slots in sweep:
        point = {}
        for mode in ("off", "on"):
            r = _ragged_burst(pt, model, prompts, max_new, mode,
                              slots, blocks)
            point[mode] = r
            agg[mode][0] += r["wall_s"]
            agg[mode][1] += int(r["tokens_per_s"] * r["wall_s"])
            p99s[mode].append(r["ttft_p99_ms"])
        point["speedup"] = round(
            point["on"]["tokens_per_s"] / point["off"]["tokens_per_s"],
            3) if point["off"]["tokens_per_s"] else 0.0
        ragged["sweep"]["slots_%d" % slots] = point
    on_tps = agg["on"][1] / agg["on"][0] if agg["on"][0] else 0.0
    off_tps = agg["off"][1] / agg["off"][0] if agg["off"][0] else 0.0
    ragged["on_tokens_per_s"] = round(on_tps, 1)
    ragged["off_tokens_per_s"] = round(off_tps, 1)
    ragged["speedup"] = round(on_tps / off_tps, 3) if off_tps else 0.0
    ragged["on_ttft_p99_ms"] = round(max(p99s["on"]), 2)
    ragged["off_ttft_p99_ms"] = round(max(p99s["off"]), 2)
    if not on_tpu:
        # smoke-arm guarantee: killing the dispatch seam never costs
        # throughput or tail TTFT, even on the XLA fallback path
        assert on_tps >= off_tps, \
            "ragged on slower than off: %.1f < %.1f" % (on_tps, off_tps)
        assert ragged["on_ttft_p99_ms"] <= ragged["off_ttft_p99_ms"], \
            "ragged on p99 TTFT worse than off: %.2f > %.2f" % (
                ragged["on_ttft_p99_ms"], ragged["off_ttft_p99_ms"])
    return ragged


def _slo_verdict(report):
    """Slim per-objective verdict for the bench JSON, read straight
    off an SLOEngine report — the SAME rolling windows the dashboard
    uses, no parallel bespoke math."""
    return {"state": report["state"],
            "objectives": {
                name: {"state": o["state"],
                       "value": round(o["value_slow"], 4),
                       "threshold": o["threshold"],
                       "burn_slow": round(o["burn_slow"], 2),
                       "samples": o["samples"]}
                for name, o in report["objectives"].items()}}


def _round_attribution(att):
    return {k: (round(v, 2) if isinstance(v, float) else v)
            for k, v in att.items()}


def _bench_serving():
    """Continuous-batching serving bench: seeded Poisson arrivals
    streamed through ServingEngine. Emits tokens/s plus p50/p99
    per-token latency and TTFT (JSON, same shape as the training
    bench), plus a ``ragged`` sub-object comparing the single ragged
    mixed prefill+decode dispatch against the legacy two-program path
    on a deterministic burst, plus the request-log latency attribution
    and rolling-window SLO verdicts. Off-TPU runs a tiny config to
    prove the path."""
    import threading
    import time

    import jax

    import paddle_tpu as pt

    # the serving arms run with telemetry ON: the attribution and SLO
    # sections below come from the request-scoped windows
    pt.observability.enable()
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = pt.models.gpt3_125M(dropout=0.0, attention_dropout=0.0)
        n_req, max_new, rate = 48, 64, 24.0
        slots, blocks, metric = 16, 2048, "serving_tokens_per_s_chip"
    else:
        cfg = pt.models.gpt_tiny(dropout=0.0, attention_dropout=0.0)
        n_req, max_new, rate = 10, 12, 50.0
        slots, blocks, metric = 4, 128, "serving_tokens_per_s_cpu_smoke"
    pt.seed(0)
    model = pt.models.GPTForCausalLM(cfg)
    model.eval()
    eng = pt.serving.ServingEngine(model, max_slots=slots, block_size=16,
                                   num_blocks=blocks, prefill_chunk=32)
    eng.start()
    rng = np.random.default_rng(1234)       # seeded arrival trace
    prompts = [rng.integers(0, cfg.vocab_size,
                            int(rng.integers(4, 48))).tolist()
               for _ in range(n_req)]
    gaps = rng.exponential(1.0 / rate, n_req)

    # warmup request pays the step compile(s) outside the timed window
    wid = eng.submit(prompts[0], max_new_tokens=4)
    for _ in eng.stream(wid):
        pass

    ttfts, tok_gaps = [], []
    lock = threading.Lock()

    def consume(rid, t_submit):
        last = None
        for _tok in eng.stream(rid):
            now = time.monotonic()
            with lock:
                if last is None:
                    ttfts.append(now - t_submit)
                else:
                    tok_gaps.append(now - last)
            last = now

    threads = []
    with _stopwatch("bench.serving_window") as sw:
        for p, g in zip(prompts, gaps):
            time.sleep(float(g))
            ts = time.monotonic()
            rid = eng.submit(p, max_new_tokens=max_new)
            th = threading.Thread(target=consume, args=(rid, ts))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
    wall = sw.elapsed
    compiles = eng.decode_compiles
    ragged_compiles = eng.ragged_compiles
    mode = eng.config.ragged
    preempts = eng.scheduler.preemptions
    attribution = _round_attribution(eng.request_log.attribution())
    slo = _slo_verdict(eng.slo.evaluate())
    snap_path = _knobs.get_str("PADDLE_TPU_OPS_SNAPSHOT")
    if snap_path:
        eng.dump_ops_snapshot(snap_path)
    eng.shutdown()
    ragged = _bench_serving_ragged(pt, cfg, model, on_tpu)
    total = n_req * max_new
    print(json.dumps({
        "metric": metric,
        "value": round(total / wall, 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "extra": {
            "requests": n_req, "max_new_tokens": max_new,
            "poisson_rate_req_per_s": rate,
            "arrival_rate_req_per_s": rate, "seed": 1234,
            "slots": slots, "wall_s": round(wall, 3),
            "ttft_p50_ms": round(1e3 * float(np.percentile(ttfts, 50)), 2),
            "ttft_p99_ms": round(1e3 * float(np.percentile(ttfts, 99)), 2),
            "token_latency_p50_ms": round(
                1e3 * float(np.percentile(tok_gaps, 50)), 2),
            "token_latency_p99_ms": round(
                1e3 * float(np.percentile(tok_gaps, 99)), 2),
            "decode_compiles": compiles,
            "ragged_compiles": ragged_compiles,
            "ragged_mode": mode, "preemptions": preempts,
            "shed": 0,      # single engine, no admission control
            "ragged": ragged,
            "attribution": attribution,
            "slo": slo,
        },
    }))
    return 0


def _bench_cluster():
    """Multi-replica cluster bench: seeded Poisson arrivals swept
    across offered rates into saturation through the prefix-affinity
    router. Emits the saturated aggregate tokens/s plus a degradation
    curve — per sweep point: achieved tokens/s, p50/p99 TTFT, shed
    rate, preemptions. Rates auto-scale off a measured capacity probe
    (1 replica vs N), so the curve shape is machine-independent:
    graceful degradation means p99 TTFT stays bounded and shed rate
    rises smoothly past 1.0x offered load, with no cliff.

    A second phase (``extra["ramp"]``) drives the control-plane +
    Autoscaler loop end to end: a seeded Poisson wave at ~2.5x ONE
    replica's capacity into a pool that starts at a single replica,
    with a seeded mid-wave ``hang``. See :func:`_cluster_ramp`."""
    import threading
    import time

    import jax

    import paddle_tpu as pt
    from paddle_tpu.serving.cluster import (ClusterRouter, Overloaded,
                                            Replica)

    # telemetry ON: attribution + SLO verdicts read the request-scoped
    # rolling windows of the long-lived sweep router
    pt.observability.enable()
    on_tpu = jax.devices()[0].platform == "tpu"
    host_cores = len(os.sched_getaffinity(0)) \
        if hasattr(os, "sched_getaffinity") else (os.cpu_count() or 1)
    n_rep = _knobs.get_int("PADDLE_TPU_CLUSTER_REPLICAS")
    if on_tpu:
        cfg = pt.models.gpt3_125M(dropout=0.0, attention_dropout=0.0)
        n_req, max_new = 48, 64
        slots, blocks = 16, 2048
        metric = "cluster_tokens_per_s_chip"
    else:
        cfg = pt.models.gpt_tiny(dropout=0.0, attention_dropout=0.0)
        n_req, max_new = 24, 10
        slots, blocks = 4, 256
        metric = "cluster_tokens_per_s_cpu_smoke"
    pt.seed(0)
    model = pt.models.GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(1234)       # seeded arrival trace

    def mk_router(n, max_queue=None):
        reps = [Replica("r%d" % i, model, max_slots=slots,
                        block_size=16, num_blocks=blocks,
                        prefill_chunk=32) for i in range(n)]
        for r in reps:
            r.warmup()                      # compiles outside any window
        return ClusterRouter(reps, max_queue=max_queue)

    def mk_prompts(n):
        return [rng.integers(0, cfg.vocab_size,
                             int(rng.integers(4, 32))).tolist()
                for _ in range(n)]

    # --- capacity probe: all requests offered at once (saturated);
    # best of two trials — peak sustainable rate, not a noisy single
    def capacity(n):
        best = 0.0
        for _ in range(2):
            router = mk_router(n)
            router.start()
            crids = [router.submit(p, max_new_tokens=max_new)
                     for p in mk_prompts(n_req)]
            t0 = time.monotonic()
            toks = sum(len(router.result(c)) for c in crids)
            wall = time.monotonic() - t0
            router.shutdown()
            best = max(best, toks / wall)
        return best

    cap1 = capacity(1)
    capn = capacity(n_rep) if n_rep > 1 else cap1
    cap_req = capn / max_new                # capacity in requests/s

    # --- rate sweep into saturation on one long-lived router; the
    # tight per-replica queue bound is what makes overload shed
    # (typed Overloaded) instead of growing an unbounded backlog
    router = mk_router(n_rep, max_queue=2)
    sweep = []
    for offered in (0.4, 0.8, 1.5, 3.0, 6.0):
        rate = offered * cap_req
        prompts = mk_prompts(n_req)
        due = np.cumsum(rng.exponential(1.0 / rate, n_req))
        ttfts, toks, shed = [], [0], 0
        lock = threading.Lock()

        def consume(crid, t_submit):
            first = True
            for _tok in router.stream(crid):
                with lock:
                    if first:
                        ttfts.append(time.monotonic() - t_submit)
                        first = False
                    toks[0] += 1

        pre0 = sum(r.engine.scheduler.preemptions
                   for r in router.replicas)
        threads = []
        # single-threaded load generator: the SAME loop submits due
        # arrivals (absolute-clock: falling behind the Poisson schedule
        # bursts, never stretches the trace) and steps the replicas, so
        # offered-vs-service is pure queueing — a GIL-starved submit
        # thread can't silently throttle the offered load. Consumers
        # only drain finished tokens off the stream queues.
        with _stopwatch("bench.cluster_window") as sw:
            t_start = time.monotonic()
            i = 0
            while True:
                now = time.monotonic() - t_start
                while i < n_req and float(due[i]) <= now:
                    ts = time.monotonic()
                    try:
                        crid = router.submit(prompts[i],
                                             max_new_tokens=max_new)
                        th = threading.Thread(target=consume,
                                              args=(crid, ts))
                        th.start()
                        threads.append(th)
                    except Overloaded:
                        shed += 1
                    i += 1
                busy = router.step()
                if not busy:
                    if i >= n_req:
                        break
                    left = t_start + float(due[i]) - time.monotonic()
                    if left > 0:
                        time.sleep(min(left, 0.01))
            for th in threads:
                th.join()
        pre = sum(r.engine.scheduler.preemptions
                  for r in router.replicas) - pre0
        pct = (lambda q: round(
            1e3 * float(np.percentile(ttfts, q)), 2)) if ttfts else \
            (lambda q: None)
        sweep.append({
            "offered_x_capacity": offered,
            "arrival_rate_req_per_s": round(rate, 2),
            "tokens_per_s": round(toks[0] / sw.elapsed, 1),
            "ttft_p50_ms": pct(50), "ttft_p99_ms": pct(99),
            "shed": shed, "shed_rate": round(shed / n_req, 3),
            "preemptions": pre,
        })
    # one merged snapshot over router + all replica windows, taken
    # while the sweep router is still live; optionally dumped for
    # ptop --snapshot
    snap = router.ops_snapshot()
    attribution = _round_attribution(snap["attribution"])
    slo = _slo_verdict(snap["slo"])
    snap_path = _knobs.get_str("PADDLE_TPU_OPS_SNAPSHOT")
    if snap_path:
        from paddle_tpu.observability.request_log import write_snapshot
        write_snapshot(snap, snap_path)
    router.shutdown()

    # --- ramp phase: the autoscaled pool under a traffic wave plus a
    # silent replica hang (lease eviction + token-exact replay)
    ramp = _cluster_ramp(pt, model, cfg, rng, slots=slots,
                         blocks=blocks, n_req=n_req, max_new=max_new,
                         cap1=cap1)

    # --- cluster-wide KV cache: long-shared-prefix workload, tier-on
    # vs tier-off (cross-replica index fetch + host-tier restore)
    kv_store = _cluster_kv(pt, model, cfg, rng, slots=slots,
                           blocks=blocks, max_new=max_new)

    print(json.dumps({
        "metric": metric,
        "value": round(capn, 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "extra": {
            "replicas": n_rep, "requests_per_point": n_req,
            "max_new_tokens": max_new, "seed": 1234,
            "slots": slots, "max_queue": 2,
            "host_cores": host_cores,
            "capacity_1rep_tokens_per_s": round(cap1, 1),
            "capacity_tokens_per_s": round(capn, 1),
            "scaling_x": round(capn / cap1, 2) if cap1 else 0.0,
            # concurrent wall-clock scaling needs one core/chip per
            # replica; on a smaller host the replicas time-share the
            # device and scaling_x is pinned near 1.0 by physics
            "scaling_bound_by_host": host_cores < n_rep and not on_tpu,
            "sweep": sweep,
            "attribution": attribution,
            "slo": slo,
            "ramp": ramp,
            "kv_store": kv_store,
        },
    }))
    return 0


def _cluster_ramp(pt, model, cfg, rng, slots, blocks, n_req, max_new,
                  cap1):
    """Autoscale ramp scenario: a seeded Poisson traffic wave offered
    at ~2.5x ONE replica's measured capacity into a pool that starts
    at a single replica behind the shared control plane. Exercises the
    full elastic serving loop on the wall clock:

    * queue pressure, sustained -> scale-out with warm joins (every
      spawned replica must still show exactly ONE ragged compile),
    * a seeded mid-wave ``hang`` — the replica goes silent without
      reporting, so only the missed-lease scan can find it — followed
      by eviction inside the lease budget and token-exact replay of
      its in-flight work onto survivors,
    * the idle tail after the wave -> scale-in back to one replica.

    Token exactness and the recovery bound are asserted (greedy
    decoding makes both deterministic); latency numbers are recorded,
    not asserted, so the bench stays machine-independent. Returns the
    ``extra["ramp"]`` record.
    """
    import threading
    import time

    from paddle_tpu.distributed.resilience import faults
    from paddle_tpu.observability.slo import BURN
    from paddle_tpu.serving.cluster import (AutoscaleConfig, Autoscaler,
                                            ClusterControlPlane,
                                            ClusterRouter, Replica)

    knobs = dict(max_slots=slots, block_size=16, num_blocks=blocks,
                 prefill_chunk=32)
    prompts = [rng.integers(0, cfg.vocab_size,
                            int(rng.integers(4, 32))).tolist()
               for _ in range(n_req)]

    # greedy references through a single engine (token-exact vs
    # generate() by the serve_smoke invariant) — what the wave must
    # reproduce no matter how the pool scales or fails underneath
    ref = pt.serving.ServingEngine(model, **knobs)
    rrids = [ref.submit(p, max_new_tokens=max_new) for p in prompts]
    while ref.step():
        pass
    refs = [ref.result(r) for r in rrids]
    ref.shutdown()

    lease_s = 1.0
    cp = ClusterControlPlane(lease_timeout=lease_s)
    spawned = []

    # warm standbys, compiled BEFORE the wave: in this single-threaded
    # loop a mid-wave cold compile would stall every replica's beats
    # past the lease and the scan would evict the whole pool (a real
    # warm pool keeps joins off the serving threads the same way)
    standby = [Replica("r%d" % i, model, **knobs) for i in (1, 2, 3)]
    for r in standby:
        r.warmup()

    def spawn(name):
        if standby and standby[0].name == name:
            rep = standby.pop(0)
        else:
            rep = Replica(name, model, **knobs)
            rep.warmup()
        spawned.append(rep)
        return rep

    first = Replica("r0", model, **knobs)
    first.warmup()
    spawned.append(first)
    router = ClusterRouter([first], control_plane=cp)
    scaler = Autoscaler(router, spawn,
                        AutoscaleConfig(min_replicas=1, max_replicas=3,
                                        up_ticks=2, idle_ticks=25,
                                        cooldown_ticks=10, queue_hwm=2))

    rate = 2.5 * cap1 / max_new             # req/s, 2.5x one replica
    due = np.cumsum(rng.exponential(1.0 / rate, n_req))
    hang_i = (2 * n_req) // 3               # arm mid-wave

    ttfts, outs = [], {}
    lock = threading.Lock()
    threads, events = [], []
    state_at_first_up = [None]
    t_hang, t_evict = [None], [None]
    peak = 1

    def consume(idx, crid, t_submit):
        first_tok = True
        got = []
        for tok in router.stream(crid):
            if first_tok:
                with lock:
                    ttfts.append(time.monotonic() - t_submit)
                first_tok = False
            got.append(tok)
        with lock:
            outs[idx] = got

    try:
        t_start = time.monotonic()
        i = 0
        while True:
            now = time.monotonic() - t_start
            while i < n_req and float(due[i]) <= now:
                if i == hang_i:
                    # the NEXT replica step across the pool goes
                    # silent: no death report, beats just stop
                    faults.configure("cluster.replica:hang@1", seed=0)
                    t_hang[0] = time.monotonic()
                ts = time.monotonic()
                crid = router.submit(prompts[i],
                                     max_new_tokens=max_new)
                th = threading.Thread(target=consume,
                                      args=(i, crid, ts))
                th.start()
                threads.append(th)
                i += 1
            busy = router.step()
            ev = scaler.tick()
            if ev is not None:
                events.append(ev)
                if ev["kind"] == "scale_up" and \
                        state_at_first_up[0] is None:
                    state_at_first_up[0] = \
                        router.slo.evaluate()["state"]
            peak = max(peak, router.num_alive())
            if t_hang[0] is not None and t_evict[0] is None and \
                    any(r.hung and not r.alive for r in spawned):
                t_evict[0] = time.monotonic()
            if not busy:
                if i >= n_req and \
                        all(not th.is_alive() for th in threads):
                    break
                assert time.monotonic() - t_start < 120.0, \
                    "ramp failed to drain"
                time.sleep(0.002)
        for th in threads:
            th.join()
        # idle tail: the scaler must walk the pool back to min
        deadline = time.monotonic() + 30.0
        while router.num_alive() > 1 and time.monotonic() < deadline:
            router.step()
            scaler.tick()
            time.sleep(0.001)
    finally:
        faults.reset()

    assert [outs[k] for k in range(n_req)] == refs, \
        "ramp streams diverged from single-engine references"
    assert len(ttfts) == n_req, \
        "%d/%d requests never got a first token" % (len(ttfts), n_req)
    assert peak >= 2, "wave never scaled the pool out"
    assert t_evict[0] is not None, \
        "seeded hang was never evicted via the lease"
    recovery = t_evict[0] - t_hang[0]
    assert recovery <= lease_s + 2.0, \
        "hang->eviction took %.2fs (lease %.1fs)" % (recovery, lease_s)
    assert router.num_alive() == 1, \
        "idle scale-in left %d replicas" % router.num_alive()
    for r in spawned:
        assert r.engine.ragged_compiles == 1, \
            "replica %s compiled ragged %d times (joins must be warm)" \
            % (r.name, r.engine.ragged_compiles)

    pct = (lambda q: round(
        1e3 * float(np.percentile(ttfts, q)), 2)) if ttfts else \
        (lambda q: None)
    ramp = {
        "offered_x_1rep_capacity": 2.5,
        "arrival_rate_req_per_s": round(rate, 2),
        "requests": n_req,
        "ttft_p50_ms": pct(50), "ttft_p99_ms": pct(99),
        "peak_replicas": peak,
        "final_replicas": router.num_alive(),
        "scale_events": [
            {k: (round(v, 3) if isinstance(v, float) else v)
             for k, v in e.items() if k != "t"} for e in events],
        "slo_state_at_first_scale_out": state_at_first_up[0],
        "scaled_out_before_sustained_burn":
            state_at_first_up[0] != BURN,
        "hang_to_eviction_s": round(recovery, 3),
        "lease_timeout_s": lease_s,
        "replay_token_exact": True,          # asserted above
        "warm_joins_one_compile_each": True,  # asserted above
    }
    router.shutdown()
    for r in standby:                        # never-promoted standbys
        r.shutdown()
    return ramp


def _cluster_kv(pt, model, cfg, rng, slots, blocks, max_new):
    """Cluster-wide KV cache workload (``extra["kv_store"]``): a long
    shared system prompt served tier-ON vs tier-OFF through identical
    2-replica routers. Three phases per arm:

    * seed — plant the prefix on r0 through normal serving;
    * cross — saturate r0 (``max_queue=1``) so the next shared-prefix
      request lands on r1: tier-on imports the prefix pages through
      the global index instead of recomputing them;
    * host — force-demote every cached block on both replicas (tier-on
      spills to host RAM, tier-off discards — the pre-tier behavior),
      then serve the prefix again: tier-on promotes from host, tier-off
      recomputes the full prefill.

    Reports prefill tokens saved (the index/host fetches) and the TTFT
    delta per phase. Token parity vs a single tier-off engine and one
    ragged compile per replica are asserted; latency is recorded, not
    asserted, so the bench stays machine-independent."""
    import threading
    import time

    from paddle_tpu.serving.cluster import ClusterRouter, Replica
    from paddle_tpu.serving.kv_store import (ClusterKVStore,
                                             KVStoreConfig)

    # int8 KV pools: the host spill is the pool layout, so tiered
    # streams can stay token-exact vs the recompute references
    knobs = dict(max_slots=slots, block_size=16, num_blocks=blocks,
                 prefill_chunk=32, kv_quant="int8")
    max_new = min(int(max_new), 12)
    shared = rng.integers(0, cfg.vocab_size, 128).tolist()  # 8 blocks
    tails = [rng.integers(0, cfg.vocab_size, int(n)).tolist()
             for n in (6, 9, 13)]
    reqs = [shared + t for t in tails]       # seed / cross / host
    junk = rng.integers(0, cfg.vocab_size, 24).tolist()

    ref = pt.serving.ServingEngine(model, **knobs)
    refs = []
    for p in reqs:
        rid = ref.submit(list(p), max_new_tokens=max_new)
        while ref.step():
            pass
        refs.append(ref.result(rid))
    ref.shutdown()

    def run(tier_on):
        reps = [Replica("r%d" % i, model, **knobs) for i in range(2)]
        for r in reps:
            r.warmup()
        kv = ClusterKVStore(config=KVStoreConfig(
            tier="host", host_mb=64)) if tier_on else None
        router = ClusterRouter(reps, max_queue=1, kv_store=kv)
        outs, ttft = {}, {}
        lock = threading.Lock()

        def consume(crid, key, t0):
            got, first = [], True
            for tok in router.stream(crid):
                if first:
                    with lock:
                        ttft[key] = time.monotonic() - t0
                    first = False
                got.append(tok)
            with lock:
                outs[key] = got

        def drive(key, prompt, prime=None):
            jc = router.submit(junk, max_new_tokens=max_new) \
                if prime else None           # queues on r0, unstepped
            crid = router.submit(list(prompt),
                                 max_new_tokens=max_new)
            th = threading.Thread(target=consume,
                                  args=(crid, key, time.monotonic()))
            th.start()
            while router.step():
                pass
            th.join(timeout=60.0)
            if jc is not None:
                router.result(jc)

        drive("seed", reqs[0])               # prefix lands on r0
        c0 = dict(kv.counts) if kv else {}
        drive("cross", reqs[1], prime=True)  # r0 full -> r1 serves
        c1 = dict(kv.counts) if kv else {}
        # forced demotion sweep: tier-on spills through the pump,
        # tier-off discards (exactly the pre-tier eviction behavior)
        for r in reps:
            with r.engine._lock:
                r.engine.manager.pop_evictable(blocks)
        if kv is not None:
            while kv.pump() > 0:
                pass
        drive("host", reqs[2])               # restore vs recompute
        c2 = dict(kv.counts) if kv else {}
        for r in reps:
            assert r.engine.ragged_compiles == 1, \
                "replica %s compiled ragged %d times" \
                % (r.name, r.engine.ragged_compiles)
        router.shutdown()
        return ([outs[k] for k in ("seed", "cross", "host")],
                {k: round(1e3 * v, 2) for k, v in ttft.items()},
                (c0, c1, c2))

    outs_off, ttft_off, _ = run(tier_on=False)
    outs_on, ttft_on, (c0, c1, c2) = run(tier_on=True)
    assert outs_off == refs, "tier-off streams != references"
    assert outs_on == refs, "tier-on streams != references"
    cross_saved = c1["fetch_tokens"] - c0["fetch_tokens"]
    host_saved = c2["fetch_tokens"] - c1["fetch_tokens"]
    assert c1["fetches_replica"] > c0["fetches_replica"], \
        "cross phase never fetched through the global index"
    assert c2["fetches_host"] > c1["fetches_host"], \
        "host phase never promoted from the host tier"
    return {
        "shared_prefix_tokens": len(shared),
        "requests": len(reqs),
        "cross_replica": {
            "prefill_tokens_saved": cross_saved,
            "ttft_on_ms": ttft_on.get("cross"),
            "ttft_off_ms": ttft_off.get("cross"),
            "ttft_delta_ms": round(ttft_off.get("cross", 0.0)
                                   - ttft_on.get("cross", 0.0), 2),
        },
        "host_restore": {
            "prefill_tokens_saved": host_saved,
            "ttft_on_ms": ttft_on.get("host"),
            "ttft_off_ms": ttft_off.get("host"),
            "ttft_delta_ms": round(ttft_off.get("host", 0.0)
                                   - ttft_on.get("host", 0.0), 2),
        },
        "demoted_blocks": c2["demotes"],
        "crc_failures": c2["crc_failures"],
        "token_parity_vs_tier_off": True,    # asserted above
        "one_ragged_compile_per_replica": True,
    }


def _bench_elastic():
    """Elastic-training bench, three arms:

    1. recovery latency — the seeded 3-process chaos drill
       (tools/elastic_drill.py): kill rank 2 mid-step, survivors commit
       a shrink epoch and resume from peer-replicated snapshots; the
       reported number is kill -> first post-epoch step completion,
       minus the ordinary per-step cost that would have been paid
       anyway.
    2. disk-restore baseline — the PR 3 path this subsystem replaces:
       a fresh process restores the SAME payload through
       CheckpointManager (latest_valid + load), timed end-to-end
       including process start. Peer recovery must beat it.
    3. snapshot overhead — single-rank ElasticDataParallel steps with
       SNAP_FREQ in {1, 10, 50} vs a never-snapshot baseline on a
       ~256 KB parameter set; reports the added % per setting.
    """
    import subprocess
    import tempfile
    import time

    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import elastic_drill

    # --- arm 1: chaos drill (asserts its own acceptance criteria)
    with _stopwatch("bench.elastic_window"):
        summary = elastic_drill.main(snap_freq=1)
    recovery_s = float(summary["recovery_wall_s"])

    from paddle_tpu.distributed.elastic import (ElasticConfig,
                                                ElasticDataParallel)
    from paddle_tpu.distributed.resilience.checkpoint_manager import \
        CheckpointManager
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.optimizer.optimizers import Adam

    rng = np.random.default_rng(7)
    base_params = [rng.standard_normal((128, 128)).astype(np.float32)
                   for _ in range(4)]
    payload_bytes = int(sum(p.nbytes for p in base_params))

    # --- arm 2: fresh-process disk restore of an equivalent payload
    repo = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory(prefix="elastic_bench_ckpt_") as td:
        mgr = CheckpointManager(td, rank=0, world_size=1)
        mgr.save({"__elastic_state__": {
            "params": [np.asarray(p) for p in base_params],
            "opt": {"m": [np.zeros(p.size, np.float32)
                          for p in base_params],
                    "v": [np.zeros(p.size, np.float32)
                          for p in base_params],
                    "count": 10},
            "step": 10}}, 10, blocking=True)
        code = (
            "import os, sys, time; t0 = time.time();"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu');"
            f"sys.path.insert(0, {repo!r});"
            "from paddle_tpu.distributed.resilience.checkpoint_manager "
            "import CheckpointManager;"
            f"m = CheckpointManager({td!r}, rank=0, world_size=1);"
            "step, path = m.latest_valid();"
            "state = {'__elastic_state__': None}; m.load(state, path);"
            "assert state['__elastic_state__'] is not None;"
            "print(time.time() - t0)")
        t0 = time.monotonic()
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, check=True)
        disk_wall_s = time.monotonic() - t0
        disk_load_s = float(out.stdout.strip().splitlines()[-1])

    # --- arm 3: snapshot overhead vs a never-snapshot baseline
    def grad_fn(params, X, Y):
        grads = [0.001 * p for p in params]
        return float(sum(float(np.vdot(p, p)) for p in params)), grads

    def data_fn(step):
        z = np.zeros((1, 1), np.float32)
        return z, z

    steps = 40

    def timed_run(freq, ns):
        store = TCPStore("127.0.0.1", 0, is_master=True)
        trainer = ElasticDataParallel(
            store, 0, 1, [p.copy() for p in base_params],
            grad_fn, data_fn, Adam(learning_rate=0.01),
            config=ElasticConfig(snap_freq=freq, beat_interval=0.2,
                                 timeout=10.0),
            namespace=ns)
        t0 = time.monotonic()
        trainer.run(steps)
        wall = time.monotonic() - t0
        trainer.shutdown()
        return wall

    timed_run(steps + 1, "bench_warm")        # pay one-time costs
    never = steps + 1                          # freq > steps: no pushes
    t_base = min(timed_run(never, f"bench_base{i}") for i in range(3))
    overhead = {}
    for freq in (1, 10, 50):
        t = min(timed_run(freq, f"bench_f{freq}_{i}") for i in range(3))
        overhead[str(freq)] = round(100.0 * (t - t_base) / t_base, 1)

    # Failure detection (lease expiry -> shrink commit) is common to
    # both recovery tiers, so the head-to-head is post-detection: the
    # survivors' join+adopt from peer memory vs the PR 3 path's fresh
    # process + CheckpointManager restore of the same payload.
    peer_restore_s = max(float(r["latency_ms"])
                         for r in summary["recoveries"]) / 1e3
    detect_s = float(summary["t_kill_to_shrink_commit_s"])

    print(json.dumps({
        "metric": "elastic_recovery_s_cpu_smoke",
        "value": round(recovery_s, 3),
        "unit": "s",
        "vs_baseline": round(disk_wall_s / peer_restore_s, 2)
        if peer_restore_s > 0 else 0.0,
        "extra": {
            "recovery_wall_s": round(recovery_s, 3),
            "t_kill_to_shrink_commit_s": round(detect_s, 3),
            "step_baseline_s": round(
                float(summary["step_baseline_s"]), 4),
            "epoch_log": summary["epoch_log"],
            "peer_restore_s": round(peer_restore_s, 3),
            "disk_restore_baseline_s": round(disk_wall_s, 3),
            "disk_restore_load_s": round(disk_load_s, 3),
            "beats_disk_restore": peer_restore_s < disk_wall_s,
            "end_to_end_peer_s": round(recovery_s, 3),
            "end_to_end_disk_s": round(detect_s + disk_wall_s, 3),
            "snapshot_overhead_pct": overhead,
            "snapshot_steps": steps,
            "payload_bytes": payload_bytes,
            "drill_snap_freq": 1,
        },
    }))
    return 0


def _bench_ps():
    """Parameter-server bench, four arms:

    1. failover recovery — the seeded 3-process kill drill
       (tools/ps_drill.py): kill the primary server mid-epoch, the
       backup promotes inside the lease budget, and the recommender
       loop finishes bit-exact; reports kill-step extra latency vs an
       ordinary step, head-to-head with a cold process restart.
    2. exactly-once — the in-process lost-ack drill: a ``ps.push``
       fault after delivery forces a retransmit; requires dedup hits
       and a bit-equal table digest vs the clean run.
    3. pull/push throughput — a single-process LocalTransport worker
       hammering one sparse shard; reports rows/s both ways plus
       p50/p99 pull latency.
    4. bounded-capacity eviction — zipfian pushes into a
       capacity-bounded SparseTable; reports the eviction rate and the
       resident-row ceiling holding.
    """
    import time

    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import ps_drill

    # --- arm 1: kill drill (asserts its own acceptance criteria)
    with _stopwatch("bench.ps_window"):
        summary = ps_drill.main()
    recovery_s = float(summary["recovery_wall_s"])
    cold_restart_s = float(summary["cold_restart_s"])
    fo = summary["failovers"][0]

    # --- arm 2: lost-ack retransmit dedup (asserts digest equality)
    dedup = ps_drill.dedup_drill()

    from paddle_tpu.distributed.ps import (LocalTransport, PSServer,
                                           PSWorker)
    from paddle_tpu.distributed.ps.tables import SparseTable

    # --- arm 3: LocalTransport pull/push throughput + pull latency
    dim, batch, rounds = 32, 2048, 30
    srv = PSServer(0, n_servers=1)
    try:
        srv.add_sparse_table(0, dim, optimizer="adagrad", lr=0.1)
        w = PSWorker(1, 1, worker_id="bench",
                     transport=LocalTransport())
        rng = np.random.default_rng(11)
        ids = rng.integers(0, 200_000, size=batch)
        grads = rng.standard_normal((batch, dim)).astype(np.float32)
        w.pull_sparse(0, ids, dim=dim)           # materialize rows
        w.push_sparse(0, ids, grads)             # pay one-time costs
        pull_lat, push_lat = [], []
        for _ in range(rounds):
            t0 = time.perf_counter()
            w.pull_sparse(0, ids, dim=dim)
            pull_lat.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            w.push_sparse(0, ids, grads)
            push_lat.append(time.perf_counter() - t0)
        pull_rows_per_s = batch * rounds / sum(pull_lat)
        push_rows_per_s = batch * rounds / sum(push_lat)
        pull_p50_ms = float(np.percentile(pull_lat, 50)) * 1e3
        pull_p99_ms = float(np.percentile(pull_lat, 99)) * 1e3
    finally:
        srv.shutdown_local()

    # --- arm 4: eviction rate under zipfian skew at bounded capacity
    cap, evict_rounds = 1024, 20
    tbl = SparseTable(16, optimizer="sgd", lr=0.1, seed=0,
                      capacity=cap)
    zrng = np.random.default_rng(13)
    pushed = 0
    for _ in range(evict_rounds):
        zids = zrng.zipf(1.3, size=512) % 100_000
        tbl.push(zids, zrng.standard_normal(
            (512, 16)).astype(np.float32))
        pushed += 512
    ev = tbl.counters()
    assert ev["rows"] <= cap, ev

    print(json.dumps({
        "metric": "ps_failover_recovery_s_cpu_smoke",
        "value": round(recovery_s, 3),
        "unit": "s",
        "vs_baseline": round(cold_restart_s / recovery_s, 2)
        if recovery_s > 0 else 0.0,
        "extra": {
            "recovery_wall_s": round(recovery_s, 3),
            "failover_latency_s": round(float(fo["latency_s"]), 3),
            "failover_budget_s": ps_drill.FAILOVER_S,
            "step_baseline_s": round(
                float(summary["step_baseline_s"]), 4),
            "cold_restart_s": round(cold_restart_s, 3),
            "beats_cold_restart": recovery_s < cold_restart_s,
            "drill_steps": summary["total_steps"],
            "kill_step": summary["kill_step"],
            "push_dedup_hits": dedup["dedup_hits"],
            "dedup_bit_equal": True,     # dedup_drill asserts it
            "pull_rows_per_s": round(pull_rows_per_s, 1),
            "push_rows_per_s": round(push_rows_per_s, 1),
            "pull_p50_ms": round(pull_p50_ms, 3),
            "pull_p99_ms": round(pull_p99_ms, 3),
            "throughput_batch": batch,
            "eviction_rate": round(ev["evictions"] / pushed, 4),
            "evictions": ev["evictions"],
            "resident_rows": ev["rows"],
            "capacity": cap,
        },
    }))
    return 0


def _tp_overlap_result(on_tpu):
    """tp_overlap sub-bench: decomposed ring all-gather-matmul vs the
    serial gather-then-GEMM pair on a 2-device mp mesh.

    The serial arm materializes the full gathered [T, K] operand before
    the GEMM can start; the ring arm streams per-rank blocks, so each
    shift's bytes ride inside the previous block's GEMM (and on host CPU
    it also moves half the gather bytes — the measurable win there).
    Sweeps chunk counts, asserts the steady state never retraces and the
    2-rank ring output is bitwise equal to the serial composition."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.fusion import overlap_mm

    if len(jax.devices()) < 2:
        return {"skipped": True, "reason": "needs >= 2 devices"}
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("mp",))
    if on_tpu:
        T, K, N, iters = 16384, 4096, 1024, 16
    else:
        # host-CPU smoke: bandwidth-bound shape (small N) so the gather
        # buffer traffic, not the GEMM, decides the race
        T, K, N, iters = 8192, 1024, 128, 8
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((T, K)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((K, N)) * 0.05, jnp.float32)

    def timed(fn):
        out = fn(x, w)
        jax.block_until_ready(out)          # warmup pays the compile
        with _stopwatch("bench.tp_overlap_window") as sw:
            for _ in range(iters):
                out = fn(x, w)
            jax.block_until_ready(out)
        return sw.elapsed / iters * 1e3, out

    def _serial(xl, wl):
        return jnp.matmul(jax.lax.all_gather(xl, "mp", tiled=True), wl)

    serial = jax.jit(overlap_mm._shard_map(
        _serial, mesh, (P("mp", None), P(None, "mp")), P(None, "mp")))
    off_ms, ref = timed(serial)

    traces = []

    def _overlap(chunks):
        def fn(a, b):
            traces.append(0)
            return overlap_mm.sharded_all_gather_matmul(
                a, b, mesh=mesh, chunks=chunks)
        return jax.jit(fn)

    sweep = {}
    best = None
    for chunks in (1, 2, 4):
        jov = _overlap(chunks)
        n0 = len(traces)
        ms, out = timed(jov)
        assert len(traces) == n0 + 1, \
            f"tp_overlap chunks={chunks} retraced in steady state"
        # 2-rank ring == serial composition bitwise (every partial sum
        # has exactly two terms) — same contract tests/test_tp_overlap.py
        # enforces on loss and grads
        assert np.array_equal(np.asarray(ref), np.asarray(out)), chunks
        sweep[str(chunks)] = round(ms, 3)
        if best is None or ms < best[1]:
            best = (chunks, ms)

    with overlap_mm.override(tp_overlap="pallas"):
        pallas_impl = overlap_mm.impl()     # ppermute fallback off-TPU
        pallas_ms, out = timed(_overlap(best[0]))
        assert np.array_equal(np.asarray(ref), np.asarray(out)), "pallas"

    speedup = off_ms / best[1]
    if not on_tpu:
        assert speedup > 1.0, \
            f"tp_overlap smoke lost to serial: {speedup:.3f}x"
    return {
        "primitive": "all_gather_matmul", "mesh": "mp=2",
        "shape": [T, K, N],
        "off_step_ms": round(off_ms, 3),
        "on_step_ms": round(best[1], 3),
        "on_chunks": best[0],
        "chunk_sweep_ms": sweep,
        "pallas_step_ms": round(pallas_ms, 3),
        "pallas_impl": pallas_impl,
        "speedup": round(speedup, 3),
    }


def _multichip_result():
    """Body of the multichip pipeline bench (shared with the
    ``dryrun_multichip`` artifact in ``__graft_entry__.py``).

    Runs the SAME pure-function transformer through two pipeline legs on
    ``S`` devices:

    * device leg — :class:`CompiledPipeline`: the whole 1F1B schedule is
      one jit; stage boundaries move by ring ``collective-permute``
      (``PADDLE_TPU_PP_RING`` picks ppermute vs the Pallas DMA ring) and
      grad reduction is bucketed into the backward.
    * host leg — the pre-existing host-driven path: ``StagedProgram`` +
      ``Pipeline1F1BPass.apply`` (eager per-job vjp, host-orchestrated
      stage hops), i.e. what ``_StagedTrainStep`` executes.

    Returns the structured metric dict (tokens/s, MFU, n_devices,
    schedule, speedup_vs_host) instead of a raw stdout tail."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.distributed.passes.pipeline_scheduler_pass import (
        Pipeline1F1BPass, StagedProgram)
    from paddle_tpu.distributed.pipeline import (
        CompiledPipeline, overlap_bucket_bytes, ring_impl)
    from paddle_tpu.observability import profiler as _prof

    # profiling on for the whole leg (child process, state is ours):
    # the PP/DP overlap notes fire at trace time during warmup, the TP
    # note during the tp_overlap sub-bench, and the fenced attribution
    # step at the end reads them all
    _prof.enable_profiling("on")
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_dev = len(jax.devices())
    S = 2
    if n_dev < S:
        return {"metric": "multichip_pp_tokens_per_s", "value": 0.0,
                "unit": "tokens/s", "vs_baseline": 0.0,
                "extra": {"skipped": True, "n_devices": n_dev,
                          "reason": "needs >= 2 devices"}}
    if on_tpu:
        hidden, heads, vocab, seq = 2048, 16, 50304, 1024
        B, mb, M, iters = 12, 1, 8, 4     # blocks/stage, micro size/count
    else:
        hidden, heads, vocab, seq = 128, 4, 1024, 128
        B, mb, M, iters = 1, 2, 4, 4
    L, h4 = S * B, 4 * hidden
    rng = np.random.default_rng(0)

    def w(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    def per_layer():
        return [
            np.ones(hidden, np.float32), np.zeros(hidden, np.float32),
            w(hidden, 3 * hidden), np.zeros(3 * hidden, np.float32),
            w(hidden, hidden), np.zeros(hidden, np.float32),
            np.ones(hidden, np.float32), np.zeros(hidden, np.float32),
            w(hidden, h4), np.zeros(h4, np.float32),
            w(h4, hidden), np.zeros(hidden, np.float32),
        ]

    layers = [per_layer() for _ in range(L)]
    # 12 leaves, each [S, B, ...]: stage s owns layers [s*B, (s+1)*B)
    stacked = [np.stack([np.stack([layers[s * B + b][i] for b in range(B)])
                         for s in range(S)]) for i in range(12)]
    extra = {"wte": w(vocab, hidden), "wpe": w(seq, hidden),
             "lnfw": np.ones(hidden, np.float32),
             "lnfb": np.zeros(hidden, np.float32),
             "head": w(hidden, vocab)}

    def _ln(x, wt, bs):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * wt + bs

    def _blk(p, x):
        ln1w, ln1b, wqkv, bqkv, wo, bo, ln2w, ln2b, w1, b1, w2, b2 = p
        b, s, d = x.shape
        hd = d // heads
        q, k, v = jnp.split(_ln(x, ln1w, ln1b) @ wqkv + bqkv, 3, axis=-1)

        def sp(t):
            return t.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)

        att = (sp(q) @ sp(k).transpose(0, 1, 3, 2)) * (1.0 / np.sqrt(hd))
        att = jnp.where(np.tril(np.ones((s, s), bool)), att, -1e9)
        o = (jax.nn.softmax(att, -1) @ sp(v)).transpose(0, 2, 1, 3)
        x = x + o.reshape(b, s, d) @ wo + bo
        z = _ln(x, ln2w, ln2b)
        return x + jax.nn.gelu(z @ w1 + b1) @ w2 + b2

    def stage_fn(params, x):
        for i in range(B):
            x = _blk([a[i] for a in params], x)
        return x

    def pre_fn(ex, ids):
        return ex["wte"][ids] + ex["wpe"][None, :]

    def _head_loss(lnfw, lnfb, head, hh, ym):
        z = _ln(hh, lnfw, lnfb) @ head
        lp = jax.nn.log_softmax(z.astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, ym[..., None], -1).mean()

    def loss_fn(ex, hh, ym):
        return _head_loss(ex["lnfw"], ex["lnfb"], ex["head"], hh, ym)

    gb = M * mb
    ids = jnp.asarray(rng.integers(0, vocab, (gb, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, vocab, (gb, seq)), jnp.int32)

    # ---- device leg: one-jit compiled 1F1B over S devices
    pipe = CompiledPipeline(
        stage_fn, stacked, loss_fn, num_stages=S, num_micro=M,
        optimizer=pt.optimizer.SGD(learning_rate=0.01),
        extra_params=extra, pre_fn=pre_fn)
    loss_dev = float(pipe.step(ids, labels))       # warmup: pays the compile
    with _stopwatch("bench.multichip_window") as sw:
        for _ in range(iters):
            last = pipe.step(ids, labels)
        float(last)
        jax.block_until_ready(pipe.params)
    el_dev = sw.elapsed

    # ---- host leg: same math through the host-driven schedule
    host_params = [[jnp.asarray(leaf[s]) for leaf in stacked]
                   for s in range(S)]
    host_params[0] = [jnp.asarray(extra["wte"]),
                      jnp.asarray(extra["wpe"])] + host_params[0]
    host_params[-1] = host_params[-1] + [
        jnp.asarray(extra["lnfw"]), jnp.asarray(extra["lnfb"]),
        jnp.asarray(extra["head"])]

    def host_first(p, xi):
        return stage_fn(p[2:], p[0][xi] + p[1][None, :])

    def host_mid(p, hh):
        return stage_fn(p, hh)

    def host_last(p, hh, ym):
        return _head_loss(p[12], p[13], p[14], stage_fn(p[:12], hh), ym)

    prog = StagedProgram(
        [host_first] + [host_mid] * (S - 2) + [host_last], host_params,
        loss_fn=None, devices=list(jax.devices()[:S]),
        last_takes_label=True)
    sched = Pipeline1F1BPass()
    opt_h = pt.optimizer.SGD(learning_rate=0.01)
    state_h = opt_h.init_state([a for st in prog.params for a in st])
    micros_x = [ids[i * mb:(i + 1) * mb] for i in range(M)]
    micros_y = [labels[i * mb:(i + 1) * mb] for i in range(M)]

    def host_step():
        nonlocal state_h
        loss, grads, _ = sched.apply(prog, micros_x, micros_y)
        flat_p = [a for st in prog.params for a in st]
        flat_g = [g for gs in grads for g in gs]
        new_p, state_h = opt_h.update(flat_p, flat_g, state_h)
        i = 0
        for st in prog.params:
            for j in range(len(st)):
                st[j] = new_p[i]
                i += 1
        return loss

    loss_host = float(host_step())                 # warmup leg symmetry
    with _stopwatch("bench.multichip_window") as sw:
        for _ in range(iters):
            last_h = host_step()
        float(last_h)
        jax.block_until_ready([a for st in prog.params for a in st])
    el_host = sw.elapsed

    n_params = sum(int(np.prod(a.shape)) for a in stacked)
    n_params += sum(int(np.prod(v.shape)) for v in extra.values())
    fpt = 6 * n_params + 6 * L * hidden * seq
    tps = gb * seq * iters / el_dev
    tps_host = gb * seq * iters / el_host
    peak, peak_known = _peak_flops(dev)
    mfu = tps * fpt / (peak * S) if peak else 0.0

    # TP overlap sub-bench first: it fires the profiler's "tp" ring
    # note, so the overlap report below covers all three mechanisms
    tp_overlap = _tp_overlap_result(on_tpu)

    # ---- profiled attribution step: one more compiled step, device-
    # fenced between dispatch and drain so the profiler attributes wall
    # time to phases. Runs OUTSIDE the timed windows.
    _prof.configure(flops_per_step=float(fpt) * gb * seq,
                    tokens_per_step=gb * seq,
                    peak_flops=(peak * S) if peak else 0.0)
    rec = _prof.StepRecord(iters + 1)
    rec.mark("data_wait")                     # batch already resident
    loss_prof = pipe.step(ids, labels)
    rec.mark("dispatch")
    jax.block_until_ready(loss_prof)
    rec.mark("device")
    prof_rep = rec.close(tokens=gb * seq)
    segs = prof_rep["segments"]
    wall = prof_rep["wall_s"]
    # the tentpole invariant, asserted on the smoke arm: phase segments
    # sum to the measured step time exactly (fp telescoping only)
    assert abs(sum(segs.values()) - wall) <= 1e-9 + 1e-6 * wall, \
        f"attribution segments {sum(segs.values())} != wall {wall}"
    overlap = _prof.overlap_report()

    metric = ("multichip_pp_train_tokens_per_s_chip" if on_tpu
              else "multichip_pp_tokens_per_s_cpu_smoke")
    res = {
        "metric": metric,
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
        "extra": {
            "n_devices": S, "schedule": "1F1B-compiled",
            "transport": f"device({ring_impl()})",
            "micro_batches": M, "micro_batch": mb, "seq": seq,
            "params": n_params, "mfu": round(mfu, 4),
            "loss_device": round(loss_dev, 6),
            "loss_host": round(loss_host, 6),
            "host_tokens_per_s": round(tps_host, 1),
            "speedup_vs_host": round(el_host / el_dev, 3),
            "pp_bucket_mb": overlap_bucket_bytes() / float(1 << 20),
            "compiles": pipe.trace_count,
            "tp_overlap": tp_overlap,
            "attribution": {
                "step_mfu": round(prof_rep["mfu"], 4),
                "wall_ms": round(wall * 1e3, 4),
                "segments_ms": {k: round(v * 1e3, 4)
                                for k, v in segs.items()},
            },
            "overlap_efficiency": {
                m: round(o["efficiency"], 4)
                for m, o in sorted(overlap.items())
            },
        },
    }
    if not peak_known:
        res["extra"]["peak_flops_assumed_v5e"] = True
    # contract checks: one trace total (the profiled extra step must
    # NOT have retraced), and both legs computed the same first-step
    # loss from identical init params
    assert pipe.trace_count == 1, \
        f"compiled pipeline retraced: {pipe.trace_count}"
    assert abs(loss_dev - loss_host) <= 2e-3 * max(1.0, abs(loss_host)), \
        f"leg disparity: device {loss_dev} vs host {loss_host}"
    return res


def _bench_multichip():
    """Parent of ``--multichip``: re-exec in a fresh interpreter so the
    forced CPU device count lands before jax initializes, demote backend
    noise ("[Gloo] Rank N is connected...") out of the output, and pass
    through the child's one JSON metric line."""
    import subprocess

    from paddle_tpu.distributed.log_utils import filter_noise_lines

    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env.setdefault("PADDLE_TPU_PP_TRANSPORT", "device")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multichip-child"],
        capture_output=True, text=True, env=env, timeout=1800)
    for ln in filter_noise_lines(proc.stderr.splitlines()):
        if ln.strip():
            print(ln, file=sys.stderr)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    if proc.returncode != 0 or not lines:
        print(f"--multichip child failed (rc={proc.returncode})",
              file=sys.stderr)
        return proc.returncode or 1
    print(lines[-1])
    try:
        child_result = json.loads(lines[-1])
    except json.JSONDecodeError:
        return 0
    return _maybe_perfdiff(child_result)


def _bench_multichip_child():
    from paddle_tpu.distributed.log_utils import install_stderr_filter

    install_stderr_filter()
    print(json.dumps(_multichip_result()))
    return 0


def main():
    if "--multichip-child" in sys.argv:
        return _bench_multichip_child()
    if "--multichip" in sys.argv:
        return _bench_multichip()
    if "--elastic" in sys.argv:
        return _bench_elastic()
    if "--ps" in sys.argv:
        return _bench_ps()

    import jax

    import paddle_tpu as pt

    if "--serving" in sys.argv:
        return _bench_serving()
    if "--cluster" in sys.argv:
        return _bench_cluster()

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    small = (_knobs.get_str("PADDLE_TPU_BENCH") or "").lower() == "125m"

    if not on_tpu:
        # off-TPU smoke (no MFU meaning): tiny config, just prove the path
        cfg = pt.models.gpt_tiny(dropout=0.0, attention_dropout=0.0)
        batch, seq = 2, 128
        metric = "gpt_tiny_train_tokens_per_sec_cpu_smoke"
        opt_kwargs = {}
        iters = 2
    elif small:
        cfg = pt.models.gpt3_125M(dropout=0.0, attention_dropout=0.0,
                                  lm_ce_chunks=8)
        batch, seq = 64, 512
        metric = "gpt3_125m_train_tokens_per_sec_chip"
        opt_kwargs = {"factored_v": True, "moment_dtype": "bfloat16"}
        iters = 8
    else:
        cfg = pt.models.gpt3_1p3B(dropout=0.0, attention_dropout=0.0,
                                  recompute=False, lm_ce_chunks=8)
        batch, seq = (8, 1024)
        metric = "gpt3_1p3b_train_tokens_per_sec_chip"
        opt_kwargs = {"factored_v": True, "moment_dtype": "bfloat16"}
        iters = 4

    model, step, ids, labels = _build(pt, cfg, batch, seq, on_tpu,
                                      opt_kwargs)
    el, loss = _measure(step, ids, labels, iters)
    tokens_per_sec = batch * seq * iters / el
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # training FLOPs/token: 6N for the matmuls + causal attention term
    attn_flops = 6 * cfg.num_layers * cfg.hidden_size * seq  # fwd+bwd
    flops_per_token = 6 * n_params + attn_flops
    peak, peak_known = _peak_flops(dev)
    mfu = tokens_per_sec * flops_per_token / peak if peak else 0.0

    extra = {
        "device": getattr(dev, "device_kind", str(dev)),
        "batch": batch, "seq": seq, "params": n_params,
        "mfu": round(mfu, 4), "loss": round(float(loss), 4),
        "recompute": bool(getattr(cfg, "recompute", False)),
        "optimizer": "AdamW bf16-m + factored-v (Adafactor rank-1)"
        if opt_kwargs else "AdamW fp32",
        "lm_ce_chunks": int(getattr(cfg, "lm_ce_chunks", 0)),
    }
    if not peak_known:
        extra["peak_flops_assumed_v5e"] = True
    # headline MFU is measured with overlap routing live (auto -> on);
    # single-chip runs have no mp mesh, so the serial GEMMs are untouched
    # and the number stays comparable to earlier rounds
    from paddle_tpu.fusion import overlap_mm as _ov
    extra["tp_overlap"] = {"mode": _ov.mode(), "impl": _ov.impl(),
                           "chunks": _ov.default_chunks()}
    extra["fusion"] = _bench_fusion(pt, on_tpu)

    # flops cross-check (the "MFU is never silently wrong" promise):
    # XLA's own HLO cost model vs the 6N analytic model the headline
    # MFU divides by. >10% disagreement means one of them is lying —
    # flagged on stderr, never silent.
    try:
        ca = step.lower(ids, labels).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        xla_flops = float(ca.get("flops", 0.0)) if isinstance(ca, dict) \
            else 0.0
    except Exception:
        xla_flops = 0.0
    if xla_flops > 0:
        model_flops = float(flops_per_token) * batch * seq
        div = abs(xla_flops - model_flops) / model_flops
        extra["flops_check"] = {
            "model": model_flops, "xla": xla_flops,
            "divergence": round(div, 4),
        }
        from paddle_tpu.observability import profiler as _prof
        _prof.flops_divergence(model_flops, xla_flops)
        if div > 0.10:
            print(f"bench: WARNING: analytic 6N FLOPs model diverges "
                  f"{div:.1%} from XLA cost analysis "
                  f"(model={model_flops:.3e}, xla={xla_flops:.3e}) — "
                  f"headline MFU is suspect", file=sys.stderr)

    if on_tpu and not small:
        # streaming variant: fresh per-step batches via run_steps_stream
        # (genuine-training throughput next to the same-batch headline)
        rng = np.random.default_rng(1)
        xs = rng.integers(0, cfg.vocab_size, (iters, batch, seq))
        stream_ids = pt.to_tensor(xs, dtype="int64")
        loss_s = step.run_steps_stream(iters, stream_ids, stream_ids)
        float(loss_s)
        xs2 = rng.integers(0, cfg.vocab_size, (iters, batch, seq))
        s_ids2 = pt.to_tensor(xs2, dtype="int64")
        with _stopwatch("bench.train_window") as sw:
            float(step.run_steps_stream(iters, s_ids2, s_ids2))
        el_s = sw.elapsed
        tps_s = batch * seq * iters / el_s
        extra["stream_fresh_data"] = {
            "tokens_per_s": round(tps_s, 1),
            "mfu": round(tps_s * flops_per_token / peak, 4),
            "of_headline": round(tps_s / tokens_per_sec, 3),
        }

        # seq-2048 sub-bench (round-2 weak #1: 0.30 MFU there; round-5:
        # fused single-pass flash bwd + ce-chunks 8 -> 0.667)
        del model, step, ids, labels
        cfg2 = pt.models.gpt3_1p3B(dropout=0.0, attention_dropout=0.0,
                                   recompute=False, lm_ce_chunks=8)
        m2, step2, ids2, labels2 = _build(pt, cfg2, 4, 2048, on_tpu,
                                          opt_kwargs)
        el2, _ = _measure(step2, ids2, labels2, iters)
        tps2 = 4 * 2048 * iters / el2
        fpt2 = 6 * n_params + 6 * cfg2.num_layers * cfg2.hidden_size * 2048
        extra["seq2048"] = {
            "batch": 4, "tokens_per_s": round(tps2, 1),
            "mfu": round(tps2 * fpt2 / peak, 4),
        }

        # ---- decode (serving) bench, driver-visible (VERDICT r4 #5):
        # GPT-1.3B b8 plen128, quantized weights + int8 KV cache.
        # Two-point (64 vs 192 new tokens) differencing cancels the
        # fixed tunnel dispatch+read overhead, leaving device step time.
        del m2, step2, ids2, labels2
        extra["decode"] = _bench_decode(pt, cfg2)
        extra["moe"] = _bench_moe()

    result = {
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        # mfu is a fraction (0..1); north star is 0.45 (BASELINE.json)
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
        "extra": extra,
    }
    print(json.dumps(result))
    return _maybe_perfdiff(result)


def _maybe_perfdiff(result: dict) -> int:
    """Optional regression gate: ``--diff BASE.json`` (or env
    ``PADDLE_TPU_PERFDIFF_BASE``) compares the just-printed result
    against a baseline via tools/perfdiff.py and makes the bench exit
    nonzero on a regression beyond the noise bounds."""
    base = None
    if "--diff" in sys.argv:
        i = sys.argv.index("--diff")
        if i + 1 >= len(sys.argv):
            print("bench: --diff needs a baseline JSON path",
                  file=sys.stderr)
            return 2
        base = sys.argv[i + 1]
    base = base or _knobs.get_str("PADDLE_TPU_PERFDIFF_BASE")
    if not base:
        return 0
    import importlib.util

    pd_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tools", "perfdiff.py")
    spec = importlib.util.spec_from_file_location("_perfdiff", pd_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    try:
        old = mod.load_doc(base)
    except ValueError as e:
        print(f"bench: perfdiff baseline unusable: {e}", file=sys.stderr)
        return 2
    regressions, notes = mod.compare(old, result, mod.DEFAULT_NOISE)
    for n in notes:
        print(f"perfdiff ok: {n}", file=sys.stderr)
    for r in regressions:
        print(f"perfdiff REGRESSION: {r}", file=sys.stderr)
    if regressions:
        print(f"bench: {len(regressions)} regression(s) vs {base}",
              file=sys.stderr)
        return 1
    print(f"bench: no regression vs {base}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
