#!/usr/bin/env python
"""Flagship benchmark: GPT-3 single-chip full-training-step throughput.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": "tokens/s",
   "vs_baseline": MFU / 0.45}

vs_baseline is measured MFU over the north-star target (BASELINE.json:
>=45% MFU); >1.0 beats the target. The reference publishes no in-tree
numbers (BASELINE.md), so MFU-vs-north-star is the comparable scalar.

Headline config: GPT-3-1.3B, batch 16 x seq 1024, bf16 params, bf16 AdamW
first moments (fp32 update math), per-block rematerialization — the
>=1B-param single-chip configuration (VERDICT r1 next #1). Set
PADDLE_TPU_BENCH=125m for the round-1 small config (batch 64 x seq 512).

Context (tools/profile_bench.py, committed breakdown in STATUS.md): a bare
bf16 matmul chain measures 0.574 MFU-equivalent through the axon tunnel on
this chip — the practical ceiling the MFU below should be read against.
MFU counts only the standard 6N FLOPs/token: the rematerialized forward
(~+33% real FLOPs) is uncredited, so hardware utilization is higher.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    """Per-chip peak bf16 FLOP/s by TPU generation (public specs)."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v5 lite": 197e12,   # v5e
        "v5litepod": 197e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6e": 918e12,
        "v6 lite": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    if device.platform == "tpu":
        return 197e12
    return 0.0  # CPU: MFU not meaningful


def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu.jit import TrainStep

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    small = os.environ.get("PADDLE_TPU_BENCH", "").lower() == "125m"

    if not on_tpu:
        # off-TPU smoke (no MFU meaning): tiny config, just prove the path
        cfg = pt.models.gpt_tiny(dropout=0.0, attention_dropout=0.0)
        batch, seq = 2, 128
        metric = "gpt_tiny_train_tokens_per_sec_cpu_smoke"
        moment_dtype = "float32"
        iters = 2
    elif small:
        cfg = pt.models.gpt3_125M(dropout=0.0, attention_dropout=0.0)
        batch, seq = 64, 512
        metric = "gpt3_125m_train_tokens_per_sec_chip"
        moment_dtype = "float32"
        iters = 8
    else:
        cfg = pt.models.gpt3_1p3B(dropout=0.0, attention_dropout=0.0,
                                  recompute=True)
        batch, seq = (16, 1024)
        metric = "gpt3_1p3b_train_tokens_per_sec_chip"
        moment_dtype = "bfloat16"
        iters = 4

    pt.set_default_dtype("bfloat16" if on_tpu else "float32")
    try:
        model = pt.models.GPTForCausalLM(cfg)
    finally:
        pt.set_default_dtype("float32")
    opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                             parameters=model.parameters(),
                             moment_dtype=moment_dtype)
    step = TrainStep(model, opt, grad_clip_norm=1.0)

    rng = np.random.default_rng(0)
    ids = pt.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)),
                       dtype="int64")
    labels = pt.to_tensor(rng.integers(0, cfg.vocab_size, (batch, seq)),
                          dtype="int64")

    # run_steps chains N optimizer steps in ONE dispatch: the chip sits
    # behind a high-latency tunnel (~100ms/round-trip) and, on this
    # platform, block_until_ready can return before execution finishes —
    # a device->host scalar read (float()) is the only honest barrier.
    loss = step.run_steps(iters, ids, labels)   # warmup/compile
    float(loss)
    t0 = time.perf_counter()
    loss = step.run_steps(iters, ids, labels)
    float(loss)                                 # d2h barrier
    el = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / el
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # training FLOPs/token: 6N for the matmuls + causal attention term
    attn_flops = 6 * cfg.num_layers * cfg.hidden_size * seq  # fwd+bwd, causal
    flops_per_token = 6 * n_params + attn_flops
    peak = _peak_flops(dev)
    mfu = tokens_per_sec * flops_per_token / peak if peak else 0.0

    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        # mfu is a fraction (0..1); north star is 0.45 (BASELINE.json)
        "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
        "extra": {
            "device": getattr(dev, "device_kind", str(dev)),
            "batch": batch, "seq": seq, "params": n_params,
            "mfu": round(mfu, 4), "loss": round(float(loss), 4),
            "recompute": bool(getattr(cfg, "recompute", False)),
            "moment_dtype": moment_dtype,
            # v5e-specific measurement (tools/profile_bench.py)
            **({"measured_matmul_ceiling_mfu_equiv": 0.574}
               if "v5 lite" in getattr(dev, "device_kind", "").lower()
               else {}),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
