"""Audio feature layers (reference: python/paddle/audio/features/layers.py —
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor, run_op
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame_signal(x, n_fft, hop_length, center, pad_mode="reflect"):
    """x: [..., time] -> frames [..., n_frames, n_fft]."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    n = x.shape[-1]
    n_frames = 1 + (n - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    return x[..., idx]


class Spectrogram(nn.Layer):
    """STFT magnitude/power spectrogram (reference: layers.py Spectrogram).
    Output: [..., n_fft//2 + 1, n_frames]."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = get_window(window, self.win_length)._data
        if self.win_length < n_fft:
            lpad = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - self.win_length - lpad))
        self.register_buffer("fft_window", Tensor(w))

    def forward(self, x):
        t = as_tensor(x)
        n_fft, hop, win, power, center, pad_mode = (
            self.n_fft, self.hop_length, self.fft_window._data, self.power,
            self.center, self.pad_mode)

        def fn(a):
            frames = _frame_signal(a, n_fft, hop, center, pad_mode)
            spec = jnp.fft.rfft(frames * win, axis=-1)
            mag = jnp.abs(spec)
            if power != 1.0:
                mag = mag ** power
            # [..., n_frames, bins] -> [..., bins, n_frames]
            return jnp.swapaxes(mag, -1, -2)

        return run_op(fn, [t], name="spectrogram")


class MelSpectrogram(nn.Layer):
    """reference: layers.py MelSpectrogram."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: str = "slaney", dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center)
        self.register_buffer("fbank_matrix", compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm))

    def forward(self, x):
        spec = self.spectrogram(x)
        fb = self.fbank_matrix._data

        def fn(s):
            return jnp.einsum("mf,...ft->...mt", fb, s)

        return run_op(fn, [spec], name="mel_spectrogram")


class LogMelSpectrogram(nn.Layer):
    """reference: layers.py LogMelSpectrogram."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: str = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, n_mels, f_min, f_max, htk,
                                  norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self.mel(x), self.ref_value, self.amin,
                           self.top_db)


class MFCC(nn.Layer):
    """reference: layers.py MFCC."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None, n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 top_db: Optional[float] = None, dtype: str = "float32",
                 **mel_kwargs):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, n_mels=n_mels,
            f_min=f_min, f_max=f_max, top_db=top_db, **mel_kwargs)
        self.register_buffer("dct_matrix", create_dct(n_mfcc, n_mels))

    def forward(self, x):
        logmel = self.log_mel(x)
        dct = self.dct_matrix._data

        def fn(lm):
            return jnp.einsum("mk,...mt->...kt", dct, lm)

        return run_op(fn, [logmel], name="mfcc")
