"""Audio functional ops (reference: python/paddle/audio/functional/ —
window.py get_window, functional.py hz_to_mel/mel_to_hz/mel_frequencies/
fft_frequencies/compute_fourier_basis equivalents, create_dct).

All transforms compose jnp ops (FFT lowers to XLA's FFT HLO), so they run
on TPU and are differentiable through run_op.
"""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor, run_op

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "fft_frequencies", "compute_fbank_matrix", "create_dct",
           "power_to_db"]


def get_window(window: str, win_length: int, fftbins: bool = True,
               dtype: str = "float64") -> Tensor:
    """reference: audio/functional/window.py get_window."""
    n = win_length
    sym = not fftbins
    denom = (n - 1) if sym else n
    k = np.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / denom)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / denom)
             + 0.08 * np.cos(4 * np.pi * k / denom))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    elif window == "bartlett":
        w = 1.0 - np.abs(2.0 * k / denom - 1.0)
    else:
        raise ValueError(f"unsupported window {window!r}")
    # float64 requires jax_enable_x64; degrade gracefully to float32
    import jax

    jdt = jnp.float64 if (dtype == "float64"
                          and jax.config.jax_enable_x64) else jnp.float32
    return Tensor(jnp.asarray(w, dtype=jdt))


def hz_to_mel(freq, htk: bool = False):
    """reference: audio/functional/functional.py hz_to_mel."""
    f = np.asarray(freq, dtype=np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        # Slaney
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mels = np.where(f >= min_log_hz,
                        min_log_mel + np.log(np.maximum(f, 1e-10)
                                             / min_log_hz) / logstep, mels)
        out = mels
    return float(out) if np.isscalar(freq) else out


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, dtype=np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        freqs = np.where(m >= min_log_mel,
                         min_log_hz * np.exp(logstep * (m - min_log_mel)),
                         freqs)
        out = freqs
    return float(out) if np.isscalar(mel) else out


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr: int, n_fft: int):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney") -> Tensor:
    """Mel filterbank [n_mels, 1 + n_fft//2] (reference:
    compute_fbank_matrix)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights, dtype=jnp.float32))


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho"
               ) -> Tensor:
    """DCT-II matrix [n_mels, n_mfcc] (reference: create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(np.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.T, dtype=jnp.float32))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """reference: audio/functional power_to_db."""
    t = as_tensor(spect)

    def fn(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return run_op(fn, [t], name="power_to_db")
