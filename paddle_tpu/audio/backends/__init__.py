"""paddle.audio.backends (reference: python/paddle/audio/backends/ —
init_backend.py get_current_backend/list_available_backends/set_backend
over the wave backend).

Zero-dependency wave backend: stdlib ``wave`` handles 16-bit PCM WAV —
the format the reference's bundled backend supports without soundfile.
"""
from __future__ import annotations

import wave as _wave

import numpy as np

__all__ = ["get_current_backend", "list_available_backends",
           "set_backend", "AudioInfo", "info", "load", "save"]

_backend = "wave_backend"


def list_available_backends():
    out = ["wave_backend"]
    try:  # pragma: no cover - not in this image
        import soundfile  # noqa: F401

        out.append("soundfile")
    except ImportError:
        pass
    return out


def get_current_backend():
    return _backend


def set_backend(backend_name):
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name!r} not available; choose from "
            f"{list_available_backends()}")
    global _backend
    _backend = backend_name


class AudioInfo:
    """reference: audio/backends/backend.py AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    """reference: paddle.audio.info."""
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), f.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """reference: paddle.audio.load -> (Tensor [C, L] float32, sr)."""
    from ...core.tensor import Tensor
    import jax.numpy as jnp

    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n = f.getnframes()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(count)
    dtype = {1: np.int8, 2: np.int16, 4: np.int32}[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, ch)
    if normalize:
        data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(np.ascontiguousarray(arr))), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """reference: paddle.audio.save — 16-bit PCM WAV."""
    from ...core.tensor import Tensor

    a = np.asarray(src.numpy() if isinstance(src, Tensor) else src)
    if channels_first:
        a = a.T
    if a.dtype.kind == "f":
        a = np.clip(a, -1.0, 1.0)
        a = (a * (2 ** 15 - 1)).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(a.shape[1] if a.ndim == 2 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(a).tobytes())
