"""paddle.audio.datasets (reference: python/paddle/audio/datasets/ —
TESS, ESC50 over local archives).

Zero-egress: parses local extracted dataset directories when present
(wav files named per each corpus' convention); synthesizes deterministic
waveforms otherwise so pipelines run in CI.
"""
from __future__ import annotations

import os
from typing import List

import numpy as np

from ...config import knobs
from ...io import Dataset

__all__ = ["TESS", "ESC50"]


class _AudioClassDataset(Dataset):
    n_classes = 2
    sample_rate = 16000

    def __init__(self, mode="train", feat_type="raw", archive=None,
                 **feat_kwargs):
        self.mode = mode
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        self._files: List[str] = []
        self._labels: List[int] = []
        root = archive or os.path.join(
            os.path.expanduser(knobs.get_str("PADDLE_TPU_DATA_HOME")),
            self.__class__.__name__.lower())
        if os.path.isdir(root):
            self._scan(root)
        self._synth = len(self._files) == 0
        self._n = knobs.get_int("PADDLE_TPU_SYNTH_SAMPLES") \
            if self._synth else len(self._files)

    def _scan(self, root):
        raise NotImplementedError

    def _feature(self, wav):
        if self.feat_type == "raw":
            return wav.astype(np.float32)
        from .. import features as F
        import paddle_tpu as pt

        x = pt.to_tensor(wav.astype(np.float32)[None])
        extractor = {
            "spectrogram": F.Spectrogram,
            "melspectrogram": F.MelSpectrogram,
            "logmelspectrogram": F.LogMelSpectrogram,
            "mfcc": F.MFCC,
        }[self.feat_type](sr=self.sample_rate, **self.feat_kwargs) \
            if self.feat_type != "spectrogram" \
            else F.Spectrogram(**self.feat_kwargs)
        return extractor(x).numpy()[0]

    def __getitem__(self, idx):
        if self._synth:
            rng = np.random.RandomState(idx)
            label = idx % self.n_classes
            t = np.arange(self.sample_rate, dtype=np.float32) \
                / self.sample_rate
            wav = 0.3 * np.sin(2 * np.pi * (200 + 50 * label) * t) \
                + 0.05 * rng.randn(self.sample_rate).astype(np.float32)
        else:
            from ..backends import load

            sig, _ = load(self._files[idx])
            wav = sig.numpy()[0]
            label = self._labels[idx]
        return self._feature(wav), np.int32(label)

    def __len__(self):
        return self._n


class TESS(_AudioClassDataset):
    """Toronto emotional speech set (reference audio/datasets/tess.py):
    7 emotions encoded in the wav filename's last token."""

    n_classes = 7
    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad"]

    def _scan(self, root):
        for dirpath, _, files in os.walk(root):
            for fn in sorted(files):
                if not fn.lower().endswith(".wav"):
                    continue
                emo = fn.rsplit("_", 1)[-1][:-4].lower()
                if emo in self.EMOTIONS:
                    self._files.append(os.path.join(dirpath, fn))
                    self._labels.append(self.EMOTIONS.index(emo))


class ESC50(_AudioClassDataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py):
    label is the last dash field of the filename, fold the first."""

    n_classes = 50
    sample_rate = 44100

    def __init__(self, mode="train", split=1, feat_type="raw",
                 archive=None, **feat_kwargs):
        self.split = split
        super().__init__(mode=mode, feat_type=feat_type, archive=archive,
                         **feat_kwargs)

    def _scan(self, root):
        for dirpath, _, files in os.walk(root):
            for fn in sorted(files):
                if not fn.lower().endswith(".wav"):
                    continue
                parts = fn[:-4].split("-")
                if len(parts) != 4:
                    continue
                fold, target = int(parts[0]), int(parts[3])
                test_fold = fold == self.split
                if (self.mode == "train") != test_fold:
                    self._files.append(os.path.join(dirpath, fn))
                    self._labels.append(target)
