"""Audio features + IO (reference: python/paddle/audio/)."""
from . import backends, datasets, features, functional  # noqa: F401
from .backends import info, load, save  # noqa: F401
from .features import (LogMelSpectrogram, MFCC, MelSpectrogram,  # noqa: F401
                       Spectrogram)

__all__ = ["functional", "features", "backends", "datasets", "info",
           "load", "save", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
