"""Audio features (reference: python/paddle/audio/)."""
from . import features, functional  # noqa: F401
from .features import (LogMelSpectrogram, MFCC, MelSpectrogram,  # noqa: F401
                       Spectrogram)

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
