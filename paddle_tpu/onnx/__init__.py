"""paddle.onnx (reference: python/paddle/onnx/__init__.py — export via
paddle2onnx).

TPU-native interchange is StableHLO (jit.save / jax.export), which every
XLA/PJRT runtime loads directly — that is what ``export`` writes here.
Emitting the ONNX protobuf itself would require the paddle2onnx
converter stack targeting the ONNX runtime rather than XLA; with no such
converter in this image, the portable StableHLO artifact is the
supported interchange format.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` for interchange: parameters + (when input_spec is
    given) the serialized StableHLO forward program."""
    from .. import jit as _jit

    _jit.save(layer, path, input_spec=input_spec)
    return path
