"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference mounted at /root/reference), built on
JAX/XLA/Pallas. See SURVEY.md for the blueprint.

Top-level namespace mirrors ``paddle.*`` (reference:
python/paddle/__init__.py): tensor creation/math/manipulation ops, dtypes,
autograd controls, plus the ``nn`` / ``optimizer`` / ``io`` / ``distributed``
subpackages.
"""
from __future__ import annotations

from .version import full_version as __version__  # noqa: E402

from .core.dtype import (  # noqa: F401
    bfloat16,
    float8_e4m3fn,
    float8_e5m2,
    pstring,
    raw,
    bool_,
    complex128,
    complex64,
    dtype,
    float16,
    float32,
    float64,
    int16,
    int32,
    int64,
    int8,
    uint8,
)

# paddle spells bool dtype "paddle.bool"
bool = bool_  # noqa: A001

from .core.tensor import Tensor, is_tensor, to_tensor  # noqa: F401,E402
from .core.autograd import (  # noqa: F401,E402
    enable_grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .core.random import get_rng_state, seed, set_rng_state  # noqa: F401,E402
from .core import random as _random_core  # noqa: F401,E402

from .ops import *  # noqa: F401,F403,E402
from . import ops as _ops  # noqa: E402

from .core import tensor_methods as _tm  # noqa: E402

_tm.install()

from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import observability  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from .hapi import Model, summary  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from .framework.param_attr import ParamAttr  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import linalg  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from . import version  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import decomposition  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import fusion  # noqa: F401,E402
from . import models  # noqa: F401,E402
from . import serving  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from .framework.io_utils import load, save  # noqa: F401,E402
from .framework import (  # noqa: F401,E402
    get_default_dtype,
    set_default_dtype,
    get_flags,
    set_flags,
)
from .device import (  # noqa: F401,E402
    get_cudnn_version,
    get_device,
    is_compiled_with_cinn,
    set_device,
)
from .distributed.parallel import DataParallel  # noqa: F401,E402  (paddle.DataParallel)

# functional conveniences at top level, paddle-style
from .nn.functional import one_hot  # noqa: F401,E402  (paddle.nn.functional too)

CPUPlace = object
TPUPlace = object


def disable_static(place=None):
    from . import static as _static

    _static.disable_static(place)


def enable_static():
    from . import static as _static

    _static.enable_static()


def iinfo(dtype):
    import jax.numpy as jnp

    from .core.dtype import to_jax_dtype

    return jnp.iinfo(to_jax_dtype(dtype))


def finfo(dtype):
    import jax.numpy as jnp

    from .core.dtype import to_jax_dtype

    return jnp.finfo(to_jax_dtype(dtype))


def in_dynamic_mode() -> bool:
    from .core import static_flags

    return not static_flags.enabled


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=True, no_grad_vars=None):
    from .core.autograd import grad as _grad

    return _grad(outputs, inputs, grad_outputs, retain_graph, create_graph,
                 allow_unused)
