"""Parallel environment bootstrap
(reference: python/paddle/distributed/parallel.py:978 init_parallel_env).

Env contract (same var names as the reference launch):
  PADDLE_TRAINER_ID      process rank
  PADDLE_TRAINERS_NUM    world size (process count)
  PADDLE_MASTER          host:port of the TCPStore master
  PADDLE_DIST_BACKEND    cpu | xla (default: cpu off-TPU, xla on TPU multi-host)
"""
from __future__ import annotations

import os
from typing import Optional

__all__ = ["ParallelEnv", "init_parallel_env", "get_rank", "get_world_size",
           "is_initialized"]

_initialized = False
_default_group = None


class ParallelEnv:
    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = int(os.environ.get("FLAGS_selected_devices",
                                             os.environ.get(
                                                 "PADDLE_LOCAL_RANK", "0")))

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def local_rank(self):
        return self._device_id

    @property
    def device_id(self):
        return self._device_id

    @property
    def dev_id(self):
        return self._device_id

    @property
    def nranks(self):
        return self._world_size

    @property
    def current_endpoint(self):
        eps = self.trainer_endpoints
        return eps[self._rank] if self._rank < len(eps) else ""

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(ParallelEnv().rank)
    return ParallelEnv().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return group.world_size
    return ParallelEnv().world_size


def is_initialized() -> bool:
    return _initialized


def init_parallel_env(backend: Optional[str] = None):
    """reference: distributed/parallel.py:978 — global TCPStore, default
    process group, (on TPU multi-host) jax.distributed.initialize."""
    global _initialized, _default_group
    if _initialized:
        return _default_group
    env = ParallelEnv()

    import jax

    if backend is None:
        backend = os.environ.get("PADDLE_DIST_BACKEND", "")
    if not backend:
        backend = "xla" if jax.default_backend() == "tpu" and \
            env.world_size > 1 else "cpu"

    if backend == "xla" and env.world_size > 1:
        master = os.environ.get("PADDLE_MASTER", "127.0.0.1:8476")
        try:
            jax.distributed.initialize(
                coordinator_address=master,
                num_processes=env.world_size,
                process_id=env.rank)
        except Exception:
            pass  # already initialized or single-host emulation

    from . import collective as coll
    from .store import create_or_get_global_tcp_store
    from .process_group import new_process_group_impl

    if env.world_size > 1:
        store = create_or_get_global_tcp_store()
    else:
        store = None
    pg = new_process_group_impl(backend, store, env.rank, env.world_size,
                                gid=0)
    _default_group = coll._register_default_group(pg, env)
    _initialized = True
    return _default_group
