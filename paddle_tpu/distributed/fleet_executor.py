"""FleetExecutor actor runtime (reference:
paddle/fluid/distributed/fleet_executor/fleet_executor.h:36,
carrier.h:50 Carrier, interceptor.h Interceptor/ComputeInterceptor,
message_bus.h; python surface fleet_executor_utils.py TaskNode).

The reference runs static pipeline programs as an actor system: each
rank's Carrier hosts Interceptors (one per TaskNode), exchanging
DATA_IS_READY / DATA_IS_USELESS credit messages through a MessageBus
(in-process queues locally, brpc across ranks).

TPU-native analog: same actor semantics over python threads — each
Interceptor is an actor thread with a mailbox; upstream sends
DATA_IS_READY with a payload, downstream replies DATA_IS_USELESS to
return credit (buffer slots = max_run_times, the pipeline depth). The
compute a TaskNode runs is a jitted callable (the per-stage XLA program)
instead of a sub-Program, so the heavy work still happens in single XLA
dispatches; the actor layer contributes exactly what the reference's
does — dataflow sequencing and backpressure for multi-stage streaming
inference/training on one host.

Cross-rank delivery (r5): when ``init_rpc`` has run, a TaskNode whose
``rank`` differs from the executor's rank is hosted remotely —
``MessageBus.send`` routes DATA_IS_READY / DATA_IS_USELESS / STOP for
non-local tasks through the rpc agent (distributed/rpc.py), the analog
of the reference's brpc MessageBus (fleet_executor/message_bus.h).
Credit backpressure crosses ranks the same way: the downstream rank's
DATA_IS_USELESS rides rpc back to the upstream rank's interceptor.
"""
from __future__ import annotations

import queue
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional

from .. import observability as _obs

__all__ = ["TaskNode", "Interceptor", "Carrier", "MessageBus",
           "FleetExecutor"]

# executor_id -> live MessageBus on THIS process (rpc delivery target);
# messages landing before the bus exists buffer in _PENDING
_ACTIVE_BUSES: Dict[str, "MessageBus"] = {}
_PENDING: Dict[str, List["_Msg"]] = {}
_REGISTRY_LOCK = threading.Lock()


def _remote_deliver(executor_id: str, kind: str, src: int, dst: int,
                    payload, step: int, ctx=None):
    """rpc entry point on the receiving rank (reference: message_bus.cc
    DispatchMsgToCarrier)."""
    import numpy as np

    from .pipeline.transport import get_fleet_transport, \
        is_payload_descriptor

    if is_payload_descriptor(payload):
        # device-native transport: the control message carried only a
        # shape/dtype/seq descriptor — the tensor arrives via the
        # ProcessGroup p2p collective and never touches the host. The
        # recv MUST happen here (before any buffering) because the
        # sender has already launched its half of the collective.
        transport = get_fleet_transport()
        if transport is None:
            raise RuntimeError(
                "received a device-payload descriptor but no pipeline "
                "transport is registered on this rank — set "
                "PADDLE_TPU_PP_TRANSPORT consistently on every rank")
        with _obs.activate_context(ctx):
            payload = transport.recv(payload)
    elif payload is not None and not isinstance(payload, (int, float)):
        payload = np.asarray(payload)
    msg = _Msg(kind, src, dst, payload, step, ctx)
    with _REGISTRY_LOCK:
        bus = _ACTIVE_BUSES.get(executor_id)
        if bus is None or dst not in bus._boxes:
            _PENDING.setdefault(executor_id, []).append(msg)
            return True
    bus._boxes[dst].put(msg)
    return True


class _Msg:
    DATA_IS_READY = "DATA_IS_READY"
    DATA_IS_USELESS = "DATA_IS_USELESS"
    STOP = "STOP"

    def __init__(self, kind, src, dst, payload=None, step=0, ctx=None):
        self.kind = kind
        self.src = src
        self.dst = dst
        self.payload = payload
        self.step = step
        # trace context {trace_id, span_id} stamped by MessageBus.send;
        # rides rpc to the peer rank so its spans join the same trace
        self.ctx = ctx


class TaskNode:
    """reference: fleet_executor_utils.py TaskNode — one schedulable unit
    (here: a python callable, usually a jitted stage fn)."""

    def __init__(self, task_id: int, fn: Optional[Callable] = None,
                 rank: int = 0, max_run_times: int = 1,
                 node_type: str = "Compute"):
        self.task_id = task_id
        self.fn = fn
        self.rank = rank
        self.max_run_times = max_run_times
        self.node_type = node_type
        self.downstream: List[int] = []
        self.upstream: List[int] = []

    def add_downstream_task(self, task_id: int, buffs: int = 1):
        self.downstream.append(task_id)

    def add_upstream_task(self, task_id: int, buffs: int = 1):
        self.upstream.append(task_id)


class MessageBus:
    """Message router (reference message_bus.h): in-process queues for
    local interceptors, the rpc agent for tasks hosted on other ranks."""

    def __init__(self, rank: int = 0, executor_id: str = "default",
                 task_ranks: Optional[Dict[int, int]] = None):
        self.rank = rank
        self.executor_id = executor_id
        self.task_ranks = task_ranks or {}
        self._boxes: Dict[int, "queue.Queue[_Msg]"] = {}
        with _REGISTRY_LOCK:
            live = _ACTIVE_BUSES.get(executor_id)
            if live is not None:
                # a silent replacement would steal the live executor's
                # in-flight rpc traffic — fail loudly instead (release
                # the previous FleetExecutor, or pick a distinct id)
                raise RuntimeError(
                    f"MessageBus executor_id {executor_id!r} is already "
                    "active on this process; release() the previous "
                    "FleetExecutor or use a unique executor_id per run")
            _ACTIVE_BUSES[executor_id] = self

    def register(self, task_id: int) -> "queue.Queue[_Msg]":
        q = queue.Queue()
        # drain any rpc deliveries that raced ahead of this executor's
        # construction (the peer rank may start streaming immediately);
        # box insertion and backlog drain share the registry lock with
        # _remote_deliver so no message can fall between them
        with _REGISTRY_LOCK:
            backlog = _PENDING.get(self.executor_id, [])
            still = []
            for m in backlog:
                if m.dst == task_id:
                    q.put(m)
                else:
                    still.append(m)
            if still:
                _PENDING[self.executor_id] = still
            else:
                _PENDING.pop(self.executor_id, None)
            self._boxes[task_id] = q
        return q

    def close(self):
        """Unregister from the delivery registry (released executors must
        not silently swallow late rpc messages). Pending messages for
        this executor id are dropped: they belong to THIS generation's
        run, and leaving them would replay stale traffic into a future
        executor reusing the id.

        Contract for REUSING an executor_id across runs: cross-rank
        traffic still in flight at close() time can land after it and
        buffer for the next generation (messages carry no generation
        tag, matching the reference brpc bus). Callers must call
        rpc.shutdown() between runs before re-creating an executor under
        the same id — it both barriers the ranks AND kills the rpc
        dispatchers, so no queued fire-and-forget delivery can replay
        into the next generation (a plain store barrier would not drain
        those). The in-tree tests do exactly this."""
        with _REGISTRY_LOCK:
            if _ACTIVE_BUSES.get(self.executor_id) is self:
                _ACTIVE_BUSES.pop(self.executor_id, None)
                _PENDING.pop(self.executor_id, None)

    def send(self, msg: _Msg):
        if _obs.enabled():
            _obs.registry.counter(
                "fleet.messages", tags={"kind": msg.kind}).inc()
            if msg.ctx is None:
                msg.ctx = _obs.current_context()
            _obs.flight_recorder.record(
                "fleet.send", msg_kind=msg.kind, src=msg.src,
                dst=msg.dst, step=msg.step)
        box = self._boxes.get(msg.dst)
        if box is not None:
            box.put(msg)
            return
        dst_rank = self.task_ranks.get(msg.dst)
        if dst_rank is None or dst_rank == self.rank:
            raise KeyError(f"no interceptor registered for task "
                           f"{msg.dst}")
        # cross-rank: ship through the rpc agent (brpc analog); payload
        # travels as numpy, fire-and-forget like the reference's
        # async brpc Send
        import numpy as np

        from . import rpc as _rpc

        agent = _rpc._agent
        if agent is None:
            if msg.kind == _Msg.STOP:
                return  # teardown after rpc shutdown: best-effort only
            raise RuntimeError(
                f"task {msg.dst} lives on rank {dst_rank} but rpc is not "
                "initialized — call paddle.distributed.rpc.init_rpc")
        by_rank = getattr(self, "_by_rank", None)
        if by_rank is None or self._by_rank_agent is not agent:
            by_rank = {w.rank: w.name for w in agent.workers.values()}
            self._by_rank = by_rank
            self._by_rank_agent = agent
        payload = msg.payload
        if payload is not None and not isinstance(payload, (int, float)):
            from .pipeline.transport import get_fleet_transport, \
                transport_mode

            transport = get_fleet_transport()
            if transport is not None and transport_mode() != "host" \
                    and hasattr(payload, "shape") \
                    and hasattr(payload, "dtype"):
                # device-native transport: launch the p2p collective and
                # post the descriptor control message under the SAME
                # per-destination lock, so the receiver's rpc dispatcher
                # sees descriptors in collective launch order
                transport.send(
                    payload, dst_rank,
                    post=lambda desc: _rpc.rpc_async(
                        by_rank[dst_rank], _remote_deliver,
                        args=(self.executor_id, msg.kind, msg.src,
                              msg.dst, desc, msg.step, msg.ctx)))
                return
            payload = np.asarray(payload)
        _rpc.rpc_async(by_rank[dst_rank], _remote_deliver,
                       args=(self.executor_id, msg.kind, msg.src,
                             msg.dst, payload, msg.step, msg.ctx))


class Interceptor(threading.Thread):
    """Actor for one TaskNode (reference interceptor.h
    ComputeInterceptor): consumes one ready input per upstream, runs the
    node fn, emits to downstreams, returns credit upstream."""

    def __init__(self, node: TaskNode, bus: MessageBus, results: list):
        super().__init__(daemon=True,
                         name=f"interceptor-{node.task_id}")
        self.node = node
        self.bus = bus
        self.box = bus.register(node.task_id)
        self.results = results
        self._credits = {d: node.max_run_times for d in node.downstream}
        self._pending: Dict[int, "queue.Queue"] = {}
        self._stop = False
        self.steps_run = 0

    def run(self):
        # a source node's "upstream" is the external feeder (id -1)
        ups = list(self.node.upstream) or [-1]
        ready: Dict[int, list] = {u: [] for u in ups}
        stall_since = None  # inputs ready, downstream credit exhausted
        while not self._stop:
            msg = self.box.get()
            if msg.kind == _Msg.STOP:
                # propagate to downstream actors once per edge;
                # best-effort — a peer rank may already be torn down
                for d in self.node.downstream:
                    try:
                        self.bus.send(_Msg(_Msg.STOP, self.node.task_id,
                                           d))
                    except Exception:
                        pass
                return
            if msg.kind == _Msg.DATA_IS_USELESS:
                if msg.src in self._credits:
                    self._credits[msg.src] += 1
            elif msg.kind == _Msg.DATA_IS_READY:
                if msg.src not in ready:
                    # stale/misrouted traffic must not kill the actor
                    # thread (the pipeline would hang instead of erroring
                    # at the timeout with a diagnosable state)
                    warnings.warn(
                        f"interceptor {self.node.task_id}: dropping "
                        f"message from unknown upstream {msg.src}")
                    continue
                ready[msg.src].append(msg)
            # fire when every upstream has a ready item and every
            # downstream has a credit slot
            while ups and all(ready[u] for u in ups) and all(
                    c > 0 for c in self._credits.values()):
                if stall_since is not None:
                    _obs.registry.counter("fleet.credit_stall_s").inc(
                        time.perf_counter() - stall_since)
                    stall_since = None
                ins = [ready[u].pop(0) for u in ups]
                step = ins[0].step
                # adopt the upstream's trace context: this node's span
                # (and every message it emits) joins the trace the feed
                # started, across ranks — the Perfetto stitch point
                with _obs.activate_context(ins[0].ctx):
                    with _obs.span("fleet.node", cat="fleet",
                                   args={"task": self.node.task_id,
                                         "step": step}):
                        out = self.node.fn(*[m.payload for m in ins]) \
                            if self.node.fn else ins[0].payload
                        self.steps_run += 1
                        for m in ins:  # return credit upstream (not
                            if m.src >= 0:  # the feeder)
                                self.bus.send(
                                    _Msg(_Msg.DATA_IS_USELESS,
                                         self.node.task_id, m.src))
                        if self.node.downstream:
                            for d in self.node.downstream:
                                self._credits[d] -= 1
                                self.bus.send(
                                    _Msg(_Msg.DATA_IS_READY,
                                         self.node.task_id, d, out,
                                         step))
                        else:  # sink
                            self.results.append(
                                (step, self.node.task_id, out))
            if _obs.enabled() and stall_since is None and ups and \
                    all(ready[u] for u in ups) and any(
                        c <= 0 for c in self._credits.values()):
                # ready to fire but blocked on downstream credit — the
                # pipeline-backpressure time the bubble metric can't see
                stall_since = time.perf_counter()

    def stop(self):
        self._stop = True
        self.box.put(_Msg(_Msg.STOP, -1, self.node.task_id))


class Carrier:
    """Hosts this rank's interceptors (reference carrier.h:50)."""

    def __init__(self, rank: int = 0, executor_id: str = "default",
                 task_ranks: Optional[Dict[int, int]] = None):
        self.rank = rank
        self.bus = MessageBus(rank, executor_id, task_ranks)
        self.interceptors: Dict[int, Interceptor] = {}
        self.results: list = []

    def create_interceptor(self, node: TaskNode) -> Interceptor:
        ic = Interceptor(node, self.bus, self.results)
        self.interceptors[node.task_id] = ic
        return ic

    def start(self):
        for ic in self.interceptors.values():
            ic.start()

    def wait(self, n_results: int, timeout: float = 60.0):
        import time

        t0 = time.time()
        while len(self.results) < n_results:
            if time.time() - t0 > timeout:
                raise TimeoutError(
                    f"FleetExecutor: {len(self.results)}/{n_results} "
                    "results after timeout")
            time.sleep(0.001)

    def release(self):
        for ic in self.interceptors.values():
            ic.stop()
        self.bus.close()


class FleetExecutor:
    """reference fleet_executor.h:36 — build the task graph, run N
    micro-batches through the actor pipeline, collect sink outputs."""

    def __init__(self, task_nodes: List[TaskNode], rank: int = 0,
                 executor_id: str = "default"):
        self.nodes = {n.task_id: n for n in task_nodes}
        self.rank = rank
        # validate + wire upstream lists BEFORE registering the message
        # bus: a constructor failure after registration would leak the
        # executor_id (release() is unreachable on a half-built object)
        for n in task_nodes:
            for d in n.downstream:
                if d not in self.nodes:
                    raise KeyError(
                        f"task {n.task_id} declares downstream {d} "
                        "which is not in the task graph")
                if n.task_id not in self.nodes[d].upstream:
                    self.nodes[d].upstream.append(n.task_id)
        task_ranks = {n.task_id: n.rank for n in task_nodes}
        if any(n.rank != rank for n in task_nodes):
            # cross-rank graph: register the device payload transport up
            # front (when a collective group exists and the knob allows)
            # so array payloads ride ProcessGroup p2p — the store/rpc
            # bus keeps only control messages + descriptors
            from .pipeline.transport import ensure_fleet_transport

            ensure_fleet_transport()
        self.carrier = Carrier(rank, executor_id, task_ranks)
        # host only THIS rank's interceptors; other ranks run their own
        # FleetExecutor over the same graph (reference: each rank's
        # Carrier holds its TaskNodes, the bus crosses ranks)
        for n in task_nodes:
            if n.rank == rank:
                self.carrier.create_interceptor(n)
        self._sources = [n for n in task_nodes
                         if not n.upstream and n.rank == rank]
        self._sinks = [n for n in task_nodes
                       if not n.downstream and n.rank == rank]
        self._started = False

    def run(self, feeds: List[Any], timeout: float = 60.0,
            n_results: Optional[int] = None) -> List[Any]:
        """Stream ``feeds`` (one per micro-batch) through the graph;
        returns LOCAL sink outputs in micro-batch order (a rank hosting
        no sink returns [] immediately — its interceptors keep serving
        the pipeline in the background)."""
        if not self._started:
            self.carrier.start()
            self._started = True
        self.carrier.results.clear()
        with _obs.span("fleet.run", cat="fleet",
                       args={"rank": self.rank, "feeds": len(feeds)}):
            # feed with backpressure honoring the source's declared
            # depth; sends stamp the fleet.run span's trace context, so
            # every downstream fire (local or cross-rank) stitches into
            # one trace per run
            if self._sources:
                src = self._sources[0]
                for step, payload in enumerate(feeds):
                    self.carrier.bus.send(
                        _Msg(_Msg.DATA_IS_READY, -1, src.task_id,
                             payload, step))
            # -1 credits: the source treats feeder credit as infinite
            if n_results is None:
                n_results = len(feeds) * len(self._sinks)
            self.carrier.wait(n_results, timeout)
        # key on (step, sink id) — deterministic across thread schedules,
        # and payloads (jax arrays) never enter the comparison
        out = sorted(self.carrier.results, key=lambda r: (r[0], r[1]))
        return [o for _, _, o in out]

    def release(self):
        self.carrier.release()