"""paddle_tpu.distributed (reference: python/paddle/distributed/).

TPU-native distributed stack:
- environment / rank info over jax.distributed + jax process indices
- collective API operating on DistTensors / sharded arrays (compiled XLA
  collectives over ICI/DCN — the ProcessGroupXLA concept from SURVEY §5)
- Fleet hybrid parallel (topology/HCG, TP/PP/sharding wrappers)
- semi-auto parallel (ProcessMesh, shard_tensor, reshard, DistTensor)
"""
from __future__ import annotations

from .parallel_env import (  # noqa: F401
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)
from .collective import (  # noqa: F401
    Group,
    P2POp,
    all_gather,
    all_gather_object,
    all_reduce,
    batch_isend_irecv,
    broadcast_object_list,
    gather,
    get_backend,
    scatter_object_list,
    stream,
    all_to_all,
    alltoall,
    barrier,
    broadcast,
    destroy_process_group,
    get_group,
    irecv,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    split_group,
    wait,
    ReduceOp,
)
from .auto_parallel.api import (  # noqa: F401
    DistAttr,
    dtensor_from_fn,
    dtensor_from_local,
    local_value,
    reshard,
    shard_dataloader,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    unshard_dtensor,
)
from .auto_parallel.api import to_static  # noqa: F401
from .auto_parallel.engine import DistModel, Engine, Strategy  # noqa: F401
from .auto_parallel.process_mesh import ProcessMesh  # noqa: F401
from .auto_parallel.placement_type import (  # noqa: F401
    Partial,
    Placement,
    Replicate,
    Shard,
)
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from . import resilience  # noqa: F401
from . import sequence_parallel  # noqa: F401
from . import rpc  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
    ulysses_attention_sharded,
)
from .parallel import DataParallel  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from . import launch  # noqa: F401
from .spawn import spawn  # noqa: F401

# ---- round-4 parity exports (reference distributed/__init__.py __all__) ----
from . import io  # noqa: F401
from .extras import (  # noqa: F401
    CountFilterEntry,
    EntryAttr,
    ProbabilityEntry,
    ReduceType,
    ShowClickEntry,
    alltoall_single,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
    is_available,
    shard_scaler,
    split,
)
from .fleet_dataset import InMemoryDataset, QueueDataset  # noqa: F401
from .fleet.topology import ParallelMode  # noqa: F401
from .auto_parallel.api import (  # noqa: F401
    ShardingStage1,
    ShardingStage2,
    ShardingStage3,
)
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
from . import fleet_executor  # noqa: F401,E402
from . import passes  # noqa: F401,E402
from .auto_parallel import cluster as _cluster  # noqa: F401,E402
from .auto_parallel import cost_model as _cost_model  # noqa: F401,E402
