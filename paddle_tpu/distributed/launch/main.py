"""python -m paddle_tpu.distributed.launch (reference:
python/paddle/distributed/launch/main.py:23; CollectiveController.build_pod
launch/controllers/collective.py:37,262; restart policy --max_restart;
elastic relaunch fleet/elastic/manager.py:457-530).

TPU-native process model: ONE process per host (jax owns all local chips);
--nproc_per_node>1 supported for the CPU-backend test mode. Env contract
matches the reference (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_TRAINER_ENDPOINTS, PADDLE_MASTER).

Multi-node rendezvous (real, not fabricated): the launcher whose bind on
the --master port wins hosts the TCPStore master daemon; every node
(auto-)assigns its node rank via store ADD, publishes its *real* worker
endpoints under ``launch/{job}/g{gen}/node/{rank}``, barriers on all
nodes, and builds the global rank/endpoint table from what was published —
the reference's master-KV build_pod flow over our own store.

Restart: a non-zero worker exit bumps the shared restart generation
(store ADD); every launcher polls the generation, kills its pod, and
re-runs rendezvous under the new generation, up to --max_restart times.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _free_port(host="127.0.0.1"):
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _advertise_ip(master_host: str) -> str:
    """The IP peers can reach us on: the one routing toward the master."""
    if master_host in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect((master_host, 9))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return socket.gethostbyname(socket.gethostname())


class GenerationChanged(Exception):
    """A newer restart generation superseded the one being rendezvoused."""

    def __init__(self, gen: int):
        super().__init__(f"superseded by generation {gen}")
        self.gen = gen


class _Rendezvous:
    """Store-backed node rendezvous + restart-generation channel."""

    def __init__(self, master: str, nnodes: int, job_id: str,
                 node_rank: int, timeout: float = 900.0):
        from ..store import TCPStore

        host, port = master.rsplit(":", 1)
        self.job = job_id
        self.nnodes = nnodes
        self.timeout = timeout
        # the rank-0 contender hosts the daemon; everyone else connects.
        # With an explicit --rank we know who we are; with auto-assign the
        # machine that can bind the master address decides (binding the
        # master's concrete IP fails with EADDRNOTAVAIL on other hosts)
        is_master = node_rank == 0
        if node_rank < 0:
            try:
                probe = socket.socket()
                probe.bind((host if host != "localhost" else "127.0.0.1",
                            int(port)))
                probe.close()
                is_master = True
            except OSError:
                is_master = False
        try:
            self.store = TCPStore(host, int(port), is_master=is_master,
                                  world_size=nnodes, timeout=timeout)
        except OSError:
            # lost the probe->bind race to a same-host peer: be a client
            self.store = TCPStore(host, int(port), is_master=False,
                                  world_size=nnodes, timeout=timeout)
        if node_rank < 0:
            node_rank = self.store.add(f"launch/{self.job}/nodes", 1) - 1
        self.node_rank = node_rank

    def exchange_endpoints(self, gen: int, endpoints: list[str]) -> dict:
        """Publish our endpoints, wait for all nodes, return
        {node_rank: [endpoints]} (reference: build_pod master-KV sync).

        Waits in short slices and aborts with :class:`GenerationChanged`
        if the restart counter moves past ``gen`` — two nodes failing
        concurrently would otherwise rendezvous under different
        generations and deadlock until the full timeout."""
        key = f"launch/{self.job}/g{gen}/node/{self.node_rank}"
        self.store.set(key, json.dumps(endpoints).encode())
        peers = {}
        deadline = time.time() + self.timeout
        for r in range(self.nnodes):
            k = f"launch/{self.job}/g{gen}/node/{r}"
            while True:
                if self.store.check(k):
                    break
                cur = self.restart_gen()
                if cur > gen:
                    raise GenerationChanged(cur)
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rendezvous g{gen}: node {r} never published")
                time.sleep(0.2)
            peers[r] = json.loads(self.store.get(k).decode())
        return peers

    def mark_done(self, gen: int) -> int:
        """Count this node's workers as finished for generation ``gen``
        (generation-scoped so a restart starts the count afresh)."""
        return self.store.add(f"launch/{self.job}/g{gen}/done", 1)

    def finish_done_count(self, gen: int) -> int:
        return self.store.add(f"launch/{self.job}/g{gen}/done", 0)

    def restart_gen(self) -> int:
        return self.store.add(f"launch/{self.job}/restart", 0)

    def bump_restart(self) -> int:
        return self.store.add(f"launch/{self.job}/restart", 1)

    def regenerate(self, gen: int):
        """Re-register for a restart generation: fresh contiguous node
        ranks (a dead node leaves no hole) and a possibly-scaled node
        count (reference: elastic manager scale-in/out :484-530). Returns
        (gen_rank, gen_nnodes); gen_rank >= gen_nnodes means this node
        was scaled in and should exit."""
        nnodes = self.nnodes
        if self.store.check("elastic/num_nodes"):
            nnodes = int(self.store.get("elastic/num_nodes").decode())
        rank = self.store.add(f"launch/{self.job}/g{gen}/nodes", 1) - 1
        self.node_rank = rank
        self.nnodes = nnodes
        return rank, nnodes


def _spawn_pod(args, node_rank, nproc, world, rank_base, master, endpoints,
               gen):
    """Start this node's worker processes with the launch env contract."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    extra_path = pkg_root + (os.pathsep + os.environ["PYTHONPATH"]
                             if os.environ.get("PYTHONPATH") else "")
    procs = []
    os.makedirs(args.log_dir, exist_ok=True)
    for local_rank in range(nproc):
        rank = rank_base + local_rank
        env = dict(os.environ)
        env["PYTHONPATH"] = extra_path
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": master,
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_NODE_RANK": str(node_rank),
            "PADDLE_RESTART_GEN": str(gen),
            "FLAGS_selected_devices": str(local_rank),
        })
        if args.store_hosted:
            env["PADDLE_STORE_HOSTED"] = "1"
        if args.backend:
            env["PADDLE_DIST_BACKEND"] = args.backend
        log_file = os.path.join(args.log_dir, f"workerlog.{rank}")
        with open(log_file, "ab") as lf:
            p = subprocess.Popen(
                [sys.executable, args.training_script]
                + args.training_script_args,
                env=env, stdout=lf if world > 1 else None,
                stderr=subprocess.STDOUT if world > 1 else None)
        procs.append(p)
    return procs


def _kill_pod(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + 10
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def launch(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (reference: "
                    "python -m paddle.distributed.launch)")
    parser.add_argument("--nnodes", type=str, default="1")
    parser.add_argument("--nproc_per_node", type=int, default=None)
    parser.add_argument("--master", type=str, default=None)
    parser.add_argument("--rank", type=int, default=-1)
    parser.add_argument("--run_mode", type=str, default="collective")
    parser.add_argument("--job_id", type=str, default="default")
    parser.add_argument("--devices", "--gpus", type=str, default=None)
    parser.add_argument("--log_dir", type=str, default="log")
    parser.add_argument("--log_level", type=str, default="INFO")
    parser.add_argument("--max_restart", type=int, default=3)
    parser.add_argument("--rdv_timeout", type=float, default=900.0,
                        help="rendezvous/finish barrier wait (seconds)")
    parser.add_argument("--backend", type=str, default=None)
    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    nnodes = int(str(args.nnodes).split(":")[0])
    nproc = args.nproc_per_node or 1

    multi_node = nnodes > 1 or args.master is not None
    args.store_hosted = multi_node
    rdv = None
    if multi_node:
        master = args.master or f"127.0.0.1:{_free_port()}"
        rdv = _Rendezvous(master, nnodes, args.job_id, args.rank,
                          timeout=args.rdv_timeout)
        node_rank = rdv.node_rank
    else:
        master = args.master or f"127.0.0.1:{_free_port()}"
        node_rank = max(args.rank, 0)

    world = nnodes * nproc
    procs: list = []
    current_gen = rdv.restart_gen() if rdv else 0
    restarts_used = 0

    def _build_and_spawn(gen):
        if rdv is not None:
            if gen > 0:
                # restart generation: re-register for fresh contiguous
                # node ranks + possibly-scaled node count (dead/scaled-in
                # nodes leave no hole in the new rendezvous)
                gen_rank, gen_nnodes = rdv.regenerate(gen)
                if gen_rank >= gen_nnodes:
                    _kill_pod(procs)
                    sys.exit(0)  # scaled in
            ip = _advertise_ip(master.rsplit(":", 1)[0])
            mine = [f"{ip}:{_free_port()}" for _ in range(nproc)]
            peers = rdv.exchange_endpoints(gen, mine)
            ordered = [ep for r in sorted(peers) for ep in peers[r]]
            endpoints = ",".join(ordered)
            gen_world = len(ordered)
            rank_base = sum(len(peers[r]) for r in sorted(peers)
                            if r < rdv.node_rank)
            return _spawn_pod(args, rdv.node_rank, nproc, gen_world,
                              rank_base, master, endpoints, gen)
        endpoints = ",".join(
            f"127.0.0.1:{_free_port()}" for _ in range(world))
        rank_base = node_rank * nproc
        return _spawn_pod(args, node_rank, nproc, world, rank_base, master,
                          endpoints, gen)

    def _spawn_gen(gen):
        """Rendezvous+spawn, following generation bumps that land while we
        wait (one logical fault = one restart, however many nodes bump)."""
        while True:
            try:
                return gen, _build_and_spawn(gen)
            except GenerationChanged as e:
                gen = e.gen

    current_gen, procs = _spawn_gen(current_gen)

    def _terminate(code=1, *_):
        _kill_pod(procs)
        sys.exit(code if isinstance(code, int) and code else 1)

    signal.signal(signal.SIGINT, _terminate)
    signal.signal(signal.SIGTERM, _terminate)

    exit_code = 0
    local_done = False
    done_marked = False
    done_deadline = None
    try:
        while True:
            time.sleep(0.2)
            # cross-node restart signal (another node's worker died /
            # elastic manager bumped the generation): kill + re-rendezvous.
            # A node whose own workers already finished STAYS in this loop
            # until every node is done, so it rejoins a restart generation
            # instead of deadlocking peers (pod-restart semantics: the
            # whole job re-runs, as in the reference --max_restart policy).
            if rdv is not None:
                gen = rdv.restart_gen()
                if gen > current_gen:
                    if restarts_used >= args.max_restart:
                        sys.exit(1)
                    restarts_used += 1
                    _kill_pod(procs)
                    current_gen, procs = _spawn_gen(gen)
                    local_done = done_marked = False
                    continue

            if local_done:
                if rdv.finish_done_count(current_gen) >= rdv.nnodes:
                    break
                if time.time() > done_deadline:
                    # a peer died without marking done: our work succeeded,
                    # don't hang forever (bounded by --rdv_timeout)
                    break
                continue

            statuses = [p.poll() for p in procs]
            failed = [r for r in statuses if r not in (None, 0)]
            if failed:
                if restarts_used < args.max_restart:
                    restarts_used += 1
                    _kill_pod(procs)
                    if rdv is not None:
                        # take the max of our bump and the live counter so
                        # a concurrent peer failure doesn't look like a
                        # *new* generation next poll (one fault, one
                        # restart)
                        current_gen = max(rdv.bump_restart(),
                                          rdv.restart_gen())
                        current_gen, procs = _spawn_gen(current_gen)
                    else:
                        procs = _build_and_spawn(current_gen)
                    continue
                exit_code = failed[0]
                if rdv is not None:
                    # signal peers: their pods must not wait forever on a
                    # dead member — the bump makes them restart and, once
                    # their own budget is exhausted, exit too
                    try:
                        rdv.bump_restart()
                    except Exception:
                        pass
                break
            if all(r == 0 for r in statuses):
                if rdv is None:
                    break
                if not done_marked:
                    rdv.mark_done(current_gen)
                    done_marked = True
                local_done = True
                done_deadline = time.time() + rdv.timeout
    except SystemExit:
        raise
    except Exception:
        # a dead store / broken rendezvous must not orphan the pod
        exit_code = exit_code or 1
        raise
    finally:
        _kill_pod(procs)
    sys.exit(exit_code)


def main():
    launch()


if __name__ == "__main__":
    main()
