"""python -m paddle_tpu.distributed.launch (reference:
python/paddle/distributed/launch/main.py:23; CollectiveController.build_pod
launch/controllers/collective.py:37).

TPU-native process model: ONE process per host (jax owns all local chips);
--nproc_per_node>1 supported for the CPU-backend test mode (each proc gets
PADDLE_TRAINER_ID). Env contract matches the reference (PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS, PADDLE_MASTER).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (reference: "
                    "python -m paddle.distributed.launch)")
    parser.add_argument("--nnodes", type=str, default="1")
    parser.add_argument("--nproc_per_node", type=int, default=None)
    parser.add_argument("--master", type=str, default=None)
    parser.add_argument("--rank", type=int, default=-1)
    parser.add_argument("--run_mode", type=str, default="collective")
    parser.add_argument("--job_id", type=str, default="default")
    parser.add_argument("--devices", "--gpus", type=str, default=None)
    parser.add_argument("--log_dir", type=str, default="log")
    parser.add_argument("--log_level", type=str, default="INFO")
    parser.add_argument("--max_restart", type=int, default=3)
    parser.add_argument("--backend", type=str, default=None)
    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    nnodes = int(str(args.nnodes).split(":")[0])
    nproc = args.nproc_per_node or 1
    world = nnodes * nproc

    master = args.master or f"127.0.0.1:{_free_port()}"
    node_rank = args.rank if args.rank >= 0 else 0

    os.makedirs(args.log_dir, exist_ok=True)
    procs = []
    endpoints = ",".join(
        f"127.0.0.1:{_free_port()}" for _ in range(world))

    # make paddle_tpu importable in workers regardless of their cwd
    # (`python script.py` puts the script dir, not the launcher cwd, on
    # sys.path)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    extra_path = pkg_root + (os.pathsep + os.environ["PYTHONPATH"]
                             if os.environ.get("PYTHONPATH") else "")

    for local_rank in range(nproc):
        rank = node_rank * nproc + local_rank
        env = dict(os.environ)
        env["PYTHONPATH"] = extra_path
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": master,
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_LOCAL_RANK": str(local_rank),
            "FLAGS_selected_devices": str(local_rank),
        })
        if args.backend:
            env["PADDLE_DIST_BACKEND"] = args.backend
        log_file = os.path.join(args.log_dir,
                                f"workerlog.{rank}")
        with open(log_file, "ab") as lf:
            p = subprocess.Popen(
                [sys.executable, args.training_script]
                + args.training_script_args,
                env=env, stdout=lf if world > 1 else None,
                stderr=subprocess.STDOUT if world > 1 else None)
        procs.append(p)

    def _terminate(code=1, *_):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        sys.exit(code if isinstance(code, int) and code else 1)

    signal.signal(signal.SIGINT, _terminate)
    signal.signal(signal.SIGTERM, _terminate)

    exit_code = 0
    try:
        while True:
            alive = False
            for p in procs:
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    exit_code = ret
                    _terminate(ret)  # propagate the worker's exit code
            if not alive:
                break
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    sys.exit(exit_code)


def main():
    launch()


if __name__ == "__main__":
    main()
