"""Semi-auto parallel API: shard_tensor / reshard / shard_layer /
shard_optimizer (reference: python/paddle/distributed/auto_parallel/api.py —
shard_tensor:219, reshard:717, shard_layer:828, shard_optimizer:1660).

TPU-native realization: a DistTensor is an eager Tensor whose payload is a
*global* jax.Array with a NamedSharding over the ProcessMesh's jax Mesh.
SPMD propagation through ops is XLA GSPMD's job (per-op sharding rules ==
the reference's phi/infermeta/spmd_rules/, realized by the compiler), and
reshard is ``jax.device_put`` with the target sharding — XLA emits the
all-gather / all-to-all / slice exactly like the reference's reshard
functions (s_to_r = AllGather etc., s_to_r_reshard_function.cc:46).

``Partial`` is represented as a hidden leading "pending-sum" axis sharded
over the partial mesh axis; reshard materializes the reduction.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor
from .placement_type import Partial, Placement, Replicate, Shard
from .process_mesh import ProcessMesh

__all__ = ["DistAttr", "shard_tensor", "dtensor_from_fn", "dtensor_from_local",
           "reshard", "shard_layer", "shard_optimizer", "unshard_dtensor",
           "ShardingStage1", "ShardingStage2", "ShardingStage3", "to_static",
           "local_value", "shard_dataloader"]


class DistAttr:
    """Sharding metadata attached to a Tensor (reference: TensorDistAttr,
    phi/core/distributed/auto_parallel/dist_attr.h)."""

    def __init__(self, mesh: ProcessMesh, placements: Sequence[Placement]):
        self.process_mesh = mesh
        self.placements = list(placements)

    def __repr__(self):
        return f"DistAttr(mesh={self.process_mesh}, placements={self.placements})"

    @property
    def dims_mapping(self):
        # tensor-dim -> mesh-axis mapping (reference dims_mapping convention)
        mapping = {}
        for axis, p in enumerate(self.placements):
            if isinstance(p, Shard):
                mapping[p.dim] = axis
        return mapping


def _spec_for(placements, mesh: ProcessMesh, ndim: int) -> PartitionSpec:
    """placements[i] describes mesh axis i; build a per-tensor-dim spec."""
    per_dim: List[Optional[object]] = [None] * ndim
    for axis, p in enumerate(placements):
        if isinstance(p, Shard):
            name = mesh.dim_names[axis]
            if per_dim[p.dim] is None:
                per_dim[p.dim] = name
            elif isinstance(per_dim[p.dim], tuple):
                per_dim[p.dim] = per_dim[p.dim] + (name,)
            else:
                per_dim[p.dim] = (per_dim[p.dim], name)
    return PartitionSpec(*per_dim)


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 place=None, stop_gradient=None) -> Tensor:
    """reference: auto_parallel/api.py:219."""
    t = data if isinstance(data, Tensor) else Tensor(jnp.asarray(data))
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError(
            "shard_tensor with Partial: use dtensor_from_local")
    jmesh = mesh.get_jax_mesh()
    spec = _spec_for(placements, mesh, t.ndim)
    sharded = jax.device_put(t._data, NamedSharding(jmesh, spec))
    out = Tensor(sharded, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient)
    # preserve Parameter-ness for optimizer plumbing
    if hasattr(t, "trainable"):
        out.stop_gradient = not t.trainable
    out._dist_attr = DistAttr(mesh, placements)
    if isinstance(data, Tensor):
        data._data = sharded
        data._dist_attr = out._dist_attr
        return data
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def dtensor_from_local(local_tensor, mesh: ProcessMesh, placements) -> Tensor:
    """reference: auto_parallel/api.py:631. Builds the global DistTensor
    from this process's local shard."""
    t = local_tensor if isinstance(local_tensor, Tensor) \
        else Tensor(jnp.asarray(local_tensor))
    multiproc = jax.process_count() > 1
    partial_axes = [i for i, p in enumerate(placements)
                    if isinstance(p, Partial)]
    if partial_axes:
        # hidden pending-sum representation: stack local values on a leading
        # axis sharded over the partial mesh axis
        axis = partial_axes[0]
        n = mesh.shape[axis]
        if multiproc:
            # every rank contributes ITS unreduced value along its slots
            # of the hidden axis (the true SPMD semantics of Partial).
            # A process owning D devices on the partial axis provides D
            # slots of t/D so the global sum is still sum_p(t_p).
            from jax.experimental import multihost_utils

            jmesh = mesh.get_jax_mesh()
            spec = _partial_hidden_spec(mesh, placements, t.ndim + 1)
            me = jax.process_index()
            axdevs = np.moveaxis(jmesh.devices, axis, 0)
            own = [i for i in range(axdevs.shape[0])
                   if any(d.process_index == me
                          for d in np.ravel(axdevs[i]))]
            d_local = max(len(own), 1)
            local = jnp.broadcast_to(t._data[None] / d_local,
                                     (d_local,) + tuple(t.shape))
            garr = multihost_utils.host_local_array_to_global_array(
                local, jmesh, spec)
            out = Tensor(garr, stop_gradient=t.stop_gradient)
            out._dist_attr = DistAttr(mesh, placements)
            out._dist_attr._partial_hidden = True
            return out
        stacked = jnp.broadcast_to(t._data[None] / n,
                                   (n,) + tuple(t.shape))
        return _place_partial_hidden(stacked, mesh, placements,
                                     t.stop_gradient)
    jmesh = mesh.get_jax_mesh()
    spec = _spec_for(placements, mesh, t.ndim)
    if multiproc:
        # true multi-process SPMD: the global array is assembled from the
        # per-rank shards (reference semantics of dtensor_from_local,
        # auto_parallel/api.py:631) — NOT by treating local as global
        from jax.experimental import multihost_utils

        garr = multihost_utils.host_local_array_to_global_array(
            t._data, jmesh, spec)
        out = Tensor(garr, stop_gradient=t.stop_gradient)
        out._dist_attr = DistAttr(mesh, placements)
        return out
    # local -> global: in single-process mode the "local" value is the shard
    # of a global array; reconstruct by tiling/concatenation semantics.
    # Single-controller: treat local as the global (tests construct global).
    out = Tensor(jax.device_put(t._data, NamedSharding(jmesh, spec)),
                 stop_gradient=t.stop_gradient)
    out._dist_attr = DistAttr(mesh, placements)
    return out


@functools.lru_cache(maxsize=None)
def _replicated_identity(jmesh):
    """Cached compiled all-gather-to-replicated over a mesh (fresh
    lambdas per call would defeat the jit cache)."""
    return jax.jit(lambda x: x,
                   out_shardings=NamedSharding(jmesh, PartitionSpec()))


def _shift_shard(p, by):
    if isinstance(p, Shard):
        return Shard(p.dim + by)
    return p


def _partial_hidden_spec(mesh, placements, ndim):
    """Spec for the hidden-pending-sum layout: Shard(0) over the (first)
    partial mesh axis, other placements shifted by one dim."""
    axis = next(i for i, p in enumerate(placements)
                if isinstance(p, Partial))
    eff = [Shard(0) if i == axis else
           (Replicate() if isinstance(p, Partial) else _shift_shard(p, 1))
           for i, p in enumerate(placements)]
    return _spec_for(eff, mesh, ndim)


def _place_partial_hidden(stacked, mesh, placements, stop_gradient):
    """Shared hidden-pending-sum construction: ``stacked`` is
    [n, *shape] where slot values sum to the logical tensor."""
    jmesh = mesh.get_jax_mesh()
    spec = _partial_hidden_spec(mesh, placements, stacked.ndim)
    out = Tensor(jax.device_put(stacked, NamedSharding(jmesh, spec)),
                 stop_gradient=stop_gradient)
    out._dist_attr = DistAttr(mesh, placements)
    out._dist_attr._partial_hidden = True
    return out


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """reference: auto_parallel/api.py:717 + the 30 reshard functions under
    phi/core/distributed/auto_parallel/reshard/. XLA emits the transfer."""
    t = dist_tensor
    attr = t._dist_attr
    data = t._data
    if attr is not None and getattr(attr, "_partial_hidden", False):
        # materialize pending sum first (p->r / p->s: AllReduce or
        # ReduceScatter, reference p_to_r_reshard_function.cc)
        data = jnp.sum(data, axis=0)
    if any(isinstance(p, Partial) for p in placements):
        # r->p (reference r_to_p_reshard_function.cc): the value lives on
        # one rank of the partial axis, zeros elsewhere — hidden-axis form:
        # slot 0 = value, other slots = 0, Shard(0) over the partial axis
        axis = next(i for i, p in enumerate(placements)
                    if isinstance(p, Partial))
        n = mesh.shape[axis]
        stacked = jnp.concatenate(
            [data[None], jnp.zeros((n - 1,) + tuple(data.shape),
                                   data.dtype)], axis=0)
        return _place_partial_hidden(stacked, mesh, placements,
                                     t.stop_gradient)
    jmesh = mesh.get_jax_mesh()
    spec = _spec_for(placements, mesh, data.ndim)
    from ...core.autograd import run_op

    tmp = Tensor(data, stop_gradient=t.stop_gradient)
    out = run_op(
        lambda a: jax.lax.with_sharding_constraint(
            a, NamedSharding(jmesh, spec)) if isinstance(
            a, jax.core.Tracer) else jax.device_put(
            a, NamedSharding(jmesh, spec)),
        [tmp], name="reshard")
    out._dist_attr = DistAttr(mesh, placements)
    return out


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather to a fully replicated dense tensor (reference:
    auto_parallel/api.py unshard_dtensor)."""
    data = dist_tensor._data
    attr = dist_tensor._dist_attr
    if isinstance(data, jax.Array) and not isinstance(
            data, jax.core.Tracer) and not data.is_fully_addressable:
        # multi-process: all-gather to replicated via a compiled identity
        # (device_get cannot read non-addressable shards). The mesh comes
        # from the array's own sharding so op outputs (attr=None) work.
        data = _replicated_identity(data.sharding.mesh)(data)
        data = data.addressable_shards[0].data
    if attr is not None and getattr(attr, "_partial_hidden", False):
        data = jnp.sum(data, axis=0)
    out = Tensor(jax.device_get(data) if not isinstance(
        data, jax.core.Tracer) else data,
        stop_gradient=dist_tensor.stop_gradient)
    return out


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """reference: auto_parallel/api.py:828."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in list(sublayer._parameters.items()):
                if p is None:
                    continue
                shard_tensor(p, mesh,
                             [Replicate()] * mesh.ndim)

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inp, out: output_fn(out, process_mesh))
    return layer


class _ShardingStage:
    def __init__(self, mesh_dim=None, mesh=None):
        self.mesh_dim = mesh_dim or "dp"
        self.mesh = mesh


class ShardingStage1(_ShardingStage):
    pass


class ShardingStage2(_ShardingStage):
    pass


class ShardingStage3(_ShardingStage):
    pass


class _ShardOptimizer:
    """Wraps an Optimizer so optimizer states inherit parameter shardings
    (jnp.*_like preserves sharding) and, for ShardingStage*, states are
    sharded along the dp axis (ZeRO; reference: api.py:1349-1561)."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        shard_fn = self._shard_fn
        if isinstance(shard_fn, (ShardingStage1, ShardingStage2,
                                 ShardingStage3)) and shard_fn.mesh is not None:
            mesh = shard_fn.mesh
            axis = mesh.dim_names.index(shard_fn.mesh_dim) \
                if shard_fn.mesh_dim in mesh.dim_names else 0
            jmesh = mesh.get_jax_mesh()
            name = mesh.dim_names[axis]
            for accname, slot in self._inner._accumulators.items():
                for pid, arr in slot.items():
                    if arr.ndim == 0:
                        continue
                    # shard state dim 0 over the dp axis when divisible
                    if arr.shape[0] % mesh.shape[axis] == 0:
                        spec = PartitionSpec(
                            name, *([None] * (arr.ndim - 1)))
                        slot[pid] = jax.device_put(
                            arr, NamedSharding(jmesh, spec))


def shard_optimizer(optimizer, shard_fn=None):
    """reference: auto_parallel/api.py:1660."""
    return _ShardOptimizer(optimizer, shard_fn)


def local_value(dist_tensor: Tensor) -> Tensor:
    """This process's local shard (reference: DistTensor._local_value;
    single-controller: the first addressable shard). For a Partial tensor
    this is the rank's unreduced partial contribution."""
    data = dist_tensor._data
    attr = dist_tensor._dist_attr
    if attr is not None and getattr(attr, "_partial_hidden", False):
        # hidden axis: slots are per-device pending-sum contributions;
        # multi-process, this rank's contribution = the sum of its own
        # slots (one per local device on the partial axis)
        if isinstance(data, jax.Array) and not data.is_fully_addressable:
            first = data.addressable_shards[0]
            rest = first.index[1:]
            contribs = [jnp.sum(jnp.asarray(s.data), axis=0)
                        for s in data.addressable_shards
                        if s.index[1:] == rest]
            out = contribs[0]
            for c in contribs[1:]:
                out = out + c
            return Tensor(out)
        return Tensor(jnp.asarray(data[0]))
    try:
        shard = data.addressable_shards[0]
        return Tensor(jnp.asarray(shard.data))
    except Exception:
        return Tensor(data)


class _ShardDataLoader:
    """Iterates an inner DataLoader, placing each batch as a DistTensor
    sharded over ``shard_dims`` (batch axis on dp) — reference:
    auto_parallel/api.py:3313 shard_dataloader."""

    def __init__(self, dataloader, meshes, input_keys=None, shard_dims=None,
                 is_dataset_splitted=False):
        self._loader = dataloader
        if isinstance(meshes, (list, tuple)):
            if len(meshes) > 1:
                raise NotImplementedError(
                    "shard_dataloader with multiple meshes (per-pipeline-"
                    "stage inputs) is not supported yet; pass one mesh")
            self._mesh = meshes[0]
        else:
            self._mesh = meshes
        self._shard_dims = shard_dims
        self._input_keys = set(input_keys) if input_keys else None
        if isinstance(shard_dims, int):
            shard_dims = self._mesh.dim_names[shard_dims]
        if shard_dims is not None and not isinstance(shard_dims, str):
            raise NotImplementedError(
                f"shard_dims={shard_dims!r}: only a mesh-dim name or index "
                "is supported")
        axis = None
        if isinstance(shard_dims, str):
            axis = shard_dims
        elif shard_dims is None and "dp" in self._mesh.dim_names:
            axis = "dp"
        # dataset already split per dp rank: batches are local, do not
        # re-shard the batch dim (reference is_dataset_splitted semantics)
        self._axis = None if is_dataset_splitted else axis

    def __len__(self):
        return len(self._loader)

    def _place(self, t):
        if not isinstance(t, Tensor):
            t = Tensor(jnp.asarray(np.asarray(t)))
        placements = [Replicate()] * self._mesh.ndim
        if self._axis is not None and self._axis in self._mesh.dim_names:
            i = self._mesh.dim_names.index(self._axis)
            if t.ndim and t.shape[0] % self._mesh.shape[i] == 0:
                placements[i] = Shard(0)
            elif t.ndim:
                import warnings

                warnings.warn(
                    f"shard_dataloader: batch dim {t.shape[0]} is not "
                    f"divisible by mesh axis '{self._axis}' "
                    f"(size {self._mesh.shape[i]}); replicating this batch — "
                    "data parallelism is lost for it. Use drop_last=True or "
                    "pad the batch.", stacklevel=3)
        return shard_tensor(t, self._mesh, placements)

    def __iter__(self):
        for batch in self._loader:
            if isinstance(batch, (list, tuple)):
                yield type(batch)(self._place(b) for b in batch)
            elif isinstance(batch, dict):
                yield {k: self._place(v)
                       if self._input_keys is None or k in self._input_keys
                       else v
                       for k, v in batch.items()}
            else:
                yield self._place(batch)


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted=False):
    """reference: auto_parallel/api.py:3313."""
    return _ShardDataLoader(dataloader, meshes, input_keys, shard_dims,
                            is_dataset_splitted)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """DistModel bridge (reference: auto_parallel/api.py:2179): returns a
    DistModel whose __call__ runs the pass-composed (amp/recompute/
    sharding/gradient-merge), mesh-partitioned compiled train step
    (engine.py). With no optimizer it is a compiled predictor."""
    from .engine import DistModel

    return DistModel(layer, loader=loader, loss=loss, optimizer=optimizer,
                     strategy=strategy)
