"""Static auto-parallel engine (reference:
python/paddle/distributed/auto_parallel/static/engine.py:98 — Engine,
_build :1041, _parallel_pir :655; strategy passes under
distributed/passes/auto_parallel_*.py; DistModel bridge api.py:2179).

TPU-native pass pipeline: the reference lowers a program through
completion (dist-attr propagation) -> partition -> comm insertion ->
optimization passes (amp / recompute / sharding / gradient-merge). Here
the captured program is the jax trace of the whole train step and the
passes compose as *program transforms on that trace*:

- completion/partition/reshard  -> GSPMD: parameter + activation sharding
  annotations (constraint.py) propagate through the jaxpr and XLA inserts
  the collectives (SURVEY §2.4.12).
- amp pass                      -> the step traces under amp.auto_cast.
- recompute pass                -> per-block jax.checkpoint
  (models honor cfg.recompute; generic layers via fleet recompute).
- sharding pass (stage 1/2/3)   -> optimizer-state / parameter sharding
  over the mesh's dp axis (ZeRO semantics via NamedSharding specs).
- gradient-merge pass           -> lax.scan over micro-batch slices
  accumulating grads inside ONE compiled step (zero host round-trips).

Everything lands in a single pjit'd program per (shapes, mesh) — the
executor role of the reference's PirInterpreter is played by XLA.

A program-level pass tier also exists (distributed/passes/: PassManager,
auto_parallel_amp / auto_parallel_recompute as op-DAG rewrites over the
captured static Program, and the pipeline_scheduler_pass FThenB / 1F1B
job-list passes) for reference-style pass-driven workflows over
paddle.static programs; this Engine keeps the trace-level composition
because the whole step lives in one jax trace here, which XLA optimizes
strictly better than sequenced sub-programs.
"""
from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

import numpy as np

from ...config import knobs

__all__ = ["Strategy", "Engine", "DistModel"]


class _SubConfig:
    def __init__(self, **kw):
        self.enable = False
        for k, v in kw.items():
            setattr(self, k, v)


class Strategy:
    """Semi-auto strategy (reference: auto_parallel/strategy.py — the
    pass-pipeline knobs, one sub-config per pass)."""

    def __init__(self):
        self.amp = _SubConfig(dtype="bfloat16", level="O2")
        self.recompute = _SubConfig()
        self.sharding = _SubConfig(stage=1, degree=-1)
        self.gradient_merge = _SubConfig(k_steps=1, avg=True)
        # pp_degree > 1 partitions the model into a StagedProgram and
        # drives the schedule passes (FThenB / 1F1B / VPP / ZBH1);
        # pp_degree <= 1 keeps accumulate_steps as gradient accumulation
        self.pipeline = _SubConfig(schedule_mode="1F1B",
                                   accumulate_steps=1, pp_degree=1,
                                   vpp_degree=1)


class _StagedTrainStep:
    """Train step driven by a pipeline schedule pass over a StagedProgram
    (the executor role of the reference's standalone_executor running a
    job-list plan, fleet_executor_utils.py). Splits each batch into
    micro-batches, runs the schedule for loss+grads, applies the
    optimizer's pure functional update, and writes the new parameter
    arrays back into both the StagedProgram and the source layers."""

    def __init__(self, staged, sched, optimizer, micro: int):
        self.staged = staged
        self.sched = sched
        self.optimizer = optimizer
        self.micro = micro
        self._sizes = [len(p) for p in staged.params]
        flat = [a for stage in staged.params for a in stage]
        self.opt_state = optimizer.init_state(flat)
        self.last_jobs = None

    def _split(self, arr, m):
        import numpy as np

        from ...core.tensor import Tensor

        a = arr._data if isinstance(arr, Tensor) else np.asarray(arr)
        n = a.shape[0]
        if n % m:
            raise ValueError(f"batch {n} not divisible by {m} micro-batches")
        k = n // m
        return [a[i * k:(i + 1) * k] for i in range(m)]

    def __call__(self, *batch):
        import jax

        from ...core.tensor import Tensor

        *inputs, labels = batch
        if len(inputs) != 1:
            raise ValueError(
                "pipeline Engine expects (input, labels) batches")
        micros_x = self._split(inputs[0], self.micro)
        micros_y = self._split(labels, self.micro)
        loss, grads, jobs = self.sched.apply(self.staged, micros_x,
                                             micros_y)
        self.last_jobs = jobs
        flat_p = [a for stage in self.staged.params for a in stage]
        flat_g = []
        for s, g in enumerate(grads):
            if g is None:
                g = [jax.numpy.zeros_like(a)
                     for a in self.staged.params[s]]
            flat_g.extend(list(g))
        new_p, self.opt_state = self.optimizer.update(
            flat_p, flat_g, self.opt_state)
        # write back: StagedProgram params + the source nn.Layer params
        i = 0
        seg_params = getattr(self.staged, "segment_params", None)
        for s, n in enumerate(self._sizes):
            stage_new = new_p[i:i + n]
            if self.staged.devices is not None:
                stage_new = [jax.device_put(a, self.staged.devices[s])
                             for a in stage_new]
            self.staged.params[s] = list(stage_new)
            if seg_params is not None:
                for p, a in zip(seg_params[s], stage_new):
                    p._data = a
            i += n
        return Tensor(loss)

    def sync_params_to_model(self):
        """Parameters are written back every step; kept for TrainStep API
        compatibility."""

    def restore_state(self, opt_state=None):
        """Resume path: re-adopt the source layers' (just-loaded)
        parameter arrays into the StagedProgram and optionally replace
        the optimizer state."""
        import jax
        import jax.numpy as jnp

        seg = getattr(self.staged, "segment_params", None)
        if seg is not None:
            for s in range(len(self.staged.params)):
                stage_new = [jnp.asarray(p._data) for p in seg[s]]
                if self.staged.devices is not None:
                    stage_new = [jax.device_put(a, self.staged.devices[s])
                                 for a in stage_new]
                self.staged.params[s] = stage_new
                for p, a in zip(seg[s], stage_new):
                    p._data = a
        if opt_state is not None:
            self.opt_state = {
                k: [jnp.asarray(e) for e in v]
                if isinstance(v, (list, tuple)) else jnp.asarray(v)
                for k, v in opt_state.items()}


class Engine:
    """reference: auto_parallel/static/engine.py:98. fit/evaluate/predict
    over a pass-composed, mesh-partitioned compiled train step."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy: Optional[Strategy] = None, mesh=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy or Strategy()
        self._mesh = mesh
        self._step = None
        self.pass_manager = None   # built by _build from the strategy
        self.history = {"loss": []}

    # ------------------------------------------------------------ build
    def _resolve_mesh(self):
        if self._mesh is not None:
            return self._mesh
        from .process_mesh import get_mesh

        return get_mesh()

    @staticmethod
    def _jax_mesh(mesh):
        if mesh is None:
            return None
        return mesh.get_jax_mesh() if hasattr(mesh, "get_jax_mesh") \
            else mesh

    def _apply_recompute_pass(self):
        """Recompute pass: models expose cfg.recompute (per-block
        jax.checkpoint in their forward); generic layers fall back
        untouched (reference: auto_parallel_recompute.py)."""
        cfg = getattr(self.model, "config", None)
        if cfg is not None and hasattr(cfg, "recompute"):
            cfg.recompute = True
            for sub in self.model.sublayers():
                if hasattr(sub, "_recompute"):
                    sub._recompute = True

    def _build(self, sample_batch):
        import jax

        from ...jit import TrainStep
        from ...amp import auto_cast

        st = self.strategy

        # strategy -> registered program passes, composed through the
        # PassManager (reference: engine.py _parallel_pir applying the
        # strategy's pass list through apply_pass). Each pass OWNS the
        # interpretation of its strategy knob; the Engine only reads the
        # configured context when assembling the step. Built before the
        # pipeline branch so recompute composes with staged PP too.
        from ..passes import PassManager, new_pass

        pass_list = []
        if st.amp.enable:
            pass_list.append(new_pass("auto_parallel_amp", {
                "dtype": getattr(st.amp, "dtype", "bfloat16"),
                "level": getattr(st.amp, "level", "O2")}))
        if st.sharding.enable:
            pass_list.append(new_pass("auto_parallel_sharding", {
                "stage": int(st.sharding.stage)}))
        if st.gradient_merge.enable:
            pass_list.append(new_pass("auto_parallel_gradient_merge", {
                "k_steps": int(st.gradient_merge.k_steps),
                "avg": bool(getattr(st.gradient_merge, "avg", True))}))
        if st.recompute.enable:
            pass_list.append(new_pass("auto_parallel_recompute"))
        self.pass_manager = PassManager(pass_list)
        ctx = self.pass_manager.configure().attrs
        if ctx.get("recompute"):
            self._apply_recompute_pass()

        if st.pipeline.enable and int(getattr(
                st.pipeline, "pp_degree", 1)) > 1:
            # static pipeline parallelism: partition -> schedule pass
            # (reference: engine.py:655 _parallel_pir composing
            # pipeline_scheduler_pass into the plan). The staged path
            # doesn't compose with the trace-level passes yet — refuse
            # loudly rather than silently dropping an enabled pass.
            dropped = [name for name, c in
                       [("amp", st.amp), ("sharding", st.sharding),
                        ("gradient_merge", st.gradient_merge)]
                       if c.enable]
            if dropped:
                raise ValueError(
                    f"strategy.pipeline with pp_degree>1 does not yet "
                    f"compose with enabled pass(es) {dropped}; disable "
                    "them or use pipeline.accumulate_steps without "
                    "pp_degree (gradient accumulation path)")
            self._step = self._build_pipeline(sample_batch)
            return self._step

        mesh = self._resolve_mesh()
        loss_layer = self.loss

        amp_cfg = ctx.get("amp", {"enable": False})
        amp_enabled = amp_cfg.get("enable", False)
        amp_dtype = amp_cfg.get("dtype", "bfloat16")
        amp_level = amp_cfg.get("level", "O2")

        # fusion pass: the rewrite-layer mode and quantized-matmul mode are
        # captured ONCE at build time (like the amp/health knobs) and pinned
        # for every trace of this step — a mid-run env flip cannot split the
        # compiled program between fused and fallback call sites
        from ... import fusion as _fusion
        from ...observability import registry as _obs_reg
        from ...observability.registry import enabled as _obs_on

        fusion_mode = _fusion.mode()
        quant_mode = _fusion.mm_quant()
        if _obs_on():
            _obs_reg.counter("fusion.builds",
                             tags={"mode": fusion_mode,
                                   "quant": quant_mode}).inc()

        def loss_fn(model, *batch):
            def run():
                if loss_layer is not None:
                    *inputs, labels = batch
                    out = model(*inputs)
                    return loss_layer(out, labels)
                return model(*batch[:-1], labels=batch[-1])

            with _fusion.override(fusion=fusion_mode,
                                  quant_mode=quant_mode):
                if amp_enabled:
                    # amp pass: the whole step traces under autocast
                    with auto_cast(True, level=amp_level, dtype=amp_dtype):
                        return run()
                return run()

        fsdp_axis = None
        if ctx.get("fsdp_axis"):
            # sharding pass stage>=2: ZeRO param sharding over dp
            jm = self._jax_mesh(mesh)
            if jm is not None and ctx["fsdp_axis"] in jm.axis_names:
                fsdp_axis = ctx["fsdp_axis"]

        accumulate = ctx.get("accumulate_steps", 1)
        if st.pipeline.enable:
            accumulate = max(accumulate,
                             int(st.pipeline.accumulate_steps))

        batch_specs = None
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            jm = self._jax_mesh(mesh)
            dp = "dp" if "dp" in jm.axis_names else None
            batch_specs = [P(dp) for _ in sample_batch]

        self._step = TrainStep(
            self.model, self.optimizer, mesh=mesh, loss_fn=loss_fn,
            batch_specs=batch_specs, fsdp_axis=fsdp_axis,
            accumulate_steps=accumulate)
        return self._step

    def _build_pipeline(self, sample_batch):
        """Partition the model into a StagedProgram and pick the schedule
        pass (reference: pipeline_scheduler_pass/__init__.py
        apply_pass dispatch on schedule_mode)."""
        from ..passes.pipeline_partition import stage_program_from_layers
        from ..passes.pipeline_scheduler_pass import (
            Pipeline1F1BPass, PipelineFThenBPass, PipelineVPPPass,
            PipelineZeroBubblePass)

        st = self.strategy
        pp = int(st.pipeline.pp_degree)
        vpp = max(int(getattr(st.pipeline, "vpp_degree", 1)), 1)
        mode = getattr(st.pipeline, "schedule_mode", "1F1B")
        micro = max(int(st.pipeline.accumulate_steps), 1)

        devices = None
        jm = self._jax_mesh(self._resolve_mesh())
        if jm is not None:
            if "pp" in jm.axis_names:
                axis = jm.axis_names.index("pp")
                import numpy as _np

                dev_grid = _np.asarray(jm.devices)
                # one representative device per pp slice
                sel = _np.moveaxis(dev_grid, axis, 0).reshape(
                    jm.shape["pp"], -1)[:, 0]
                if len(sel) >= pp:
                    # virtual stage sv lives on physical sv % pp
                    devices = [sel[s % pp] for s in range(pp * vpp)]

        loss_layer = self.loss

        def loss_fn(y, label):
            if loss_layer is not None:
                return loss_layer(y, label)
            raise ValueError("Engine pipeline mode needs a loss layer")

        staged = stage_program_from_layers(
            self.model, pp * vpp, loss_fn, devices=devices)
        if mode == "1F1B" and vpp <= 1:
            from ..pipeline.transport import transport_mode

            if transport_mode() == "device":
                # opt-in fully-compiled path: the whole 1F1B schedule is
                # one jit with ring collective-permute stage transfers
                # (requires a uniform staged program; host-driven
                # schedule otherwise)
                from ..pipeline.schedule import CompiledStagedTrainStep

                try:
                    return CompiledStagedTrainStep(
                        staged, self.optimizer, micro, devices=devices)
                except ValueError as e:
                    import warnings

                    warnings.warn(
                        f"PADDLE_TPU_PP_TRANSPORT=device requested but "
                        f"the compiled pipeline is unavailable ({e}); "
                        "falling back to the host-driven schedule")
        if mode in ("ZBH1", "ZeroBubble"):
            if vpp > 1:
                raise ValueError(
                    "zero-bubble + virtual pipeline is not implemented; "
                    "use vpp_degree=1 with ZBH1 or schedule_mode='VPP'")
            sched = PipelineZeroBubblePass()
        elif mode == "FThenB":
            sched = PipelineFThenBPass()
        elif mode == "VPP" or vpp > 1:
            sched = PipelineVPPPass(pp, vpp)
        else:
            sched = Pipeline1F1BPass()
        return _StagedTrainStep(staged, sched, self.optimizer, micro)

    # -------------------------------------------------------------- fit
    def _record_build_telemetry(self, batch):
        """Per-compilation accounting (observability/xla_cost.py): AOT
        cost_analysis of the freshly built train step, keyed by
        executable, plus the schedule-analytic pipeline bubble when
        pp>1. When step profiling is on, also installs the profiler's
        step cost model (FLOPs/tokens/optimizer split from the same
        lowering), cross-checks the 6N analytic FLOPs model against
        XLA's count, and stamps the "build" memory-ledger phase.
        Runs when telemetry OR profiling is enabled."""
        from ... import observability as _obs
        from ...observability import memory as _memory
        from ...observability import profiler as _prof

        st = self.strategy
        pp = int(getattr(st.pipeline, "pp_degree", 1))
        if st.pipeline.enable and pp > 1:
            vpp = max(int(getattr(st.pipeline, "vpp_degree", 1)), 1)
            micro = max(int(st.pipeline.accumulate_steps), 1)
            mode = getattr(st.pipeline, "schedule_mode", "1F1B")
            bubble = 0.0 if mode in ("ZBH1", "ZeroBubble") else \
                (pp - 1) / (micro * vpp + pp - 1)
            _obs.registry.gauge("engine.pp_bubble_fraction").set(bubble)
        xla_flops = None
        if hasattr(self._step, "lower"):
            try:
                # Lowered.cost_analysis() runs XLA's HLO cost model
                # without building a second executable, so this never
                # duplicates the train-step compilation.
                lowered = self._step.lower(*batch)
                _obs.record_cost_analysis("engine.train_step", lowered)
                ca = lowered.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                if isinstance(ca, dict):
                    xla_flops = float(ca.get("flops", 0.0)) or None
            except Exception:
                pass  # cost model unavailable on this backend
        if not _prof.profiling_enabled():
            return
        _memory.note_phase("build")
        tokens = self._batch_tokens(batch)
        n_params = 0
        for arr in getattr(self._step, "param_arrays", ()) or ():
            sz = getattr(arr, "size", None)
            if sz:
                n_params += int(sz)
        # 6N fwd+bwd FLOPs/token; the optimizer's elementwise update
        # (~Adam) is a per-param constant, kept as a separate split so
        # the device segment can sub-attribute it
        model_flops = 6.0 * n_params * tokens if n_params else None
        _prof.configure(
            flops_per_step=xla_flops or model_flops or 0.0,
            tokens_per_step=tokens,
            optimizer_flops=18.0 * n_params if n_params else 0.0)
        if model_flops:
            _prof.flops_divergence(model_flops, xla_flops)

    @staticmethod
    def _batch_tokens(batch) -> int:
        """Tokens per step for throughput: [b, s] inputs count b*s
        elements, anything else counts batch rows."""
        lead = batch[0]
        shape = getattr(lead, "shape", None)
        if shape is None or not len(shape):
            return 1
        n = int(shape[0])
        if len(shape) >= 2:
            n *= int(shape[1])
        return n

    def fit(self, train_data, epochs=1, batch_size=None,
            steps_per_epoch=None, log_freq=10, verbose=0,
            save_dir=None, save_freq=None, resume=False,
            keep_last=3, save_async=True, elastic=None):
        """reference: engine.py:1529. train_data: DataLoader-like iterable
        of (inputs..., labels) batches.

        Fault tolerance: with ``save_dir`` set, a CheckpointManager
        writes CRC-manifested checkpoints every ``save_freq`` steps
        (async unless ``save_async=False``), keeps the newest
        ``keep_last`` and registers an emergency synchronous save for
        the watchdog-timeout and non-finite-loss failure paths.
        ``resume=True`` restores params, optimizer state, step counter,
        RNG and LR schedule from the newest VALID checkpoint (corrupt
        or partial ones are skipped) and replays the loader past the
        restored step so the trajectory matches an uninterrupted run.

        Elastic mode: pass an ``ElasticContext`` (or set
        ``PADDLE_TPU_ELASTIC=1`` in a multi-rank launch) and each step
        heartbeats the rank's membership lease and peer-replicates the
        full train state every ``PADDLE_TPU_ELASTIC_SNAP_FREQ`` steps;
        a membership change surfaces as a typed ``EpochChanged`` at the
        step boundary and the Engine re-joins, re-adopts the newest
        in-memory snapshot (disk manifest as the fallback tier when
        ``save_dir`` is set) and retries the interrupted batch.
        Composes with ``resume=``: the disk restore runs first, then
        elastic snapshots start from the restored step."""
        from ... import observability as _obs
        from ...observability import health as _health
        from ..resilience import faults as _faults

        mgr = None
        hook_token = None
        start_step = 0
        self._last_step = 0
        if save_dir is not None:
            from ..resilience import CheckpointManager, emergency

            mgr = CheckpointManager(save_dir, keep_last=keep_last)
            hook_token = emergency.register(
                lambda reason: mgr.emergency_save(
                    self._collect_state(self._last_step),
                    self._last_step, reason))
        ectx = None
        if elastic is not None and elastic is not False:
            from ..elastic import ElasticContext

            ectx = elastic if isinstance(elastic, ElasticContext) \
                else ElasticContext.from_env()
        elif knobs.get_bool("PADDLE_TPU_ELASTIC") and \
                int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
            from ..elastic import ElasticContext

            ectx = ElasticContext.from_env()
        if ectx is not None:
            from ..elastic import EpochChanged as _EpochChanged

            ectx.bind(
                lambda: self._collect_state(self._last_step),
                self._adopt_state)
        restored = not (resume and mgr is not None)
        global_step = 0
        try:
            for _ in range(epochs):
                for i, batch in enumerate(train_data):
                    if steps_per_epoch is not None \
                            and i >= steps_per_epoch:
                        break
                    batch = batch if isinstance(batch, (list, tuple)) \
                        else (batch,)
                    if self._step is None:
                        from ...observability import profiler as _prof

                        with _obs.span("engine.build"):
                            self._build(batch)
                        if _obs.enabled() or _prof.profiling_enabled():
                            self._record_build_telemetry(batch)
                    if not restored:
                        restored = True
                        start_step = self._restore_from(mgr)
                    if global_step < start_step:
                        # deterministic replay: skip already-trained
                        # batches without consuming the restored RNG
                        global_step += 1
                        continue
                    if _faults.active():
                        act = _faults.check("engine.step")
                        if act is not None:
                            _faults.apply(act)
                    self._last_step = global_step
                    # TrainStep carries its own fused grad-norm health
                    # when the policy was on at build; the staged-
                    # pipeline step has none, so the Engine checks the
                    # loss scalar there
                    check_loss = _health.enabled() and not getattr(
                        self._step, "_health_on", False)
                    try:
                        if ectx is None:
                            self._run_step(batch, global_step,
                                           check_loss)
                        else:
                            import time as _time

                            while True:
                                # membership changes surface here, at
                                # the step boundary — re-join, re-adopt
                                # the newest snapshot, retry this batch
                                try:
                                    ectx.step_begin(global_step)
                                except _EpochChanged as e:
                                    adopted = ectx.handle_epoch_change(
                                        e, disk_restore=(
                                            (lambda: self.
                                             _restore_from(mgr))
                                            if mgr is not None
                                            else None))
                                    if adopted is not None:
                                        self._last_step = int(adopted)
                                    continue
                                break
                            t_step = _time.perf_counter()
                            self._run_step(batch, global_step,
                                           check_loss)
                            ectx.step_end(
                                global_step,
                                (_time.perf_counter() - t_step)
                                * 1000.0)
                    except _health.NonFiniteError:
                        if mgr is not None:
                            mgr.emergency_save(
                                self._collect_state(global_step),
                                global_step,
                                reason="non-finite training signal")
                        raise
                    global_step += 1
                    self._last_step = global_step
                    if mgr is not None and save_freq \
                            and global_step % int(save_freq) == 0:
                        mgr.save(self._collect_state(global_step),
                                 global_step, blocking=not save_async)
        finally:
            if ectx is not None:
                ectx.stop()
            if hook_token is not None:
                from ..resilience import emergency

                emergency.unregister(hook_token)
            if mgr is not None:
                mgr.wait()
        return self.history

    def _run_step(self, batch, global_step: int, check_loss: bool):
        """One training step + history/telemetry bookkeeping. On a
        profiler-sampled step the dispatch and the device drain are
        fenced separately (``block_until_ready`` between them), so the
        step record attributes wall time to dispatch vs. device work —
        the d2h loss read alone cannot tell those apart. Non-sampled
        steps take the exact pre-profiler paths (zero extra fences)."""
        from ... import observability as _obs
        from ...observability import health as _health
        from ...observability import profiler as _prof

        rec = _prof.begin_step(global_step)
        if not _obs.enabled() and rec is None:
            loss = self._step(*batch)
            loss_f = float(np.asarray(loss._data))
            self.history["loss"].append(loss_f)
            if check_loss:
                _health.record_step(loss_f, source="loss",
                                    step=global_step)
            return
        import time as _time

        t0 = _time.perf_counter()
        with _obs.span("engine.step",
                       args={"step": global_step}):
            if rec is not None:
                rec.mark("data_wait")
                loss = self._step(*batch)
                rec.mark("dispatch")
                import jax as _jax

                _jax.block_until_ready(loss._data)  # device fence
                rec.mark("device")
                loss_f = float(np.asarray(loss._data))
            else:
                loss = self._step(*batch)
                loss_f = float(np.asarray(loss._data))  # d2h barrier
        dt = _time.perf_counter() - t0
        self.history["loss"].append(loss_f)
        if rec is not None:
            rec.close(tokens=self._batch_tokens(batch))
        if _obs.enabled():
            reg = _obs.registry
            reg.histogram("engine.step_time").observe(dt)
            reg.counter("engine.steps").inc()
            if dt > 0:
                reg.gauge("engine.tokens_per_s").set(
                    self._batch_tokens(batch) / dt)
            reg.gauge("engine.loss").set(loss_f)
            _obs.flight_recorder.record("engine.step",
                                        step=global_step,
                                        loss=loss_f, dur_s=dt)
            _obs.sample_device_memory()
        if check_loss:
            _health.record_step(loss_f, source="loss",
                                step=global_step)

    # ------------------------------------------------- checkpoint/resume
    def _collect_state(self, step: int):
        """Assemble the checkpointable training state: model params
        (sharded tensor save path) plus a ``__train_state__`` object
        blob carrying the step counter, host RNG key, optimizer state
        and LR schedule — everything a bit-deterministic resume needs."""
        from ...core import random as _rng

        state = dict(self.model.state_dict())
        train = {"step": int(step),
                 "rng": np.asarray(_rng.get_rng_state())}
        opt_state = getattr(self._step, "opt_state", None)
        if opt_state is not None:
            train["optimizer"] = {
                k: [np.asarray(e) for e in v]
                if isinstance(v, (list, tuple)) else np.asarray(v)
                for k, v in opt_state.items()}
        from ...optimizer.lr import LRScheduler

        lr = getattr(self.optimizer, "_learning_rate", None)
        if isinstance(lr, LRScheduler):
            train["lr_sched"] = lr.state_dict()
        state["__train_state__"] = train
        return state

    def _adopt_state(self, state) -> int:
        """Install an in-memory snapshot produced by
        :meth:`_collect_state` (numpy-valued after the elastic
        transport's host conversion): params written into the live
        tensors preserving dtype/sharding, then optimizer state, RNG
        and LR schedule exactly as the disk restore does. Returns the
        step the snapshot was taken at."""
        import jax
        import jax.numpy as jnp

        train = state.get("__train_state__") or {}
        live = dict(self.model.state_dict())
        for k, v in state.items():
            if k == "__train_state__":
                continue
            t = live.get(k)
            if t is None:
                continue
            new = jnp.asarray(np.asarray(v)).astype(t._data.dtype)
            if isinstance(t._data, jax.Array) \
                    and hasattr(t._data, "sharding") \
                    and len(t._data.devices()) > 1:
                new = jax.device_put(new, t._data.sharding)
            t._data = new
        if hasattr(self._step, "restore_state"):
            self._step.restore_state(opt_state=train.get("optimizer"))
        if train.get("rng") is not None:
            from ...core import random as _rng

            _rng.set_rng_state(jnp.asarray(train["rng"]))
        if train.get("lr_sched"):
            from ...optimizer.lr import LRScheduler

            lr = getattr(self.optimizer, "_learning_rate", None)
            if isinstance(lr, LRScheduler):
                lr.set_state_dict(train["lr_sched"])
        return int(train.get("step", 0))

    def _restore_from(self, mgr) -> int:
        """Restore params/optimizer/RNG/step from the newest valid
        checkpoint; returns the global step to resume from (0 when no
        valid checkpoint exists)."""
        import sys

        from ... import observability as _obs

        found = mgr.latest_valid()
        if found is None:
            return 0
        step, path = found
        state = dict(self.model.state_dict())
        state["__train_state__"] = None  # filled by load_state_dict
        mgr.load(state, path)
        train = state.get("__train_state__") or {}
        if hasattr(self._step, "restore_state"):
            self._step.restore_state(opt_state=train.get("optimizer"))
        if train.get("rng") is not None:
            import jax.numpy as jnp

            from ...core import random as _rng

            _rng.set_rng_state(jnp.asarray(train["rng"]))
        if train.get("lr_sched"):
            from ...optimizer.lr import LRScheduler

            lr = getattr(self.optimizer, "_learning_rate", None)
            if isinstance(lr, LRScheduler):
                lr.set_state_dict(train["lr_sched"])
        start = int(train.get("step", step))
        print(f"[resilience] resuming from {path} (step {start})",
              file=sys.stderr)
        if _obs.enabled():
            _obs.registry.counter("resilience.resumes").inc()
            _obs.flight_recorder.record("resilience.resume", path=path,
                                        step=start)
        return start

    def evaluate(self, eval_data, steps=None):
        from ...core.autograd import no_grad

        losses = []
        with no_grad():
            for i, batch in enumerate(eval_data):
                if steps is not None and i >= steps:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else \
                    (batch,)
                if self.loss is not None:
                    *inputs, labels = batch
                    out = self.model(*inputs)
                    losses.append(float(np.asarray(
                        self.loss(out, labels)._data)))
                else:
                    losses.append(float(np.asarray(
                        self.model(*batch[:-1], labels=batch[-1])._data)))
        return {"loss": losses}

    def predict(self, data, steps=None):
        from ...core.autograd import no_grad

        outs = []
        with no_grad():
            for i, batch in enumerate(data):
                if steps is not None and i >= steps:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else \
                    (batch,)
                outs.append(self.model(*batch))
        return outs


class DistModel:
    """reference: auto_parallel/api.py:2179 DistModel — the callable
    returned by paddle.distributed.to_static: train()/eval()/predict()
    modes; __call__ runs the pass-composed compiled step."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy: Optional[Strategy] = None, mesh=None):
        self._engine = Engine(layer, loss, optimizer, strategy=strategy,
                              mesh=mesh)
        self._mode = "train" if optimizer is not None else "predict"
        self._predict_fn = None
        self.network = layer

    def train(self):
        self._mode = "train"
        self.network.train()

    def eval(self):
        self._mode = "eval"
        self.network.eval()

    def predict(self):
        self._mode = "predict"
        self.network.eval()

    def state_dict(self, *a, **k):
        return self.network.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self.network.set_state_dict(*a, **k)

    def __call__(self, *batch):
        eng = self._engine
        if self._mode == "train":
            if eng._step is None:
                eng._build(batch)
            return eng._step(*batch)
        if self._mode == "eval":
            from ...core.autograd import no_grad

            with no_grad():
                if eng.loss is not None:
                    *inputs, labels = batch
                    return eng.loss(eng.model(*inputs), labels)
                return eng.model(*batch[:-1], labels=batch[-1])
        # predict: compiled forward (jit retrace cache), no grads
        from ...core.autograd import no_grad

        if self._predict_fn is None:
            from ... import jit as pjit

            self._predict_fn = pjit.StaticFunction(eng.model)
        with no_grad():
            return self._predict_fn(*batch)
