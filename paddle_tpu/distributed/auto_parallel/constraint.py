"""Activation/parameter sharding annotations for the jit (GSPMD) path.

TPU-native realization of the reference's SPMD-rule propagation
(reference: paddle/phi/infermeta/spmd_rules/ — 57 per-op rule files,
registered via the ``spmd_rule:`` key in phi/ops/yaml/ops.yaml): instead of
running C++ rules per op, models annotate parameters and a few activation
cut-points with mesh-axis names, and XLA's GSPMD propagates shardings
through every op and inserts the collectives (all-reduce/all-gather/
reduce-scatter over ICI) — the same job the reference's reshard engine
(phi/core/distributed/auto_parallel/reshard/) does explicitly.

Conventions used by ``paddle_tpu.models``:
  - mesh axes: "dp" (data), "mp" (tensor/model), "sp" (sequence),
    "pp" (pipeline stages), "ep" (experts). Any subset may be present.
  - ``annotate_param(p, axes)``: tuple of mesh-axis-name-or-None per dim.
  - ``shard_activation(x, axes)``: with_sharding_constraint when a global
    mesh (distributed.auto_parallel.set_mesh) is active; no-op otherwise.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor
from .process_mesh import get_mesh

__all__ = ["annotate_param", "param_spec", "shard_activation",
           "filtered_spec", "mesh_axis_size"]


def _active_jax_mesh():
    pm = get_mesh()
    if pm is None:
        return None
    try:
        return pm.get_jax_mesh()
    except Exception:
        return None


def mesh_axis_size(name: str) -> int:
    """Size of a mesh axis in the active global mesh (1 if absent)."""
    pm = get_mesh()
    if pm is None or name not in pm.dim_names:
        return 1
    return pm.get_dim_size(name)


def filtered_spec(axes: Sequence, mesh) -> PartitionSpec:
    """Drop axis names not present in ``mesh`` (so the same model code runs
    on a pure-dp mesh, a dp×mp mesh, etc.)."""
    names = set(mesh.axis_names)

    def keep(a):
        if a is None:
            return None
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x in names)
            return kept if kept else None
        return a if a in names else None

    return PartitionSpec(*[keep(a) for a in axes])


def annotate_param(p: Tensor, axes: Sequence) -> Tensor:
    """Attach a sharding annotation (mesh-axis name per tensor dim) to a
    parameter; consumed by the jit train-step builder and dryrun paths."""
    p.dist_spec = tuple(axes)
    return p


def param_spec(p: Tensor, mesh) -> PartitionSpec:
    axes = getattr(p, "dist_spec", None)
    if axes is None:
        return PartitionSpec()
    return filtered_spec(axes, mesh)


def shard_activation(x, axes: Sequence):
    """Constrain an activation's sharding under the active global mesh.

    Differentiable (with_sharding_constraint has a trivial vjp); outside a
    mesh or outside tracing this is the identity.
    """
    mesh = _active_jax_mesh()
    if mesh is None:
        return x
    spec = filtered_spec(axes, mesh)
    from ...core.autograd import run_op

    def fn(a):
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))

    if isinstance(x, Tensor):
        return run_op(fn, [x], name="shard_constraint")
    return fn(x)
