"""Cluster description (reference: python/paddle/distributed/
auto_parallel/static/cluster.py — Device/Machine/Cluster with link
bandwidths driving the cost model).

TPU-native: the cluster is a TPU slice — chips with known peak FLOPs /
HBM bandwidth, ICI links inside the slice, DCN across slices. Built
automatically from jax.devices() or explicitly for what-if planning.
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["DeviceSpec", "LinkSpec", "Machine", "Cluster",
           "build_cluster"]

# chip catalog: (peak bf16 TFLOPs, HBM GB, HBM GB/s, ICI GB/s per link)
_CHIPS = {
    "v4": (275.0, 32.0, 1228.0, 50.0),
    "v5e": (197.0, 16.0, 819.0, 50.0),
    "v5p": (459.0, 95.0, 2765.0, 100.0),
    "v6e": (918.0, 32.0, 1640.0, 100.0),
    "cpu": (0.5, 8.0, 50.0, 10.0),
}


class DeviceSpec:
    """reference: cluster.py Device."""

    def __init__(self, global_id, local_id, machine_id, dtype="TPU",
                 model="v5e"):
        self.global_id = global_id
        self.local_id = local_id
        self.machine_id = machine_id
        self.type = dtype
        self.model = model
        tf, hbm, bw, ici = _CHIPS.get(model, _CHIPS["v5e"])
        self.peak_tflops = tf
        self.memory_gb = hbm
        self.hbm_gbps = bw
        self.ici_gbps = ici


class LinkSpec:
    """reference: cluster.py Link."""

    def __init__(self, source, target, kind="ICI", bandwidth_gbps=50.0,
                 latency_us=1.0):
        self.source = source
        self.target = target
        self.type = kind
        self.bandwidth_gbps = bandwidth_gbps
        self.latency_us = latency_us


class Machine:
    """reference: cluster.py Machine — one host with its chips."""

    def __init__(self, machine_id):
        self.id = machine_id
        self.devices: Dict[int, DeviceSpec] = {}

    def add_device(self, dev: DeviceSpec):
        self.devices[dev.global_id] = dev


class Cluster:
    """reference: cluster.py Cluster."""

    def __init__(self):
        self.machines: Dict[int, Machine] = {}
        self.links: List[LinkSpec] = []

    def add_machine(self, m: Machine):
        self.machines[m.id] = m

    def add_link(self, link: LinkSpec):
        self.links.append(link)

    @property
    def devices(self) -> List[DeviceSpec]:
        out = []
        for m in self.machines.values():
            out.extend(m.devices.values())
        return sorted(out, key=lambda d: d.global_id)

    def device(self, global_id) -> DeviceSpec:
        for m in self.machines.values():
            if global_id in m.devices:
                return m.devices[global_id]
        raise KeyError(global_id)

    def bandwidth_gbps(self, a: int, b: int) -> float:
        """Effective link bandwidth between two devices: ICI inside a
        machine/slice, DCN across."""
        da, db = self.device(a), self.device(b)
        if da.machine_id == db.machine_id:
            return da.ici_gbps
        dcn = [l for l in self.links if l.type == "DCN"]
        return dcn[0].bandwidth_gbps if dcn else 12.5  # ~100 Gb/s default

    # ------------------------------------------------------------- build
    @staticmethod
    def from_devices(n_devices, chips_per_host=4, model="v5e",
                     dcn_gbps=12.5):
        c = Cluster()
        for g in range(n_devices):
            mid = g // chips_per_host
            if mid not in c.machines:
                c.add_machine(Machine(mid))
            c.machines[mid].add_device(
                DeviceSpec(g, g % chips_per_host, mid, model=model))
        n_machines = len(c.machines)
        if n_machines > 1:
            c.add_link(LinkSpec(0, chips_per_host, kind="DCN",
                                bandwidth_gbps=dcn_gbps))
        return c


def build_cluster(model: Optional[str] = None) -> Cluster:
    """Auto-describe the current jax environment as a Cluster."""
    import jax

    devs = jax.devices()
    kind = model
    if kind is None:
        plat = devs[0].platform
        kind = "v5e" if plat in ("tpu", "axon") else "cpu"
    per_host = max(1, len([d for d in devs
                           if d.process_index == devs[0].process_index]))
    return Cluster.from_devices(len(devs), chips_per_host=per_host,
                                model=kind)
