"""Analytic cost model + plan search (reference:
python/paddle/distributed/auto_parallel/static/cost/ — CompOpCost /
CommOpCost per-op classes, estimate_cost, and the parallel tuner's
cost-driven plan selection over process meshes).

TPU-native: op compute cost is the roofline max(FLOPs/peak, bytes/HBM bw)
over the captured op-DAG avals; collective costs use the standard ring
formulas over the Cluster's ICI/DCN bandwidths; the planner enumerates
(dp, mp) mesh factorizations of a transformer-shaped workload and picks
the cheapest estimated step — the what-if tier that complements the
measuring auto_tuner (distributed/auto_tuner)."""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from .cluster import Cluster, build_cluster

__all__ = ["OpCost", "CommCost", "CostEstimator", "estimate_program_cost",
           "ParallelPlanner"]


def _nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


_MATMUL_OPS = {"matmul", "mm", "bmm", "linear", "einsum", "conv2d",
               "conv3d", "conv1d", "flash_attention"}


class OpCost:
    """Per-op roofline estimate (reference cost/comp_op_cost.py)."""

    def __init__(self, name, flops, bytes_rw):
        self.name = name
        self.flops = flops
        self.bytes = bytes_rw

    def time_us(self, dev) -> float:
        t_flops = self.flops / (dev.peak_tflops * 1e12) * 1e6
        t_mem = self.bytes / (dev.hbm_gbps * 1e9) * 1e6
        return max(t_flops, t_mem)


class CommCost:
    """Collective cost via ring formulas (reference cost/comm_op_cost.py
    AllreduceSumOpCost etc.)."""

    def __init__(self, kind, bytes_, n_ranks, bandwidth_gbps,
                 latency_us=1.0):
        self.kind = kind
        self.bytes = bytes_
        self.n = max(n_ranks, 1)
        self.bw = bandwidth_gbps
        self.latency_us = latency_us

    def time_us(self) -> float:
        n, b = self.n, self.bytes
        if n <= 1:
            return 0.0
        wire = {
            "allreduce": 2.0 * (n - 1) / n * b,
            "allgather": (n - 1) / n * b,
            "reducescatter": (n - 1) / n * b,
            "alltoall": (n - 1) / n * b,
            "broadcast": b,
            "p2p": b,
        }.get(self.kind, b)
        return wire / (self.bw * 1e9) * 1e6 + self.latency_us * (n - 1)


class CostEstimator:
    """Walk a captured op-DAG and sum roofline op costs (reference
    cost/estimate_cost)."""

    def __init__(self, cluster: Optional[Cluster] = None):
        self.cluster = cluster or build_cluster()

    def op_cost(self, node) -> OpCost:
        out_bytes = sum(_nbytes(a) for a in node.out_avals)
        in_bytes = 0
        flops = 0
        in_avals = []
        for p in node.parents:
            if isinstance(p, tuple):
                a = p[0].out_avals[p[1]]
            elif hasattr(p, "aval"):
                a = p.aval
            elif hasattr(p, "_data") and hasattr(p._data, "shape"):
                a = p._data
            else:
                continue
            in_avals.append(a)
            in_bytes += _nbytes(a)
        if node.name in _MATMUL_OPS and len(in_avals) >= 2:
            try:
                a, b = in_avals[0], in_avals[1]
                m = int(np.prod(a.shape[:-1]))
                k = a.shape[-1]
                n = b.shape[-1]
                flops = 2 * m * k * n
            except Exception:
                flops = 0
        else:
            flops = 2 * sum(int(np.prod(a.shape)) for a in node.out_avals)
        return OpCost(node.name, flops, in_bytes + out_bytes)

    def estimate(self, fetches) -> Dict[str, float]:
        """Total estimated time/memory for the program producing
        ``fetches`` on one device of the cluster."""
        from ...static import graph as _g

        dev = self.cluster.devices[0]
        seen = set()
        total_us = 0.0
        peak_bytes = 0
        flops = 0

        def walk(node):
            nonlocal total_us, peak_bytes, flops
            if not isinstance(node, _g.OpNode) or id(node) in seen:
                return
            seen.add(id(node))
            for p in node.parents:
                if isinstance(p, tuple):
                    walk(p[0])
            c = self.op_cost(node)
            total_us += c.time_us(dev)
            flops += c.flops
            peak_bytes += sum(_nbytes(a) for a in node.out_avals)

        for t in fetches:
            if _g.is_symbolic(t):
                node, _ = t._sym_node
                walk(node)
        return {"time_us": total_us, "flops": flops,
                "activation_bytes": peak_bytes,
                "n_ops": len(seen)}


def estimate_program_cost(fetches, cluster: Optional[Cluster] = None):
    """reference: cost/estimate_cost(program) convenience wrapper."""
    return CostEstimator(cluster).estimate(fetches)


class ParallelPlanner:
    """Cost-driven mesh planning (reference:
    auto_parallel/static/tuner/parallel_tuner.py — search over process
    meshes scoring with the cost model; prune rules from
    distributed/auto_tuner/prune.py).

    Scores (dp, mp, pp, micro_batches, sharding_stage) configs
    analytically:
    - compute: FLOPs split over dp*mp*pp, inflated by the 1F1B bubble
      factor (m + pp - 1) / m;
    - dp: grad all-reduce of params/(mp*pp) (stage>=2 replaces it with
      reduce-scatter + all-gather — same ring bytes; stage 3 adds the
      fwd+bwd param all-gathers);
    - mp: 2 activation all-reduces per layer (Megatron), summed bytes
      unchanged by micro-batching but latency is paid per micro-batch;
    - pp: p2p boundary activations, 2 per stage boundary per
      micro-batch (fwd + bwd);
    - memory: params + optimizer states sharded by mp*pp (and dp per
      the ZeRO stage), plus the 1F1B activation stash (up to pp
      in-flight micro-batches on stage 0).
    """

    def __init__(self, cluster: Optional[Cluster] = None):
        self.cluster = cluster or build_cluster()

    def candidates(self, n_devices, max_layers: Optional[int] = None,
                   micro_batch_options: Sequence[int] = (1, 2, 4, 8),
                   stages: Sequence[int] = (1, 2, 3)
                   ) -> List[Dict[str, int]]:
        out = []
        for dp in range(1, n_devices + 1):
            if n_devices % dp:
                continue
            rem = n_devices // dp
            for mp in range(1, rem + 1):
                if rem % mp:
                    continue
                pp = rem // mp
                if max_layers is not None and pp > 1 and max_layers % pp:
                    continue
                # micro-batching only matters under pp: pp==1 configs
                # are scored with m=1 regardless of the option list
                m_opts = micro_batch_options if pp > 1 else (1,)
                for m in m_opts:
                    for st in (stages if dp > 1 else (1,)):
                        out.append({"dp": dp, "mp": mp, "pp": pp,
                                    "micro_batches": m,
                                    "sharding_stage": st})
        return out

    def score(self, cfg, *, params: int, layers: int, hidden: int,
              batch_tokens: int, dtype_bytes: int = 2,
              optimizer_bytes_per_param: int = 6,
              step_flops: Optional[float] = None) -> Dict[str, float]:
        dev = self.cluster.devices[0]
        dp, mp = cfg["dp"], cfg["mp"]
        pp = cfg.get("pp", 1)
        m = max(int(cfg.get("micro_batches", 1)), 1)
        stage = int(cfg.get("sharding_stage", 1))
        n = dp * mp * pp
        if step_flops is None:
            step_flops = 6.0 * params * batch_tokens
        t_ideal = step_flops / n / (dev.peak_tflops * 1e12) * 1e6
        # 1F1B bubble (reference pipeline_scheduler_pass cost intuition:
        # (m + pp - 1) micro-slots for m micro-batches)
        t_comp = t_ideal * (m + pp - 1) / m
        bw = self.cluster.bandwidth_gbps(0, 0)
        shard_params = params / (mp * pp)
        # dp gradient reduction; ZeRO stages keep ring bytes, stage 3
        # adds fwd+bwd param all-gathers
        t_dp = 0.0
        if dp > 1:
            t_dp = CommCost("allreduce", shard_params * 4, dp,
                            bw).time_us()
            if stage == 3:
                t_dp += 2 * CommCost("allgather",
                                     shard_params * dtype_bytes, dp,
                                     bw).time_us()
        # mp activation all-reduces: 2/layer; total bytes independent of
        # m, per-micro-batch latency paid m times
        act_bytes = batch_tokens / dp * hidden * dtype_bytes
        t_mp = 0.0
        if mp > 1:
            lat = 1.0 * (mp - 1) * 2 * (layers / pp) * (m - 1)
            t_mp = 2 * layers * CommCost("allreduce", act_bytes, mp,
                                         bw).time_us() + lat
        # gradient reductions + ZeRO gathers overlap with backward
        # compute (XLA's latency-hiding scheduler; reference analog:
        # the comm-overlap passes §2.4 delegates to XLA) — only the
        # fraction the compute cannot hide is exposed (bulk-synchronous
        # max model; validated against measured auto_tuner trials in
        # tests/test_fleet_executor_cost.py)
        t_dp_raw = t_dp
        t_dp = max(0.0, t_dp - t_comp)
        # pp boundary p2p: fwd+bwd per micro-batch per boundary
        t_pp = 0.0
        if pp > 1:
            mb_bytes = act_bytes / m
            t_pp = 2 * (pp - 1) * m * CommCost("p2p", mb_bytes, 2,
                                               bw).time_us()
        # memory: ZeRO stage shards optimizer state (1), +grads (2),
        # +params (3) over dp
        zdiv = dp if dp > 1 and stage >= 1 else 1
        mem = shard_params * dtype_bytes / (dp if stage >= 3 else 1) \
            + shard_params * optimizer_bytes_per_param / zdiv \
            + shard_params * dtype_bytes / (dp if stage >= 2 else 1)
        # 1F1B stash: stage-0 holds up to pp micro-batches of its
        # layers' activations
        mem += act_bytes / m * (layers / pp) * min(pp, m)
        fits = mem < dev.memory_gb * 1e9 * 0.9
        return {"time_us": t_comp + t_dp + t_mp + t_pp,
                "compute_us": t_comp, "dp_comm_us": t_dp_raw,
                "dp_comm_exposed_us": t_dp, "mp_comm_us": t_mp,
                "pp_comm_us": t_pp, "memory_bytes": mem, "fits": fits}

    def plan(self, n_devices, micro_batch_options=(1, 2, 4, 8),
             stages=(1, 2, 3), **workload) -> Dict:
        """Pick the cheapest fitting config over
        (dp, mp, pp, micro_batches, sharding_stage)."""
        best = None
        cands = self.candidates(n_devices,
                                max_layers=workload.get("layers"),
                                micro_batch_options=micro_batch_options,
                                stages=stages)
        for cfg in cands:
            s = self.score(cfg, **workload)
            if not s["fits"]:
                continue
            if best is None or s["time_us"] < best[1]["time_us"]:
                best = (cfg, s)
        if best is None:  # nothing fits: most-sharded config
            cfg = {"dp": 1, "mp": n_devices, "pp": 1, "micro_batches": 1,
                   "sharding_stage": 3}
            return {"config": cfg, **self.score(cfg, **workload)}
        return {"config": best[0], **best[1]}

    def plan_from_program(self, fetches, n_devices, *, batch_tokens: int,
                          layers: Optional[int] = None,
                          hidden: Optional[int] = None, **kw) -> Dict:
        """Plan from a CAPTURED program's avals instead of a hand-fed
        transformer shape (VERDICT r4 #6): FLOPs and parameter bytes
        come from the op-DAG (CostEstimator + trainable leaves). The
        residual width ("hidden") is the MOST FREQUENT matmul-output
        last-dim — in a transformer the attn-out and down projections
        hit it twice per block while the lm_head's vocab dim appears
        once, so the mode is robust where "widest" would pick the
        vocab — and the layer proxy is that count // 2."""
        from ...static import graph as _g

        est = CostEstimator(self.cluster).estimate(fetches)
        params = 0
        seen_p = set()
        dim_counts: Dict[int, int] = {}

        def walk(node):
            nonlocal params
            if not isinstance(node, _g.OpNode) or id(node) in seen_p:
                return
            seen_p.add(id(node))
            if node.name in _MATMUL_OPS:
                for a in node.out_avals:
                    if len(a.shape):
                        d = int(a.shape[-1])
                        dim_counts[d] = dim_counts.get(d, 0) + 1
            for p in node.parents:
                if isinstance(p, tuple):
                    walk(p[0])
                elif hasattr(p, "_data") and getattr(p, "trainable",
                                                     False):
                    if id(p) not in seen_p:
                        seen_p.add(id(p))
                        params += int(np.prod(p._data.shape))

        for t in fetches:
            if _g.is_symbolic(t):
                walk(t._sym_node[0])
        if dim_counts:
            # mode; ties break to the larger dim (conservative comm)
            mode_dim = max(dim_counts,
                           key=lambda d: (dim_counts[d], d))
        else:
            mode_dim = 1
        layers = layers or max(dim_counts.get(mode_dim, 2) // 2, 1)
        hidden = hidden or mode_dim
        return self.plan(n_devices, params=max(params, 1), layers=layers,
                         hidden=hidden, batch_tokens=batch_tokens,
                         step_flops=3.0 * est["flops"], **kw)
