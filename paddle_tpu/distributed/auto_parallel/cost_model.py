"""Analytic cost model + plan search (reference:
python/paddle/distributed/auto_parallel/static/cost/ — CompOpCost /
CommOpCost per-op classes, estimate_cost, and the parallel tuner's
cost-driven plan selection over process meshes).

TPU-native: op compute cost is the roofline max(FLOPs/peak, bytes/HBM bw)
over the captured op-DAG avals; collective costs use the standard ring
formulas over the Cluster's ICI/DCN bandwidths; the planner enumerates
(dp, mp) mesh factorizations of a transformer-shaped workload and picks
the cheapest estimated step — the what-if tier that complements the
measuring auto_tuner (distributed/auto_tuner)."""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from .cluster import Cluster, build_cluster

__all__ = ["OpCost", "CommCost", "CostEstimator", "estimate_program_cost",
           "ParallelPlanner"]


def _nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


_MATMUL_OPS = {"matmul", "mm", "bmm", "linear", "einsum", "conv2d",
               "conv3d", "conv1d", "flash_attention"}


class OpCost:
    """Per-op roofline estimate (reference cost/comp_op_cost.py)."""

    def __init__(self, name, flops, bytes_rw):
        self.name = name
        self.flops = flops
        self.bytes = bytes_rw

    def time_us(self, dev) -> float:
        t_flops = self.flops / (dev.peak_tflops * 1e12) * 1e6
        t_mem = self.bytes / (dev.hbm_gbps * 1e9) * 1e6
        return max(t_flops, t_mem)


class CommCost:
    """Collective cost via ring formulas (reference cost/comm_op_cost.py
    AllreduceSumOpCost etc.)."""

    def __init__(self, kind, bytes_, n_ranks, bandwidth_gbps,
                 latency_us=1.0):
        self.kind = kind
        self.bytes = bytes_
        self.n = max(n_ranks, 1)
        self.bw = bandwidth_gbps
        self.latency_us = latency_us

    def time_us(self) -> float:
        n, b = self.n, self.bytes
        if n <= 1:
            return 0.0
        wire = {
            "allreduce": 2.0 * (n - 1) / n * b,
            "allgather": (n - 1) / n * b,
            "reducescatter": (n - 1) / n * b,
            "alltoall": (n - 1) / n * b,
            "broadcast": b,
            "p2p": b,
        }.get(self.kind, b)
        return wire / (self.bw * 1e9) * 1e6 + self.latency_us * (n - 1)


class CostEstimator:
    """Walk a captured op-DAG and sum roofline op costs (reference
    cost/estimate_cost)."""

    def __init__(self, cluster: Optional[Cluster] = None):
        self.cluster = cluster or build_cluster()

    def op_cost(self, node) -> OpCost:
        out_bytes = sum(_nbytes(a) for a in node.out_avals)
        in_bytes = 0
        flops = 0
        in_avals = []
        for p in node.parents:
            if isinstance(p, tuple):
                a = p[0].out_avals[p[1]]
            elif hasattr(p, "aval"):
                a = p.aval
            elif hasattr(p, "_data") and hasattr(p._data, "shape"):
                a = p._data
            else:
                continue
            in_avals.append(a)
            in_bytes += _nbytes(a)
        if node.name in _MATMUL_OPS and len(in_avals) >= 2:
            try:
                a, b = in_avals[0], in_avals[1]
                m = int(np.prod(a.shape[:-1]))
                k = a.shape[-1]
                n = b.shape[-1]
                flops = 2 * m * k * n
            except Exception:
                flops = 0
        else:
            flops = 2 * sum(int(np.prod(a.shape)) for a in node.out_avals)
        return OpCost(node.name, flops, in_bytes + out_bytes)

    def estimate(self, fetches) -> Dict[str, float]:
        """Total estimated time/memory for the program producing
        ``fetches`` on one device of the cluster."""
        from ...static import graph as _g

        dev = self.cluster.devices[0]
        seen = set()
        total_us = 0.0
        peak_bytes = 0
        flops = 0

        def walk(node):
            nonlocal total_us, peak_bytes, flops
            if not isinstance(node, _g.OpNode) or id(node) in seen:
                return
            seen.add(id(node))
            for p in node.parents:
                if isinstance(p, tuple):
                    walk(p[0])
            c = self.op_cost(node)
            total_us += c.time_us(dev)
            flops += c.flops
            peak_bytes += sum(_nbytes(a) for a in node.out_avals)

        for t in fetches:
            if _g.is_symbolic(t):
                node, _ = t._sym_node
                walk(node)
        return {"time_us": total_us, "flops": flops,
                "activation_bytes": peak_bytes,
                "n_ops": len(seen)}


def estimate_program_cost(fetches, cluster: Optional[Cluster] = None):
    """reference: cost/estimate_cost(program) convenience wrapper."""
    return CostEstimator(cluster).estimate(fetches)


class ParallelPlanner:
    """Cost-driven mesh planning (reference:
    auto_parallel/static/tuner/parallel_tuner.py — search over process
    meshes scoring with the cost model).

    Scores (dp, mp) factorizations of a transformer step analytically:
    per-device compute shrinks with dp*mp, dp adds a grad all-reduce,
    mp adds two activation all-reduces per layer, memory must fit HBM.
    """

    def __init__(self, cluster: Optional[Cluster] = None):
        self.cluster = cluster or build_cluster()

    def candidates(self, n_devices) -> List[Dict[str, int]]:
        out = []
        for dp in range(1, n_devices + 1):
            if n_devices % dp:
                continue
            out.append({"dp": dp, "mp": n_devices // dp})
        return out

    def score(self, cfg, *, params: int, layers: int, hidden: int,
              batch_tokens: int, dtype_bytes: int = 2,
              optimizer_bytes_per_param: int = 6) -> Dict[str, float]:
        dev = self.cluster.devices[0]
        dp, mp = cfg["dp"], cfg["mp"]
        n = dp * mp
        # compute: 6 * params * tokens FLOPs, evenly split
        step_flops = 6.0 * params * batch_tokens
        t_comp = step_flops / n / (dev.peak_tflops * 1e12) * 1e6
        # dp grad all-reduce (params/mp bytes per device)
        bw = self.cluster.bandwidth_gbps(0, 0)
        t_dp = CommCost("allreduce", params / mp * 4, dp, bw).time_us() \
            if dp > 1 else 0.0
        # mp activation all-reduces: 2 per layer, [tokens/dp, hidden]
        act_bytes = batch_tokens / dp * hidden * dtype_bytes
        t_mp = (2 * layers * CommCost("allreduce", act_bytes, mp,
                                      bw).time_us()) if mp > 1 else 0.0
        mem = (params / mp * (dtype_bytes + optimizer_bytes_per_param)
               + act_bytes * layers)
        fits = mem < dev.memory_gb * 1e9 * 0.9
        return {"time_us": t_comp + t_dp + t_mp, "compute_us": t_comp,
                "dp_comm_us": t_dp, "mp_comm_us": t_mp,
                "memory_bytes": mem, "fits": fits}

    def plan(self, n_devices, **workload) -> Dict:
        """Pick the cheapest fitting (dp, mp) config."""
        best = None
        for cfg in self.candidates(n_devices):
            s = self.score(cfg, **workload)
            if not s["fits"]:
                continue
            if best is None or s["time_us"] < best[1]["time_us"]:
                best = (cfg, s)
        if best is None:  # nothing fits: most-sharded config
            cfg = {"dp": 1, "mp": n_devices}
            return {"config": cfg, **self.score(cfg, **workload)}
        return {"config": best[0], **best[1]}
