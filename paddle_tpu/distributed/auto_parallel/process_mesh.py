"""ProcessMesh over jax.sharding.Mesh
(reference: python/paddle/distributed/auto_parallel/process_mesh.py, C++
phi/core/distributed/auto_parallel/process_mesh.h:34)."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["ProcessMesh", "get_mesh", "set_mesh"]

_global_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    """N-D logical mesh of processes/devices. ``dim_names`` name the axes
    (e.g. ["dp", "mp"] or ["pp", "dp", "mp"]); the jax Mesh is built lazily
    from the flat device list so the same object works before jax device
    init."""

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 shape=None, process_ids=None):
        arr = np.asarray(mesh)
        self._mesh = arr
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self) -> List[int]:
        return list(self._mesh.shape)

    @property
    def ndim(self) -> int:
        return self._mesh.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def mesh(self):
        return self._mesh

    @property
    def process_ids(self) -> List[int]:
        return self._mesh.reshape(-1).tolist()

    @property
    def size(self):
        return int(self._mesh.size)

    def get_dim_size(self, dim_name: str) -> int:
        return self.shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, pid):
        axis = self._dim_names.index(dim_name)
        loc = np.argwhere(self._mesh == pid)
        if loc.size == 0:
            return -1
        return int(loc[0][axis])

    def get_jax_mesh(self):
        """Materialize as jax.sharding.Mesh, mapping process ids onto jax
        devices. With N processes × D local devices we map process id ->
        one device per id when ids index devices directly (single-host
        multi-device emulation) or one device per process (multi-host)."""
        if self._jax_mesh is not None:
            return self._jax_mesh
        import jax

        devices = jax.devices()
        ids = self._mesh.reshape(-1)
        if len(devices) >= ids.size and ids.max() < len(devices):
            devs = np.array([devices[i] for i in ids]).reshape(
                self._mesh.shape)
        else:
            raise RuntimeError(
                f"mesh needs {ids.size} devices; only {len(devices)} visible")
        self._jax_mesh = jax.sharding.Mesh(devs, axis_names=tuple(
            self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._mesh, other._mesh) and \
            self._dim_names == other._dim_names

    def __hash__(self):
        return hash((self._mesh.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    def __getitem__(self, index):
        """Sub-mesh selection along the first axis."""
        sub = self._mesh[index]
        if sub.ndim == self._mesh.ndim:
            return ProcessMesh(sub, self._dim_names)
        return ProcessMesh(sub, self._dim_names[1:])


def set_mesh(mesh: ProcessMesh):
    # the mesh context is MEANT to be installed at trace time — traced
    # bodies (train_step._build) call this so sharding constraints
    # resolve against the right mesh while tracing
    global _global_mesh  # ptlint: disable=jit-purity
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh
