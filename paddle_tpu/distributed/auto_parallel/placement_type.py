"""Placements: Shard / Replicate / Partial
(reference: paddle/phi/core/distributed/auto_parallel/placement_types.h:68,
108, 132)."""
from __future__ import annotations

__all__ = ["Placement", "Shard", "Replicate", "Partial"]


class Placement:
    def is_shard(self, dim=None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self).__name__)


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim=None) -> bool:
        return dim is None or dim == self.dim

    def get_dim(self) -> int:
        return self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicated(self) -> bool:
        return True

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending-reduction placement (the reference's `Partial(SUM)`):
    each shard holds a partial sum; reshard to Replicate/Shard inserts the
    all-reduce / reduce-scatter."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))

    def __repr__(self):
        return f"Partial({self.reduce_type})"
