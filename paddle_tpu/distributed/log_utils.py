"""Backend log hygiene for multi-process runs.

jaxlib's CPU collective backend prints ``[Gloo] Rank N is connected to
M peer ranks...`` straight to file descriptor 2 from C++, so neither
the ``logging`` module nor ``sys.stderr`` monkey-patching can catch it
— every spawned worker pollutes bench/test output with one line per
rank per process-group init. :func:`install_stderr_filter` reroutes
fd 2 through a pipe and demotes matching lines to the framework logger
at DEBUG, passing everything else through byte-for-byte.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Iterable, Sequence, Tuple

logger = logging.getLogger("paddle_tpu.distributed")

_DEFAULT_PATTERNS: Tuple[str, ...] = ("[Gloo]",)
_installed = False
_install_lock = threading.Lock()


def matches_backend_noise(line: str,
                          patterns: Sequence[str] = _DEFAULT_PATTERNS
                          ) -> bool:
    return any(p in line for p in patterns)


def filter_noise_lines(lines: Iterable[str],
                       patterns: Sequence[str] = _DEFAULT_PATTERNS):
    """Drop backend-noise lines from an iterable of text lines (the
    bench runner uses this on child-process output)."""
    return [ln for ln in lines if not matches_backend_noise(ln, patterns)]


def install_stderr_filter(patterns: Sequence[str] = _DEFAULT_PATTERNS
                          ) -> bool:
    """Filter fd-2 writes that match ``patterns`` (idempotent).

    Matching lines are logged at DEBUG on the framework logger; all
    other bytes pass through to the original stderr unchanged. Runs a
    daemon pump thread for the life of the process — meant for spawned
    workers and bench children, where the alternative is C++ log spam
    interleaved with structured output.
    """
    global _installed
    with _install_lock:
        if _installed:
            return False
        try:
            real_fd = os.dup(2)
            rd, wr = os.pipe()
            os.dup2(wr, 2)
            os.close(wr)
        except OSError:
            return False
        _installed = True

    def pump():
        buf = b""
        while True:
            try:
                chunk = os.read(rd, 4096)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                _emit(line + b"\n", real_fd, patterns)
        if buf:
            _emit(buf, real_fd, patterns)

    threading.Thread(target=pump, daemon=True,
                     name="stderr-noise-filter").start()
    # line-buffer the python-side stderr so interleaving stays sane
    try:
        sys.stderr.reconfigure(line_buffering=True)
    except Exception:
        pass
    return True


def _emit(raw: bytes, real_fd: int, patterns: Sequence[str]) -> None:
    try:
        text = raw.decode("utf-8", "replace")
    except Exception:
        text = ""
    if text and matches_backend_noise(text, patterns):
        logger.debug("backend: %s", text.rstrip("\n"))
        return
    try:
        os.write(real_fd, raw)
    except OSError:
        pass
