"""paddle.distributed.io (reference: python/paddle/distributed/io.py —
persistable save/load for distributed inference programs)."""
from __future__ import annotations

import os

__all__ = ["is_persistable", "save_persistables",
           "load_inference_model_distributed"]


def is_persistable(var) -> bool:
    """reference: distributed/io.py is_persistable."""
    return bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Save every persistable parameter of a static Program (reference:
    distributed/io.py save_persistables). On this stack program state is
    the parameter dict held by the Program/Executor."""
    from ..static import default_main_program

    prog = main_program if main_program is not None \
        else default_main_program()
    os.makedirs(dirname, exist_ok=True)
    from ..framework.io_utils import save

    state = prog.state_dict() if hasattr(prog, "state_dict") else {}
    save(state, os.path.join(dirname, filename or "__params__"))


def load_inference_model_distributed(dirname, executor, model_filename=None,
                                     params_filename=None):
    """reference: distributed/io.py load_inference_model_distributed —
    thin delegation to the static inference-model loader."""
    from ..static import load_inference_model

    return load_inference_model(dirname, executor)
