"""DataParallel (reference: python/paddle/distributed/parallel.py:219 +
the C++ EagerReducer, fluid/distributed/collective/reducer.h:88).

Eager DP: broadcast params at wrap time; bucketed gradient all-reduce after
backward (grad-ready hooks fire on leaf accumulation like the reference's
MarkVarReady; buckets flush when full, tail flushes on sync)."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .. import nn
from ..core.tensor import Tensor
from . import collective as dist

__all__ = ["DataParallel"]


class _Reducer:
    """Python port of the EagerReducer algorithm (reducer.h:88):
    group_size-bounded buckets in reverse registration order, fused
    all-reduce per bucket when all its grads are ready."""

    def __init__(self, params, group, group_size_limits=128 * 1024 * 1024):
        self._params = [p for p in params if not p.stop_gradient]
        self._group = group
        self._nranks = group.nranks if group else 1
        # bucket assignment (reverse order ≈ backward completion order)
        self._buckets: List[List] = []
        cur, cur_bytes = [], 0
        for p in reversed(self._params):
            nbytes = p.size * p.dtype.itemsize
            cur.append(p)
            cur_bytes += nbytes
            if cur_bytes >= group_size_limits:
                self._buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            self._buckets.append(cur)
        self._bucket_of = {}
        for bi, b in enumerate(self._buckets):
            for p in b:
                self._bucket_of[id(p)] = bi
        self._pending = [set(id(p) for p in b) for b in self._buckets]
        self._install_hooks()

    def _install_hooks(self):
        for p in self._params:
            p.register_hook(self._make_hook(p))

    def _make_hook(self, p):
        def hook(grad):
            bi = self._bucket_of.get(id(p))
            if bi is None:
                return None
            self._pending[bi].discard(id(p))
            if not self._pending[bi]:
                self._flush(bi)
            return None

        return hook

    def _flush(self, bi):
        import jax.numpy as jnp

        from ..core.selected_rows import SelectedRows

        if self._nranks <= 1:
            return
        bucket = [p for p in self._buckets[bi] if p._grad is not None]
        if not bucket:
            return
        # sparse (SelectedRows) grads sync by allgathering rows+values —
        # the reference EagerReducer's sparse allreduce path. Like the
        # dense flush, this requires grad PRESENCE to agree across ranks
        # (rank-divergent control flow needs find_unused_parameters-style
        # handling, same contract as the reference reducer)
        sparse = [p for p in bucket if isinstance(p._grad, SelectedRows)]
        for p in sparse:
            sr = p._grad.merged()
            gathered = []
            dist.all_gather_object(
                gathered, (np.asarray(sr.rows), np.asarray(sr.values)),
                group=self._group)
            rows = jnp.concatenate([jnp.asarray(r) for r, _ in gathered])
            vals = jnp.concatenate([jnp.asarray(v) for _, v in gathered])
            p._grad = SelectedRows(rows, vals / self._nranks,
                                   sr.shape).merged()
        bucket = [p for p in bucket if not isinstance(p._grad, SelectedRows)]
        if not bucket:
            return
        flat = jnp.concatenate([p._grad._data.reshape(-1).astype(jnp.float32)
                                for p in bucket])
        t = Tensor(flat)
        dist.all_reduce(t, group=self._group)
        out = t._data / self._nranks
        off = 0
        for p in bucket:
            n = p._grad.size
            p._grad._data = out[off:off + n].reshape(
                p._grad._data.shape).astype(p._grad._data.dtype)
            off += n

    def prepare_for_backward(self):
        self._pending = [set(id(p) for p in b) for b in self._buckets]

    def sync(self):
        """Flush any bucket with pending members whose grads exist (tail /
        unused-parameter case, reference find_unused_parameters)."""
        for bi, pending in enumerate(self._pending):
            if pending:
                self._flush(bi)
                self._pending[bi] = set()


class DataParallel(nn.Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group: Optional[dist.Group] = None):
        super().__init__()
        self._layers = layers
        self._group = group if group is not None else dist.get_group(0)
        self.find_unused_parameters = find_unused_parameters
        nranks = self._group.nranks if self._group else 1
        if nranks > 1:
            # sync initial params (reference: parallel.py sync_params_buffers)
            src = self._group.ranks[0]
            for p in layers.parameters():
                dist.broadcast(p, src, group=self._group)
            self._reducer = _Reducer(
                layers.parameters(), self._group,
                group_size_limits=comm_buffer_size * 1024 * 1024)
            self._hook_installed = True
        else:
            self._reducer = None

    def forward(self, *inputs, **kwargs):
        if self._reducer is not None and self.training:
            self._reducer.prepare_for_backward()
        out = self._layers(*inputs, **kwargs)
        if self._reducer is not None and self.training:
            # grads sync lazily via hooks; tail flush happens when the user
            # calls opt.step() -> we expose sync via a post-backward hook on
            # the loss; simplest correct point: flush in step via scale —
            # here we piggyback on the first hook-driven flush plus explicit
            # sync() in sync_gradients.
            pass
        return out

    def sync_gradients(self):
        if self._reducer is not None:
            self._reducer.sync()

    # paddle API parity
    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        self.sync_gradients()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    @property
    def _inner_layers(self):
        return self._layers
