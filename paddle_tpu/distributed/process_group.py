"""ProcessGroup: the virtual collective API + backends
(reference: paddle/phi/core/distributed/collective/process_group.h:48-520;
NCCL impl fluid/distributed/collective/process_group_nccl.cc).

Backends:
- ProcessGroupSingle: world_size==1 fast path (identity collectives).
- ProcessGroupCPU: multi-process on one or more hosts over the TCPStore
  (the Gloo-analog for hardware-free distributed tests — SURVEY §4 test
  strategy). Data moves as numpy buffers through the store; algorithms are
  gather-to-root + broadcast (correctness-first; bandwidth is irrelevant for
  its test role).
- ProcessGroupXLA: multi-host TPU — collectives execute as compiled
  one-collective XLA programs over ICI/DCN via jax global arrays; requires
  jax.distributed.initialize (one process per host).

Every collective returns a Task with wait()/synchronize() like the
reference's ProcessGroup::Task.
"""
from __future__ import annotations

import pickle
from typing import List, Optional

import numpy as np

from .. import observability as _obs
from ..core.tensor import Tensor
from .store import TCPStore

__all__ = ["ReduceOp", "ProcessGroup", "ProcessGroupSingle",
           "ProcessGroupCPU", "Task", "new_process_group_impl"]


class _CollectiveWindow:
    """Watchdog registration + (telemetry-on) tracing span + flight
    recorder start/finish events around ONE collective. The watchdog
    half always runs (hang detection is not a metrics feature); the
    telemetry half is one enabled() check when off."""

    __slots__ = ("op", "gid", "_watch", "_span")

    def __init__(self, op_name: str, gid: int):
        from . import watchdog

        self.op = op_name
        self.gid = gid
        self._watch = watchdog.watch(op_name, gid)
        self._span = None

    def __enter__(self):
        self._watch.__enter__()
        from .resilience import faults as _faults

        if _faults.active():
            act = _faults.check("pg.collective")
            if act is not None:
                # after _watch.__enter__ so an injected delay lands
                # INSIDE the watchdog window and can trip the timeout
                _faults.apply(act)
        if _obs.enabled():
            _obs.flight_recorder.record("pg.collective.start",
                                        op=self.op, group=self.gid)
            self._span = _obs.span("pg.collective", cat="comm",
                                   args={"op": self.op,
                                         "group": self.gid})
            self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            self._span.__exit__(exc_type, exc, tb)
            _obs.flight_recorder.record("pg.collective.finish",
                                        op=self.op, group=self.gid,
                                        ok=exc_type is None)
        self._watch.__exit__(exc_type, exc, tb)


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_NP_REDUCE = {
    ReduceOp.SUM: lambda a, b: a + b,
    ReduceOp.MAX: np.maximum,
    ReduceOp.MIN: np.minimum,
    ReduceOp.PROD: lambda a, b: a * b,
    ReduceOp.AVG: lambda a, b: a + b,  # divided at the end
}


class Task:
    def __init__(self, fn=None):
        self._fn = fn
        self._done = fn is None

    def wait(self, timeout=None):
        if not self._done:
            self._fn()
            self._done = True
        return True

    def synchronize(self):
        self.wait()

    def is_completed(self):
        return self._done


class ProcessGroup:
    """Virtual base (reference: process_group.h:48)."""

    def __init__(self, rank: int, world_size: int, gid: int = 0,
                 group_ranks: Optional[List[int]] = None):
        self._rank = rank
        self._world_size = world_size
        self._gid = gid
        self._group_ranks = group_ranks or list(range(world_size))
        self._coalescing = None  # list of (tensor, op) while coalescing

    def _g2l(self, r: int) -> int:
        """Translate a GLOBAL peer rank (the public-API convention,
        reference process_group.h) to this group's local rank."""
        try:
            return self._group_ranks.index(r)
        except ValueError:
            raise ValueError(
                f"rank {r} is not a member of group "
                f"{self._gid} (ranks={self._group_ranks})") from None

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._world_size

    def name(self) -> str:
        return f"pg_{self._gid}"

    def _watched(self, op_name: str):
        # comm watchdog + tracing span + flight-recorder window
        # (reference: CommTaskManager watchdog)
        return _CollectiveWindow(op_name, self._gid)

    # -- buffer access hooks: backends choose host (numpy) or device (jax)
    # residency. The CPU/store backend moves numpy; ProcessGroupXLA
    # overrides these to keep arrays on device end to end.
    def _get_local(self, tensor: Tensor):
        return tensor.numpy()

    def _put_local(self, tensor: Tensor, out):
        tensor._data = _to_jax(out, tensor)

    # -- collective API: subclasses implement the _impl methods -------------
    def all_reduce(self, tensor: Tensor, op=ReduceOp.SUM, sync_op=True):
        if self._coalescing is not None:
            self._coalescing.append((tensor, op))
            return Task()
        with self._watched("all_reduce"):
            out = self._all_reduce_impl(self._get_local(tensor), op)
        self._put_local(tensor, out)
        return Task()

    def broadcast(self, tensor: Tensor, src: int, sync_op=True):
        src = self._g2l(src)
        with self._watched("broadcast"):
            out = self._broadcast_impl(self._get_local(tensor), src)
        self._put_local(tensor, out)
        return Task()

    def all_gather(self, tensor_list: List[Tensor], tensor: Tensor,
                   sync_op=True):
        with self._watched("all_gather"):
            outs = self._all_gather_impl(self._get_local(tensor))
        if tensor_list is not None:
            if len(tensor_list) == 0:
                tensor_list.extend(Tensor(o) for o in outs)
            else:
                for t, o in zip(tensor_list, outs):
                    self._put_local(t, o)
        return Task()

    def reduce(self, tensor: Tensor, dst: int, op=ReduceOp.SUM, sync_op=True):
        dst = self._g2l(dst)
        with self._watched("reduce"):
            out = self._reduce_impl(self._get_local(tensor), dst, op)
        if self._rank == dst:
            self._put_local(tensor, out)
        return Task()

    def reduce_scatter(self, tensor: Tensor, tensor_list: List[Tensor],
                       op=ReduceOp.SUM, sync_op=True):
        ins = [self._get_local(t) for t in tensor_list]
        with self._watched("reduce_scatter"):
            out = self._reduce_scatter_impl(ins, op)
        self._put_local(tensor, out)
        return Task()

    def scatter(self, tensor: Tensor, tensor_list: List[Tensor], src: int,
                sync_op=True):
        src = self._g2l(src)
        ins = [self._get_local(t) for t in tensor_list] \
            if self._rank == src else None
        buf = self._get_local(tensor)
        with self._watched("scatter"):
            out = self._scatter_impl(ins, src, shape=buf.shape,
                                     dtype=buf.dtype)
        self._put_local(tensor, out)
        return Task()

    def gather(self, tensor: Tensor, gather_list: Optional[List[Tensor]],
               dst: int, sync_op=True):
        dst = self._g2l(dst)
        with self._watched("gather"):
            outs = self._gather_impl(self._get_local(tensor), dst)
        if self._rank == dst and gather_list is not None:
            if len(gather_list) == 0:
                gather_list.extend(Tensor(o) for o in outs)
            else:
                for t, o in zip(gather_list, outs):
                    self._put_local(t, o)
        return Task()

    def all_to_all(self, out_tensor_list: List[Tensor],
                   in_tensor_list: List[Tensor], sync_op=True):
        with self._watched("all_to_all"):
            outs = self._all_to_all_impl(
                [self._get_local(t) for t in in_tensor_list])
        if len(out_tensor_list) == 0:
            out_tensor_list.extend(Tensor(o) for o in outs)
        else:
            for t, o in zip(out_tensor_list, outs):
                self._put_local(t, o)
        return Task()

    def send(self, tensor: Tensor, dst: int, sync_op=True):
        dst = self._g2l(dst)
        with self._watched("send"):
            self._send_impl(self._get_local(tensor), dst)
        return Task()

    def recv(self, tensor: Tensor, src: int, sync_op=True):
        src = self._g2l(src)
        buf = self._get_local(tensor)
        with self._watched("recv"):
            out = self._recv_impl(src, buf.shape, buf.dtype)
        self._put_local(tensor, out)
        return Task()

    def sendrecv(self, send_tensor: Tensor, recv_tensor: Tensor, peer: int,
                 sync_op=True):
        """Combined send+recv with the SAME peer (the batched-isend/irecv
        role of reference pp_utils send_forward_recv_backward). Backends
        with paired device p2p (XLA) launch it as ONE bidirectional
        program so per-pair launch order matches on both endpoints; the
        buffered store backend just sequences the two ops."""
        p = self._g2l(peer)
        buf = self._get_local(recv_tensor)
        with self._watched("sendrecv"):
            out = self._sendrecv_impl(self._get_local(send_tensor), p,
                                      buf.shape, buf.dtype)
        self._put_local(recv_tensor, out)
        return Task()

    def _sendrecv_impl(self, send_arr, peer, shape, dtype):
        self._send_impl(send_arr, peer)
        return self._recv_impl(peer, shape, dtype)

    def barrier(self, device_id: Optional[int] = None):
        with self._watched("barrier"):
            self._barrier_impl()
        return Task()

    # -- coalescing (reference: process_group.h:119-121; NCCL semantics
    # process_group_nccl.cc:972-976 — buffer the collectives, launch as a
    # batch on end). all_reduce between start/end is deferred; end flushes
    # through _coalesced_all_reduce_impl (one compiled program on XLA).
    def start_coalescing(self):
        if self._coalescing is not None:
            raise RuntimeError(
                "start_coalescing while a coalescing window is already "
                "open; call end_coalescing first (use try/finally around "
                "the window so an exception cannot leave deferred "
                "all_reduces pending forever)")
        self._coalescing = []

    def end_coalescing(self):
        items, self._coalescing = self._coalescing, None
        if not items:
            return Task()
        with self._watched("coalesced_all_reduce"):
            outs = self._coalesced_all_reduce_impl(
                [self._get_local(t) for t, _ in items],
                [op for _, op in items])
        for (t, _), o in zip(items, outs):
            self._put_local(t, o)
        return Task()

    def _coalesced_all_reduce_impl(self, arrs, ops):
        return [self._all_reduce_impl(a, op) for a, op in zip(arrs, ops)]


def _to_jax(arr: np.ndarray, like: Tensor):
    import jax.numpy as jnp

    return jnp.asarray(arr).astype(like._data.dtype)


class ProcessGroupSingle(ProcessGroup):
    """world_size == 1: all collectives are local identities."""

    def __init__(self, gid=0):
        super().__init__(0, 1, gid)

    def _all_reduce_impl(self, arr, op):
        return arr

    def _broadcast_impl(self, arr, src):
        return arr

    def _all_gather_impl(self, arr):
        return [arr]

    def _reduce_impl(self, arr, dst, op):
        return arr

    def _reduce_scatter_impl(self, arrs, op):
        return arrs[0]

    def _scatter_impl(self, arrs, src, shape, dtype):
        return arrs[0]

    def _gather_impl(self, arr, dst):
        return [arr]

    def _all_to_all_impl(self, arrs):
        return arrs

    def _send_impl(self, arr, dst):
        raise RuntimeError("send/recv undefined for world_size==1")

    def _recv_impl(self, src, shape, dtype):
        raise RuntimeError("send/recv undefined for world_size==1")

    def _barrier_impl(self):
        pass


class ProcessGroupCPU(ProcessGroup):
    """TCPStore-backed collectives: the Gloo analog for multi-process tests
    (reference role: fluid/distributed/collective/process_group_gloo.cc)."""

    def __init__(self, store: TCPStore, rank: int, world_size: int,
                 gid: int = 0, group_ranks: Optional[List[int]] = None):
        super().__init__(rank, world_size, gid, group_ranks)
        self._store = store
        self._seq = 0
        self._ranks = self._group_ranks

    def _key(self, tag, rank=None):
        self._seq += 1
        base = f"pg{self._gid}/{tag}/{self._seq}"
        return base if rank is None else f"{base}/r{rank}"

    def _publish(self, key, arr):
        self._store.set(key, pickle.dumps(np.asarray(arr), protocol=4))

    def _fetch(self, key):
        return pickle.loads(self._store.get(key))

    # Collectives: root = group rank 0 gathers, computes, broadcasts back.
    def _gather_all(self, tag, arr):
        """Every rank publishes; every rank reads all -> list by group rank."""
        self._seq += 1
        base = f"pg{self._gid}/{tag}/{self._seq}"
        self._store.set(f"{base}/r{self._rank}",
                        pickle.dumps(np.asarray(arr), protocol=4))
        outs = []
        for r in range(self._world_size):
            outs.append(pickle.loads(self._store.get(f"{base}/r{r}")))
        return outs

    def _all_reduce_impl(self, arr, op):
        outs = self._gather_all("ar", arr)
        acc = outs[0].astype(np.float64 if np.issubdtype(
            outs[0].dtype, np.floating) else outs[0].dtype)
        for o in outs[1:]:
            acc = _NP_REDUCE[op](acc, o)
        if op == ReduceOp.AVG:
            acc = acc / self._world_size
        return acc.astype(arr.dtype)

    def _broadcast_impl(self, arr, src):
        self._seq += 1
        base = f"pg{self._gid}/bc/{self._seq}"
        if self._rank == src:
            self._store.set(f"{base}", pickle.dumps(np.asarray(arr),
                                                    protocol=4))
            return arr
        return pickle.loads(self._store.get(f"{base}"))

    def _all_gather_impl(self, arr):
        return self._gather_all("ag", arr)

    def _reduce_impl(self, arr, dst, op):
        outs = self._gather_all("rd", arr)
        if self._rank != dst:
            return arr
        acc = outs[0]
        for o in outs[1:]:
            acc = _NP_REDUCE[op](acc, o)
        if op == ReduceOp.AVG:
            acc = acc / self._world_size
        return acc.astype(arr.dtype)

    def _reduce_scatter_impl(self, arrs, op):
        outs = self._gather_all("rs", np.stack(arrs))
        acc = outs[0]
        for o in outs[1:]:
            acc = _NP_REDUCE[op](acc, o)
        if op == ReduceOp.AVG:
            acc = acc / self._world_size
        return acc[self._rank].astype(arrs[0].dtype)

    def _scatter_impl(self, arrs, src, shape, dtype):
        self._seq += 1
        base = f"pg{self._gid}/sc/{self._seq}"
        if self._rank == src:
            for r in range(self._world_size):
                self._store.set(f"{base}/r{r}",
                                pickle.dumps(np.asarray(arrs[r]), protocol=4))
        return pickle.loads(self._store.get(f"{base}/r{self._rank}"))

    def _gather_impl(self, arr, dst):
        outs = self._gather_all("ga", arr)
        return outs if self._rank == dst else []

    def _all_to_all_impl(self, arrs):
        outs = self._gather_all("a2a", np.stack(arrs))
        return [outs[r][self._rank] for r in range(self._world_size)]

    def _p2p_key(self, src, dst):
        # per-edge sequence counters so send/recv order pairs up even when
        # ranks interleave other collectives differently (1F1B does this)
        if not hasattr(self, "_p2p_seq"):
            self._p2p_seq = {}
        k = (src, dst)
        self._p2p_seq[k] = self._p2p_seq.get(k, 0) + 1
        return f"pg{self._gid}/p2p/{src}->{dst}/{self._p2p_seq[k]}"

    def _send_impl(self, arr, dst):
        key = self._p2p_key(self._rank, dst)
        self._store.set(key, pickle.dumps(np.asarray(arr), protocol=4))

    def _recv_impl(self, src, shape, dtype):
        key = self._p2p_key(src, self._rank)
        return pickle.loads(self._store.get(key))

    def _barrier_impl(self):
        self._seq += 1
        self._store.barrier(f"pg{self._gid}/b{self._seq}", self._world_size,
                            self._rank)


def new_process_group_impl(backend: str, store, rank: int, world_size: int,
                           gid: int = 0, group_ranks=None) -> ProcessGroup:
    """reference: python/paddle/distributed/collective.py:150
    _new_process_group_impl."""
    if world_size <= 1:
        return ProcessGroupSingle(gid)
    if backend in ("cpu", "gloo", "tcp"):
        return ProcessGroupCPU(store, rank, world_size, gid, group_ranks)
    if backend in ("xla", "tpu", "nccl", "xccl"):
        from .process_group_xla import ProcessGroupXLA

        return ProcessGroupXLA(store, rank, world_size, gid, group_ranks)
    raise ValueError(f"unknown backend {backend}")
