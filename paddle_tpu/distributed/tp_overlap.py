"""Eager chunked computation–collective overlap for the Fleet TP layers.

The compiled/SPMD side of PADDLE_TPU_TP_OVERLAP lives in
:mod:`paddle_tpu.fusion.overlap_mm` (ring ``ppermute`` chunks inside
``shard_map``). This module is the imperative collective-API formulation
for the eager ``fleet`` layers: the same matmuls decomposed into token
chunks so each chunk's collective is dispatched while the next chunk's
GEMM runs, instead of one monolithic collective after the full matmul.

Numerics: chunking a matmul by output rows and a collective by the same
rows is bitwise-exact — each token row's dot product / elementwise sum is
independent of how the rows are batched — so every PyLayer here equals
its serial mp_layers / sequence_parallel_utils counterpart byte-for-byte
(tests/test_tp_overlap.py asserts this in a 2-process spawn run).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import observability as _obs
from ..autograd import PyLayer
from ..core.tensor import Tensor
from ..fusion import overlap_mm
from . import collective as dist

__all__ = [
    "column_parallel_linear", "row_parallel_linear",
    "all_gather_matmul_eager", "matmul_reduce_scatter_eager",
]


def _chunks_for(t: int) -> int:
    return overlap_mm._clamp_chunks(t, overlap_mm.default_chunks())


def _split_rows(arr, chunks):
    # flatten leading dims to tokens; chunk over tokens
    lead, k = arr.shape[:-1], arr.shape[-1]
    return jnp.split(arr.reshape(-1, k), chunks, axis=0), lead


class _ColumnParallelOverlap(PyLayer):
    """Column-parallel linear, overlap formulation: local fwd GEMM
    (input is replicated over mp); the backward's input-grad all-reduce is
    chunked so each chunk's collective overlaps the next chunk's GEMM.
    Serial counterpart: ``_IdentityInBackwardAllReduce`` + ``F.linear``.
    """

    @staticmethod
    def forward(ctx, x, w, b, group):
        ctx.group = group
        ctx.save = (x._data, w._data)
        out = jnp.matmul(x._data, w._data)
        if b is not None:
            out = out + b._data
        ctx.has_bias = b is not None
        return Tensor(out)

    @staticmethod
    def backward(ctx, dy):
        group = ctx.group
        x, w = ctx.save
        g = dy._data
        chunks = _chunks_for(int(g.reshape(-1, g.shape[-1]).shape[0]))
        with _obs.span("tp.overlap_window", cat="collective",
                       args={"op": "mp_column_bwd", "chunks": chunks}):
            gs, lead = _split_rows(g, chunks)
            outs = []
            for gc in gs:
                dxc = Tensor(jnp.matmul(gc, w.T))
                dist.all_reduce(dxc, group=group)
                outs.append(dxc._data)
            dx = jnp.concatenate(outs, axis=0).reshape(lead + (w.shape[0],))
        k, n = x.shape[-1], g.shape[-1]
        dw = jnp.matmul(x.reshape(-1, k).T, g.reshape(-1, n))
        grads = [Tensor(dx), Tensor(dw)]
        if ctx.has_bias:
            grads.append(Tensor(jnp.sum(g, axis=tuple(range(g.ndim - 1)))))
        return tuple(grads)


class _RowParallelOverlap(PyLayer):
    """Row-parallel linear, overlap formulation: the forward's partial-sum
    all-reduce is chunked over token rows so each chunk's collective rides
    the next chunk's GEMM. Serial counterpart: ``F.linear`` +
    ``_AllReduceInForward`` (bias added by the caller, as there).
    """

    @staticmethod
    def forward(ctx, x, w, group):
        ctx.save = (x._data, w._data)
        xd, wd = x._data, w._data
        chunks = _chunks_for(
            int(xd.reshape(-1, xd.shape[-1]).shape[0]))
        with _obs.span("tp.overlap_window", cat="collective",
                       args={"op": "mp_row_fwd", "chunks": chunks}):
            xs, lead = _split_rows(xd, chunks)
            outs = []
            for xc in xs:
                oc = Tensor(jnp.matmul(xc, wd))
                dist.all_reduce(oc, group=group)
                outs.append(oc._data)
            out = jnp.concatenate(outs, axis=0).reshape(
                lead + (wd.shape[-1],))
        return Tensor(out)

    @staticmethod
    def backward(ctx, dy):
        x, w = ctx.save
        g = dy._data
        dx = jnp.matmul(g, w.T)
        k, n = x.shape[-1], g.shape[-1]
        dw = jnp.matmul(x.reshape(-1, k).T, g.reshape(-1, n))
        return Tensor(dx), Tensor(dw)


class _AllGatherMatmulEager(PyLayer):
    """Sequence-parallel column linear as a decomposed all-gather-matmul:
    the sequence all-gather is chunked so each chunk's gather overlaps the
    previous chunk's GEMM, and the backward reduce-scatters the input
    cotangent chunk by chunk. Serial counterpart: ``AllGatherOp`` +
    ``F.linear`` (sequence axis 0, reference layout ``[s, b, h]``).
    """

    @staticmethod
    def forward(ctx, x, w, b, group):
        ctx.group = group
        nranks = group.nranks
        xd, wd = x._data, w._data
        s_local = xd.shape[0]
        chunks = _chunks_for(s_local)
        ctx.chunks = chunks
        gathered = [None] * (nranks * chunks)
        parts = [None] * (nranks * chunks)
        with _obs.span("tp.overlap_window", cat="collective",
                       args={"op": "sp_column_fwd", "chunks": chunks}):
            for j, xc in enumerate(jnp.split(xd, chunks, axis=0)):
                outs = []
                dist.all_gather(outs, Tensor(xc), group=group)
                for r, o in enumerate(outs):
                    gathered[r * chunks + j] = o._data
                    parts[r * chunks + j] = jnp.matmul(o._data, wd)
        xg = jnp.concatenate(gathered, axis=0)
        out = jnp.concatenate(parts, axis=0)
        if b is not None:
            out = out + b._data
        ctx.has_bias = b is not None
        ctx.save = (xg, wd)
        return Tensor(out)

    @staticmethod
    def backward(ctx, dy):
        group, chunks = ctx.group, ctx.chunks
        nranks = group.nranks
        xg, w = ctx.save
        g = dy._data
        # dx: reduce-scatter of g @ w.T over the sequence, chunk by chunk
        dxg_blocks = jnp.split(g, nranks, axis=0)
        dx_chunks = []
        with _obs.span("tp.overlap_window", cat="collective",
                       args={"op": "sp_column_bwd", "chunks": chunks}):
            for j in range(chunks):
                contrib = [Tensor(jnp.matmul(
                    jnp.split(blk, chunks, axis=0)[j], w.T))
                    for blk in dxg_blocks]
                out = Tensor(jnp.zeros_like(contrib[0]._data))
                dist.reduce_scatter(out, contrib, group=group)
                dx_chunks.append(out._data)
        dx = jnp.concatenate(dx_chunks, axis=0)
        k, n = xg.shape[-1], g.shape[-1]
        dw = jnp.matmul(xg.reshape(-1, k).T, g.reshape(-1, n))
        grads = [Tensor(dx), Tensor(dw)]
        if ctx.has_bias:
            grads.append(Tensor(jnp.sum(g, axis=tuple(range(g.ndim - 1)))))
        return tuple(grads)


class _MatmulReduceScatterEager(PyLayer):
    """Sequence-parallel row linear as a decomposed matmul-reduce-scatter:
    each sequence sub-chunk's partial product is reduce-scattered while
    the next sub-chunk's GEMM runs; backward all-gathers the output
    cotangent chunk by chunk. Serial counterpart: ``F.linear`` +
    ``ReduceScatterOp`` (bias added by the caller, as there).
    """

    @staticmethod
    def forward(ctx, x, w, group):
        ctx.group = group
        nranks = group.nranks
        xd, wd = x._data, w._data
        s_full = xd.shape[0]
        s_local = s_full // nranks
        chunks = _chunks_for(s_local)
        ctx.chunks = chunks
        blocks = jnp.split(xd, nranks, axis=0)
        out_chunks = []
        with _obs.span("tp.overlap_window", cat="collective",
                       args={"op": "sp_row_fwd", "chunks": chunks}):
            for j in range(chunks):
                contrib = [Tensor(jnp.matmul(
                    jnp.split(blk, chunks, axis=0)[j], wd))
                    for blk in blocks]
                out = Tensor(jnp.zeros_like(contrib[0]._data))
                dist.reduce_scatter(out, contrib, group=group)
                out_chunks.append(out._data)
        ctx.save = (xd, wd)
        return Tensor(jnp.concatenate(out_chunks, axis=0))

    @staticmethod
    def backward(ctx, dy):
        group, chunks = ctx.group, ctx.chunks
        nranks = group.nranks
        x, w = ctx.save
        g = dy._data
        gathered = [None] * (nranks * chunks)
        with _obs.span("tp.overlap_window", cat="collective",
                       args={"op": "sp_row_bwd", "chunks": chunks}):
            for j, gc in enumerate(jnp.split(g, chunks, axis=0)):
                outs = []
                dist.all_gather(outs, Tensor(gc), group=group)
                for r, o in enumerate(outs):
                    gathered[r * chunks + j] = o._data
        gg = jnp.concatenate(gathered, axis=0)
        dx = jnp.matmul(gg, w.T)
        k, n = x.shape[-1], gg.shape[-1]
        dw = jnp.matmul(x.reshape(-1, k).T, gg.reshape(-1, n))
        return Tensor(dx), Tensor(dw)


# ------------------------------------------------------------- entrypoints
def column_parallel_linear(x, weight, bias, group):
    """Overlap path for ``ColumnParallelLinear`` (pre-gather output)."""
    return _ColumnParallelOverlap.apply(x, weight, bias, group)


def row_parallel_linear(x, weight, group):
    """Overlap path for ``RowParallelLinear`` (bias added by caller)."""
    return _RowParallelOverlap.apply(x, weight, group)


def all_gather_matmul_eager(x, weight, bias, group):
    """Overlap path for ``ColumnSequenceParallelLinear``."""
    return _AllGatherMatmulEager.apply(x, weight, bias, group)


def matmul_reduce_scatter_eager(x, weight, group):
    """Overlap path for ``RowSequenceParallelLinear`` (bias by caller)."""
    return _MatmulReduceScatterEager.apply(x, weight, group)
