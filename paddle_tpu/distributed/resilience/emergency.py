"""Emergency-checkpoint hook registry.

The components that *detect* a dying job (the collective watchdog's
timeout path, the health monitor's ``raise`` policy) know nothing about
the training loop; the component that can *save* it (the Engine's
CheckpointManager) knows nothing about watchdogs. This tiny stdlib-only
registry connects them: the Engine registers a best-effort synchronous
save hook for the duration of ``fit``, and the failure paths call
:func:`trigger` right before the debug bundle / abort.

Hooks must be fast and must never raise (failures are swallowed —
an emergency save must not mask the original failure).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

__all__ = ["register", "unregister", "trigger", "hook_count"]

_lock = threading.Lock()
_hooks: Dict[int, Callable[[str], Optional[str]]] = {}
_next_id = 0


def register(hook: Callable[[str], Optional[str]]) -> int:
    """Register ``hook(reason) -> saved_path_or_None``; returns a token
    for :func:`unregister`."""
    global _next_id
    with _lock:
        _next_id += 1
        _hooks[_next_id] = hook
        return _next_id


def unregister(token: int) -> None:
    with _lock:
        _hooks.pop(token, None)


def hook_count() -> int:
    with _lock:
        return len(_hooks)


def trigger(reason: str) -> List[str]:
    """Run every registered hook; return the paths of successful saves.
    Never raises."""
    with _lock:
        hooks = list(_hooks.values())
    saved: List[str] = []
    for hook in hooks:
        try:
            out = hook(reason)
            if out:
                saved.append(str(out))
        except Exception:
            import traceback

            traceback.print_exc()
    return saved
