"""Emergency-checkpoint hook registry + the shared process-abort path.

The components that *detect* a dying job (the collective watchdog's
timeout path, the health monitor's ``raise`` policy) know nothing about
the training loop; the component that can *save* it (the Engine's
CheckpointManager) knows nothing about watchdogs. This tiny stdlib-only
registry connects them: the Engine registers a best-effort synchronous
save hook for the duration of ``fit``, and the failure paths call
:func:`trigger` right before the debug bundle / abort.

Hooks must be fast and must never raise (failures are swallowed —
an emergency save must not mask the original failure).

:func:`abort_process` is the one door out of the process for every
"this job is wedged" path (the watchdog's AbortComm analog): it runs
the registered **abort interceptors** first — the elastic membership
coordinator claims the abort and converts the hang into a typed
``EpochChanged`` rejoin instead of a death — and only when nobody
claims it does it leave the forensic trail (flight-recorder debug
bundle + emergency checkpoint) and ``os._exit``. A hang and a crash
leave the same evidence either way.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

__all__ = ["register", "unregister", "trigger", "hook_count",
           "register_abort", "unregister_abort", "abort_hook_count",
           "abort_process"]

_lock = threading.Lock()
_hooks: Dict[int, Callable[[str], Optional[str]]] = {}
_abort_hooks: Dict[int, Callable[[str], bool]] = {}
_next_id = 0


def register(hook: Callable[[str], Optional[str]]) -> int:
    """Register ``hook(reason) -> saved_path_or_None``; returns a token
    for :func:`unregister`."""
    global _next_id
    with _lock:
        _next_id += 1
        _hooks[_next_id] = hook
        return _next_id


def unregister(token: int) -> None:
    with _lock:
        _hooks.pop(token, None)


def hook_count() -> int:
    with _lock:
        return len(_hooks)


def trigger(reason: str) -> List[str]:
    """Run every registered hook; return the paths of successful saves.
    Never raises."""
    with _lock:
        hooks = list(_hooks.values())
    saved: List[str] = []
    for hook in hooks:
        try:
            out = hook(reason)
            if out:
                saved.append(str(out))
        except Exception:
            import traceback

            traceback.print_exc()
    return saved


# ------------------------------------------------------------- aborts
def register_abort(hook: Callable[[str], bool]) -> int:
    """Register an abort interceptor: ``hook(reason) -> True`` claims
    the abort (the process survives and recovers through its own path,
    e.g. an elastic epoch change); ``False`` declines. Returns a token
    for :func:`unregister_abort`."""
    global _next_id
    with _lock:
        _next_id += 1
        _abort_hooks[_next_id] = hook
        return _next_id


def unregister_abort(token: int) -> None:
    with _lock:
        _abort_hooks.pop(token, None)


def abort_hook_count() -> int:
    with _lock:
        return len(_abort_hooks)


def abort_process(reason: str, exit_code: int = 1,
                  extra: Optional[dict] = None,
                  forensics_done: bool = False) -> bool:
    """The shared death path. Interceptors run first; a claimed abort
    returns False without exiting. Otherwise the forensic trail is laid
    (debug bundle + emergency-checkpoint hooks, unless the caller
    already did both, as the watchdog's dump does) and the process
    exits hard via ``os._exit(exit_code)``. Never raises on the way
    down."""
    with _lock:
        interceptors = list(_abort_hooks.values())
    for hook in interceptors:
        try:
            if hook(reason):
                import sys

                print(f"[emergency] abort claimed by interceptor: "
                      f"{reason}", file=sys.stderr)
                return False
        except Exception:
            import traceback

            traceback.print_exc()
    if not forensics_done:
        try:
            import os as _os

            from ...observability import flight_recorder

            d = flight_recorder.default_dump_dir()
            if d:
                rank = _os.environ.get("PADDLE_TRAINER_ID", "0")
                flight_recorder.dump_debug_bundle(
                    _os.path.join(
                        d, f"abort_rank{rank}_pid{_os.getpid()}"),
                    reason=reason, extra=extra or {})
        except Exception:
            import traceback

            traceback.print_exc()
        trigger(reason)
    import os as _os

    _os._exit(exit_code)
    return True  # unreachable; keeps the signature honest
