"""paddle_tpu.distributed.resilience — fault tolerance as a subsystem.

The reference stack survives hung collectives (CommTaskManager/AbortComm),
dropped store/rpc connections, and partially written checkpoints natively;
this package gives the reproduction the same reflexes:

- :mod:`retry` — a shared exponential-backoff + jitter + deadline policy
  applied to TCPStore client ops, rpc posting, and process-group
  bootstrap barriers.
- :mod:`faults` — a seeded, deterministic fault-injection harness
  (``PADDLE_TPU_FAULT_PLAN``) that drops store sockets, loses rpc
  messages, delays collectives past the watchdog timeout, truncates or
  bit-flips checkpoint writes, and kills the process mid-run — so every
  recovery path is *tested*, not hoped for.
- :mod:`checkpoint_manager` — periodic async checkpoints with per-shard
  CRC32 manifests, retention, ``latest_valid()`` corruption skipping,
  and emergency best-effort synchronous saves.
- :mod:`emergency` — the registry the watchdog timeout path and the
  health-monitor ``raise`` policy use to trigger an emergency save
  without depending on the training loop.

``CheckpointManager`` is exposed lazily so importing the light retry /
fault layers from transport modules never drags in the tensor stack.
"""
from __future__ import annotations

from . import faults  # noqa: F401
from . import retry  # noqa: F401
from . import emergency  # noqa: F401
from .retry import RetryPolicy, call_with_retry, default_policy  # noqa: F401

__all__ = ["faults", "retry", "emergency", "RetryPolicy",
           "call_with_retry", "default_policy", "CheckpointManager",
           "checkpoint_manager"]


def __getattr__(name):
    # lazy: checkpoint_manager imports distributed.checkpoint (numpy /
    # core.tensor); transport modules importing resilience.retry must
    # not pay for it
    if name in ("CheckpointManager", "checkpoint_manager"):
        # importlib (not ``from . import``): the fromlist lookup would
        # re-enter this __getattr__ while the submodule is mid-import
        import importlib

        mod = importlib.import_module(".checkpoint_manager", __name__)
        if name == "checkpoint_manager":
            return mod
        return mod.CheckpointManager
    raise AttributeError(name)
