"""The declared registry of fault-injection and retry site names.

Every site string passed to ``faults.check("<site>")``,
``call_with_retry(..., site="<site>")`` or the ``retry(site=...)``
decorator MUST be declared here, and every declared site must be
exercised by at least one test — ptlint's ``fault-sites`` pass checks
both directions (REQUIRE_USED style), so a typo'd plan spec like
``PADDLE_TPU_FAULT_PLAN=cp.laese:drop@1`` can't silently inject
nothing, and no site rots untested.

stdlib-only and import-cycle-free: loaded standalone by ptlint via
``importlib.util.spec_from_file_location``.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

__all__ = ["Site", "SITES", "is_declared", "validate"]


class Site(NamedTuple):
    name: str
    subsystem: str
    doc: str


_S = Site

_ALL: Tuple[Site, ...] = (
    # ----------------------------------------------------- substrate
    _S("store.op", "distributed",
       "One TCPStore client op (set/get/add/check/delete); retried "
       "on the default policy."),
    _S("rpc.post", "distributed",
       "One rpc request post on the wire."),
    _S("rpc.resend", "distributed",
       "The rpc retransmit schedule for a silently lost request "
       "(server dedups by call_id)."),
    _S("pg.collective", "distributed",
       "One process-group collective launch."),
    _S("ckpt.write", "distributed",
       "One checkpoint shard write (atomic rename on success)."),
    # ------------------------------------------------- control plane
    _S("cp.lease", "control_plane",
       "One heartbeat lease write; drop loses the beat on the wire."),
    _S("cp.epoch", "control_plane",
       "One epoch commit; delay holds the commit window open."),
    # ------------------------------------------------------ training
    _S("engine.step", "training",
       "One training engine optimizer step."),
    _S("elastic.heartbeat", "elastic",
       "One elastic membership heartbeat."),
    _S("elastic.epoch_commit", "elastic",
       "One elastic group-epoch commit."),
    _S("elastic.reshard", "elastic",
       "One deterministic reshard / peer-snapshot restore."),
    # ------------------------------------------------------------ ps
    _S("ps.pull", "ps",
       "One worker-side sharded pull (sparse or dense)."),
    _S("ps.push", "ps",
       "One worker-side sharded push (sparse, dense, or save)."),
    _S("ps.server", "ps",
       "PS server handler entry (crash/hang the serving shard)."),
    # ------------------------------------------------------- serving
    _S("serving.step", "serving",
       "One ServingEngine step (admit + prefill + decode)."),
    _S("cluster.replica", "serving",
       "One cluster replica step (kill/drop a whole replica)."),
)

SITES: Dict[str, Site] = {s.name: s for s in _ALL}
assert len(SITES) == len(_ALL), "duplicate fault site"


def is_declared(name: str) -> bool:
    return name in SITES


def validate() -> None:
    for s in _ALL:
        assert s.name and s.subsystem and s.doc, s
        assert s.name == s.name.strip().lower(), s.name


validate()
