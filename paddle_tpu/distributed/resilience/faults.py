"""Deterministic fault-injection harness (``PADDLE_TPU_FAULT_PLAN``).

A *plan* is a ``;``-separated list of rules::

    site:kind[=value]@spec

- ``site`` — an instrumented injection point. In-tree sites:
  ``store.op`` (TCPStore client frame exchange), ``rpc.post`` (rpc
  message send), ``pg.collective`` (inside the watchdog window of every
  collective), ``ckpt.write`` (checkpoint shard/metadata write, AFTER
  the atomic rename), ``engine.step`` (top of every Engine.fit step),
  ``serving.step`` (inside the serving engine's retried dispatch),
  ``cluster.replica`` (top of every cluster replica step; ``kill`` /
  ``raise`` / ``drop`` there simulate a replica crash in-process —
  drain + replay — rather than ``os._exit``; ``hang`` makes the
  replica go SILENT instead: it stops stepping and beating but never
  reports, so only the router's missed-lease scan can find it),
  ``cp.lease`` (a heartbeat written through the shared control-plane
  substrate, all namespaces; ``drop`` loses one beat on the wire),
  ``cp.epoch`` (an epoch commit through the substrate; ``delay=<s>``
  holds the commit open mid-transition),
  ``elastic.heartbeat`` (a rank's lease beat; ``drop`` skips the beat
  so peers see a missed-beat lease expiry), ``elastic.epoch_commit``
  (the coordinator's commit write; ``delay=<s>`` holds the epoch ack
  window open), ``elastic.reshard`` (a peer-snapshot fetch during
  shrink/expand adoption; ``truncate`` / ``bitflip`` corrupt the
  fetched CRC-tagged blob, forcing the disk-manifest fallback tier),
  ``ps.pull`` / ``ps.push`` (one PSWorker shard-op attempt: ``drop``
  fails the attempt before the send; ``raise`` fires AFTER the server
  applied — a lost ack, so the retried send with the same sequence
  number must hit the server-side push dedup, not re-apply;
  ``bitflip`` corrupts the first float32 payload array),
  ``ps.server`` (PS handler entry: ``kill`` is the failover drill's
  primary death, ``delay`` stalls the reply past the worker's rpc
  timeout, ``raise``/``drop`` fail the request after delivery).
- ``kind`` — what to inject: ``drop`` (close + fail the store socket),
  ``loss`` (silently discard an rpc message), ``delay=<s>`` (sleep,
  e.g. past the watchdog timeout), ``truncate`` / ``bitflip``
  (corrupt the just-written checkpoint file), ``kill[=<code>]``
  (``os._exit``, a hard crash), ``raise`` (ConnectionError).
- ``spec`` — WHEN: ``@2`` the 2nd invocation of that site, ``@2,5``
  the 2nd and 5th, ``@p0.05`` each invocation with probability 0.05
  drawn from a ``random.Random(PADDLE_TPU_FAULT_SEED)`` — seeded, so a
  given (plan, seed) replays the exact same fault schedule.

Example::

    PADDLE_TPU_FAULT_PLAN="store.op:drop@3;engine.step:kill=31@7"

Sites call :func:`check` (cheap: one bool when no plan is active) and
handle site-specific kinds themselves; :func:`apply` executes the
generic kinds (delay / kill / raise). Every injection is counted
(``resilience.injected_faults``), flight-recorded, and appended to the
in-process :func:`injected` log so tests can assert the schedule fired.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ...config import knobs

__all__ = ["FaultAction", "configure", "reset", "active", "check",
           "apply", "injected", "plan_text"]


class FaultAction:
    __slots__ = ("site", "kind", "value", "invocation")

    def __init__(self, site: str, kind: str, value: Optional[str],
                 invocation: int):
        self.site = site
        self.kind = kind
        self.value = value
        self.invocation = invocation

    def __repr__(self):
        v = f"={self.value}" if self.value is not None else ""
        return (f"FaultAction({self.site}:{self.kind}{v}"
                f"@{self.invocation})")


class _Rule:
    __slots__ = ("kind", "value", "at", "prob")

    def __init__(self, kind: str, value: Optional[str],
                 at: Tuple[int, ...], prob: Optional[float]):
        self.kind = kind
        self.value = value
        self.at = at
        self.prob = prob


_lock = threading.Lock()
_rules: Dict[str, List[_Rule]] = {}
_counters: Dict[str, int] = {}
_rng = random.Random(0)
_log: List[FaultAction] = []
_plan_text: Optional[str] = None
_env_loaded = False


def _parse(plan: str) -> Dict[str, List[_Rule]]:
    rules: Dict[str, List[_Rule]] = {}
    for entry in plan.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, rest = entry.split(":", 1)
            action, spec = rest.rsplit("@", 1)
            value = None
            if "=" in action:
                action, value = action.split("=", 1)
            spec = spec.strip()
            if spec.startswith("p"):
                at, prob = (), float(spec[1:])
            else:
                at = tuple(int(x) for x in spec.split(",") if x.strip())
                prob = None
        except ValueError as e:
            raise ValueError(
                f"bad PADDLE_TPU_FAULT_PLAN entry {entry!r} "
                f"(want site:kind[=value]@n[,n...]|@p<prob>)") from e
        rules.setdefault(site.strip(), []).append(
            _Rule(action.strip(), value, at, prob))
    return rules


def configure(plan: Optional[str], seed: Optional[int] = None) -> None:
    """Install a plan (None/'' clears). Resets invocation counters and
    the injection log; the probability stream restarts from ``seed``."""
    global _rules, _counters, _rng, _log, _plan_text, _env_loaded
    with _lock:
        _env_loaded = True
        _plan_text = plan or None
        _rules = _parse(plan) if plan else {}
        _counters = {}
        _log = []
        if seed is None:
            seed = knobs.get_int("PADDLE_TPU_FAULT_SEED")
        _rng = random.Random(seed)


def reset() -> None:
    configure(None)


def _ensure_env_loaded() -> None:
    global _env_loaded
    if not _env_loaded:
        configure(knobs.get_str("PADDLE_TPU_FAULT_PLAN"))


def active() -> bool:
    _ensure_env_loaded()
    return bool(_rules)


def plan_text() -> Optional[str]:
    _ensure_env_loaded()
    return _plan_text


def injected() -> List[FaultAction]:
    with _lock:
        return list(_log)


def _record(act: FaultAction) -> None:
    try:
        from ... import observability as _obs

        if _obs.enabled():
            _obs.registry.counter(
                "resilience.injected_faults",
                tags={"site": act.site, "kind": act.kind}).inc()
            _obs.flight_recorder.record(
                "resilience.fault_injected", site=act.site,
                kind=act.kind, value=act.value,
                invocation=act.invocation)
    except Exception:
        pass
    import sys

    print(f"[fault-injection] {act!r}", file=sys.stderr)


def check(site: str) -> Optional[FaultAction]:
    """Count one invocation of ``site``; return the action to inject at
    this invocation, or None. At most one rule fires per invocation."""
    _ensure_env_loaded()
    if not _rules:
        return None
    with _lock:
        n = _counters.get(site, 0) + 1
        _counters[site] = n
        for rule in _rules.get(site, ()):
            hit = (n in rule.at) if rule.prob is None else \
                (_rng.random() < rule.prob)
            if hit:
                act = FaultAction(site, rule.kind, rule.value, n)
                _log.append(act)
                break
        else:
            return None
    _record(act)
    return act


def apply(act: FaultAction) -> None:
    """Execute the generic kinds. Site-specific kinds (drop / loss /
    truncate / bitflip) are handled at the call site and ignored here."""
    if act.kind == "delay":
        time.sleep(float(act.value if act.value is not None else 1.0))
    elif act.kind == "kill":
        os._exit(int(act.value if act.value is not None else 17))
    elif act.kind == "raise":
        raise ConnectionError(f"fault-injected error at {act.site} "
                              f"(invocation {act.invocation})")
