"""Shared retry policy: exponential backoff + deterministic jitter +
deadline (reference analog: the reconnect/retry loops inside the C++
TCPStore client and brpc agent, here factored into ONE policy object so
every distributed I/O path — store ops, rpc posting, process-group
bootstrap — backs off the same way).

Env knobs (read once per :func:`default_policy` call):

- ``PADDLE_TPU_RETRY_MAX_ATTEMPTS`` (default 5) — total attempts
- ``PADDLE_TPU_RETRY_BASE_DELAY``   (default 0.05 s) — first backoff
- ``PADDLE_TPU_RETRY_MAX_DELAY``    (default 2.0 s) — backoff ceiling
- ``PADDLE_TPU_RETRY_SEED``         (default 0) — jitter seed

Jitter is drawn from a ``random.Random`` seeded per call site, so a
given (seed, site) produces the same delay sequence on every run — the
fault-injection tests rely on that determinism.

Telemetry: each retried attempt increments ``resilience.retries``
(tagged by site) and records a flight-recorder event when telemetry is
enabled; the RETRY itself works regardless — recovery is a correctness
feature, not a metrics feature.
"""
from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type

from ...config import knobs

__all__ = ["RetryPolicy", "default_policy", "call_with_retry", "retry"]

# TimeoutError is an OSError subclass since 3.10, listed explicitly for
# readers; ConnectionError covers reset/refused/aborted.
_DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError)


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25            # +[0, jitter) fraction of the delay
    deadline: Optional[float] = None  # overall budget in seconds
    retry_on: Tuple[Type[BaseException], ...] = field(
        default=_DEFAULT_RETRY_ON)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        return d * (1.0 + self.jitter * rng.random())

    def with_deadline(self, deadline: Optional[float]) -> "RetryPolicy":
        if deadline is None:
            return self
        return RetryPolicy(self.max_attempts, self.base_delay,
                           self.max_delay, self.multiplier, self.jitter,
                           deadline, self.retry_on)


def default_policy(deadline: Optional[float] = None,
                   **overrides) -> RetryPolicy:
    """Policy from the ``PADDLE_TPU_RETRY_*`` env knobs."""
    kw = dict(
        max_attempts=knobs.get_int("PADDLE_TPU_RETRY_MAX_ATTEMPTS"),
        base_delay=knobs.get_float("PADDLE_TPU_RETRY_BASE_DELAY"),
        max_delay=knobs.get_float("PADDLE_TPU_RETRY_MAX_DELAY"),
        deadline=deadline,
    )
    kw.update(overrides)
    return RetryPolicy(**kw)


def _jitter_rng(site: str) -> random.Random:
    seed = knobs.get_int("PADDLE_TPU_RETRY_SEED")
    # stable per (seed, site): zlib.crc32 is deterministic across runs,
    # unlike hash() under PYTHONHASHSEED randomization
    import zlib

    return random.Random(seed ^ zlib.crc32(site.encode()))


def _record_retry(site: str, attempt: int, err: BaseException) -> None:
    try:
        from ... import observability as _obs

        if _obs.enabled():
            _obs.registry.counter("resilience.retries",
                                  tags={"site": site}).inc()
            _obs.flight_recorder.record("resilience.retry", site=site,
                                        attempt=attempt,
                                        error=type(err).__name__)
    except Exception:
        pass


def call_with_retry(fn: Callable, policy: Optional[RetryPolicy] = None,
                    site: str = "retry",
                    on_retry: Optional[Callable] = None,
                    sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` under ``policy``; between attempts call
    ``on_retry(error)`` (e.g. a socket reconnect) and back off. The
    deadline bounds the WHOLE call: a retry whose backoff would cross
    it re-raises the last error instead of sleeping past the budget."""
    policy = policy or default_policy()
    rng = _jitter_rng(site)
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retry_on as e:
            attempt += 1
            if attempt >= policy.max_attempts:
                raise
            d = policy.delay(attempt, rng)
            if policy.deadline is not None and \
                    time.monotonic() + d - start > policy.deadline:
                raise
            _record_retry(site, attempt, e)
            if on_retry is not None:
                try:
                    on_retry(e)
                except Exception:
                    pass  # reconnect failure surfaces on the next attempt
            sleep(d)


def retry(policy: Optional[RetryPolicy] = None, site: Optional[str] = None):
    """Decorator form of :func:`call_with_retry`."""
    def deco(fn):
        import functools

        s = site or getattr(fn, "__qualname__", "retry")

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retry(lambda: fn(*args, **kwargs),
                                   policy=policy, site=s)
        return wrapped
    return deco
