"""Checkpoint lifecycle on top of the sharded ``distributed/checkpoint``
module: periodic async saves, per-shard CRC32 manifests, retention,
``latest_valid()`` corruption skipping, and emergency synchronous saves.

Layout under ``root``::

    step_00000004/
        0_0.distcp          # per-rank shard payload (sharded save)
        0.metadata          # coordinator's global metadata
        MANIFEST_0.json     # per-rank manifest: files + CRC32 + sizes
    emergency_step_00000007/
        ...

A checkpoint directory is *valid* iff every rank 0..world_size-1 of the
save wrote a manifest and every file each manifest lists exists with
the recorded size and CRC32. The manifest is written only AFTER the
payload flush completes, so a crash mid-save leaves a manifest-less
(= invisible) directory, and a torn/corrupted shard fails the CRC —
``latest_valid()`` skips both and falls back to the previous step.

Async saves snapshot tensors to host synchronously (inside
``save_state_dict``) and overlap the disk write + manifest finalize
with subsequent training steps (T3-style compute/IO overlap); ``wait``
drains them and is registered via ``atexit`` so a clean interpreter
exit never loses an in-flight save.
"""
from __future__ import annotations

import atexit
import json
import os
import re
import shutil
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..checkpoint import load_state_dict, save_state_dict

__all__ = ["CheckpointManager", "validate_checkpoint_dir"]

_MANIFEST_RE = re.compile(r"^MANIFEST_(\d+)\.json$")
_STEP_RE = re.compile(r"^(emergency_)?step_(\d+)$")


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            crc = zlib.crc32(b, crc)
    return crc & 0xFFFFFFFF


def validate_checkpoint_dir(path: str) -> Tuple[bool, str]:
    """CRC-validate one checkpoint directory. Returns (ok, detail).
    Mirrored by the stdlib-only ``tools/verify_checkpoint.py`` so CI can
    validate checkpoints without importing the framework."""
    if not os.path.isdir(path):
        return False, "not a directory"
    manifests: Dict[int, dict] = {}
    for fn in os.listdir(path):
        m = _MANIFEST_RE.match(fn)
        if not m:
            continue
        try:
            with open(os.path.join(path, fn)) as f:
                manifests[int(m.group(1))] = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"unreadable manifest {fn}: {e}"
    if not manifests:
        return False, "no manifest"
    worlds = {int(man.get("world_size", 1)) for man in manifests.values()}
    if len(worlds) != 1:
        return False, f"inconsistent world_size across manifests: {worlds}"
    world = worlds.pop()
    missing = sorted(set(range(world)) - set(manifests))
    if missing:
        return False, f"missing manifest for rank(s) {missing}"
    for rank, man in sorted(manifests.items()):
        for fname, info in man.get("files", {}).items():
            fpath = os.path.join(path, fname)
            if not os.path.exists(fpath):
                return False, f"missing file {fname} (rank {rank})"
            size = os.path.getsize(fpath)
            if size != int(info["size"]):
                return False, (f"size mismatch {fname}: "
                               f"{size} != {info['size']}")
            crc = _crc32_file(fpath)
            if crc != int(info["crc32"]):
                return False, (f"crc mismatch {fname}: "
                               f"{crc:#010x} != {int(info['crc32']):#010x}")
    return True, "ok"


class CheckpointManager:
    """Periodic + emergency checkpoints with CRC manifests, retention
    (``keep_last``) and corrupt-skip resume."""

    def __init__(self, root: str, keep_last: int = 3,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None):
        from ..parallel_env import get_rank, get_world_size

        self.root = root
        self.keep_last = max(int(keep_last), 1)
        self._rank = get_rank() if rank is None else int(rank)
        self._world = get_world_size() if world_size is None \
            else int(world_size)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._pending: List[threading.Thread] = []
        # a clean exit must not lose the last in-flight async save
        atexit.register(self.wait)

    # ---------------------------------------------------------------- paths
    def step_dir(self, step: int, emergency: bool = False) -> str:
        tag = "emergency_step_" if emergency else "step_"
        return os.path.join(self.root, f"{tag}{int(step):08d}")

    def checkpoints(self) -> List[Tuple[int, str]]:
        """All checkpoint dirs (valid or not), newest step first; at the
        same step a regular save sorts before its emergency sibling."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for fn in names:
            m = _STEP_RE.match(fn)
            if m:
                out.append((int(m.group(2)), m.group(1) is None, fn))
        out.sort(reverse=True)
        return [(step, os.path.join(self.root, fn))
                for step, _, fn in out]

    # ----------------------------------------------------------------- save
    def save(self, state_dict, step: int, blocking: bool = False,
             emergency: bool = False) -> str:
        """Checkpoint ``state_dict`` for ``step``. Non-blocking saves
        snapshot to host now and finalize (flush + CRC manifest +
        retention) on a background thread."""
        from ... import observability as _obs

        path = self.step_dir(step, emergency)
        os.makedirs(path, exist_ok=True)
        with _obs.span("ckpt.save", args={"step": int(step),
                                          "blocking": bool(blocking)}):
            ticket = save_state_dict(state_dict, path,
                                     async_save=not blocking)
            if blocking:
                self._finalize(path, step, ticket, emergency)
            else:
                t = threading.Thread(
                    target=self._finalize_bg,
                    args=(path, step, ticket, emergency), daemon=True)
                t.start()
                with self._lock:
                    self._pending.append(t)
        return path

    def emergency_save(self, state_dict, step: int,
                       reason: str = "") -> Optional[str]:
        """Best-effort synchronous save (watchdog timeout / health
        ``raise`` path). Never raises — the original failure must keep
        propagating."""
        import sys

        try:
            path = self.save(state_dict, step, blocking=True,
                             emergency=True)
            print(f"[resilience] emergency checkpoint (step {step}): "
                  f"{path}" + (f" — {reason}" if reason else ""),
                  file=sys.stderr)
            return path
        except Exception:
            import traceback

            traceback.print_exc()
            return None

    def _finalize_bg(self, path, step, ticket, emergency):
        try:
            ticket.wait()
        except BaseException:
            import traceback

            traceback.print_exc()
            return  # no manifest: the directory stays invisible
        self._finalize(path, step, ticket, emergency)

    def _finalize(self, path, step, ticket, emergency):
        if not ticket.done():
            ticket.wait()
        manifest = {
            "format": 1,
            "step": int(step),
            "rank": self._rank,
            "world_size": self._world,
            "emergency": bool(emergency),
            "unix_time": time.time(),
            "files": ticket.report,
        }
        mpath = os.path.join(path, f"MANIFEST_{self._rank}.json")
        tmp = f"{mpath}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, mpath)
        try:
            from ... import observability as _obs

            if _obs.enabled():
                _obs.registry.counter(
                    "resilience.emergency_saves" if emergency
                    else "resilience.checkpoint_saves").inc()
                _obs.flight_recorder.record(
                    "resilience.checkpoint_saved", step=int(step),
                    path=path, emergency=bool(emergency))
        except Exception:
            pass
        if self._rank == 0 and not emergency:
            self._retain()

    def _retain(self):
        """Drop the oldest VALID regular checkpoints beyond keep_last
        (invalid/in-progress dirs are never deleted here: an in-flight
        async save looks invalid until its manifest lands)."""
        valid = [(step, p) for step, p in self.checkpoints()
                 if os.path.basename(p).startswith("step_")
                 and validate_checkpoint_dir(p)[0]]
        for _, p in valid[self.keep_last:]:
            shutil.rmtree(p, ignore_errors=True)

    def wait(self) -> None:
        """Drain pending async finalizes (also runs via ``atexit``)."""
        with self._lock:
            pending, self._pending = self._pending, []
        for t in pending:
            t.join()

    # --------------------------------------------------------------- resume
    def latest_valid(self) -> Optional[Tuple[int, str]]:
        """Newest checkpoint that passes CRC validation, skipping (and
        counting) corrupt or partially written ones."""
        for step, path in self.checkpoints():
            ok, detail = validate_checkpoint_dir(path)
            if ok:
                return step, path
            import sys

            print(f"[resilience] skipping invalid checkpoint {path}: "
                  f"{detail}", file=sys.stderr)
            try:
                from ... import observability as _obs

                if _obs.enabled():
                    _obs.registry.counter(
                        "resilience.corrupt_checkpoints").inc()
                    _obs.flight_recorder.record(
                        "resilience.checkpoint_skipped", path=path,
                        detail=detail)
            except Exception:
                pass
        return None

    def load(self, state_dict, path: str) -> None:
        from ... import observability as _obs

        with _obs.span("ckpt.restore", args={"path": path}):
            load_state_dict(state_dict, path)
