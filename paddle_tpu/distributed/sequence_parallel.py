"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference provides only the group plumbing for its ``sep`` axis and
leaves the attention-side sequence exchange to model libraries (reference:
python/paddle/distributed/fleet/base/topology.py:199-258 sep groups;
test/collective/fleet/hybrid_parallel_sep_model.py:132-148 shows the
user-side pattern; no ring/Ulysses kernel in-repo). Here both are
first-class, TPU-native:

- :func:`ring_attention` — blockwise-softmax attention where K/V chunks
  rotate around the sequence-axis ring via ``lax.ppermute`` (ICI
  neighbor exchange), with online max/denominator accumulation. O(S/P)
  memory per chip; compute overlaps the permute (XLA pipelines the
  collective-permute with the per-step einsum).
- :func:`ulysses_attention` — all-to-all head<->sequence exchange
  (DeepSpeed-Ulysses style): each chip attends over the FULL sequence
  for ``heads/P`` heads, so the local attention can use the Pallas flash
  kernel, then a second all-to-all restores sequence sharding.

Both are written to be called INSIDE ``jax.shard_map`` over a mesh with
a sequence axis; the ``*_sharded`` wrappers apply shard_map for global
arrays. Both are differentiable (ppermute/all_to_all have transpose
rules; the ring step is rematerialized so residuals stay O(chunk)).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention",
           "ring_attention_sharded", "ulysses_attention_sharded"]

_NEG_INF = -1e30


def _chunk_attention(q, k, v, scale, pos_q, pos_k, causal):
    """One blockwise step: returns (unnormalized acc, rowmax m, denom l).

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; pos_*: global token positions.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = pos_q[:, None] >= pos_k[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)          # [b,h,q,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return acc, m, l


def ring_attention(q, k, v, axis_name, causal=True, scale=None):
    """Ring attention over the ``axis_name`` mesh axis (call in shard_map).

    q/k/v: LOCAL sequence shards ``[batch, seq_local, heads, head_dim]``.
    Returns the local output shard, same shape/dtype as q.
    """
    b, sl, h, d = q.shape
    if scale is None:
        scale = float(d) ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    pos_q = my * sl + jnp.arange(sl)

    @jax.checkpoint
    def step_compute(q, k_cur, v_cur, src, m_prev, l_prev, acc_prev):
        pos_k = src * sl + jnp.arange(sl)
        acc_c, m_c, l_c = _chunk_attention(q, k_cur, v_cur, scale,
                                           pos_q, pos_k, causal)
        m_new = jnp.maximum(m_prev, m_c)
        corr_prev = jnp.exp(m_prev - m_new)
        corr_c = jnp.exp(m_c - m_new)
        l_new = corr_prev * l_prev + corr_c * l_c
        acc_new = corr_prev * acc_prev + corr_c * acc_c
        return m_new, l_new, acc_new

    def body(carry, t):
        k_cur, v_cur, m_prev, l_prev, acc_prev = carry
        src = (my - t) % axis_size
        m_new, l_new, acc_new = step_compute(
            q, k_cur, v_cur, src, m_prev, l_prev, acc_prev)
        # rotate kv to the next rank (skip after the final step's compute
        # would be ideal; XLA overlaps the permute with the next compute)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, sl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sl, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, sl, d), jnp.float32)
    (k_f, v_f, m_f, l_f, acc_f), _ = jax.lax.scan(
        body, (k, v, m0, l0, acc0), jnp.arange(axis_size))
    del k_f, v_f
    l_safe = jnp.where(l_f == 0.0, 1.0, l_f)
    out = (acc_f / l_safe).astype(q.dtype)          # [b,h,s,d]
    return jnp.swapaxes(out, 1, 2)


def ulysses_attention(q, k, v, axis_name, causal=True, scale=None,
                      attention_fn=None):
    """Ulysses all-to-all attention over ``axis_name`` (call in shard_map).

    q/k/v: LOCAL sequence shards ``[batch, seq_local, heads, head_dim]``;
    ``heads`` must be divisible by the axis size. Exchanges seq<->heads so
    each rank runs full-sequence attention on heads/P heads (flash-attn
    eligible), then exchanges back.
    """
    b, sl, h, d = q.shape
    axis_size = jax.lax.psum(1, axis_name)

    def a2a_fwd(x):
        # [b, s_loc, h, d] -> [b, s_full, h/P, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def a2a_bwd(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = a2a_fwd(q), a2a_fwd(k), a2a_fwd(v)
    if attention_fn is None:
        def attention_fn(q_, k_, v_):
            from ..incubate.nn.functional.flash_attention import (
                _use_pallas, _xla_attention)
            from ..incubate.nn.pallas.flash_attn import flash_attention

            if _use_pallas(tuple(q_.shape), k_.shape[1], q_.shape[-1]):
                return flash_attention(q_, k_, v_, causal=causal, scale=scale)
            return _xla_attention(q_, k_, v_, causal, scale)

    out = attention_fn(qg, kg, vg)
    return a2a_bwd(out)


def _sharded(fn, mesh, seq_axis, batch_axis=None):
    spec = P(batch_axis, seq_axis, None, None)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )


def ring_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str,
                           causal=True, scale=None, batch_axis=None):
    """Ring attention on GLOBAL arrays [b, s, h, d] sharded over seq_axis."""
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal,
                           scale=scale)
    wrapped = _sharded(lambda q, k, v: fn(q, k, v), mesh, seq_axis,
                       batch_axis)
    spec = P(batch_axis, seq_axis, None, None)
    q, k, v = (jax.device_put(x, NamedSharding(mesh, spec))
               for x in (q, k, v))
    return wrapped(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str,
                              causal=True, scale=None, batch_axis=None):
    """Ulysses attention on GLOBAL arrays [b, s, h, d] sharded over seq_axis."""
    fn = functools.partial(ulysses_attention, axis_name=seq_axis,
                           causal=causal, scale=scale)
    wrapped = _sharded(lambda q, k, v: fn(q, k, v), mesh, seq_axis,
                       batch_axis)
    spec = P(batch_axis, seq_axis, None, None)
    q, k, v = (jax.device_put(x, NamedSharding(mesh, spec))
               for x in (q, k, v))
    return wrapped(q, k, v)
