"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py —
init:218, _init_hybrid_parallel_env:674, distributed_model in fleet/model.py:32,
distributed_optimizer in fleet/optimizer.py:68)."""
from __future__ import annotations

from typing import Optional

from .distributed_strategy import DistributedStrategy
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["init", "Fleet", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer", "worker_num",
           "worker_index", "is_first_worker", "barrier_worker"]

_fleet: Optional["Fleet"] = None
_hcg: Optional[HybridCommunicateGroup] = None


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


class Fleet:
    def __init__(self):
        self._is_collective = True
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        global _hcg
        from ..parallel_env import ParallelEnv, init_parallel_env

        self._is_collective = is_collective
        self._strategy = strategy or DistributedStrategy()
        env = ParallelEnv()
        if env.world_size > 1:
            init_parallel_env()
        self._init_hybrid_parallel_env()
        _hcg = self._hcg
        return self

    def _init_hybrid_parallel_env(self):
        """reference: fleet.py:674-737."""
        hc = self._strategy.hybrid_configs
        self.dp_degree = max(hc.get("dp_degree", 1), 1)
        self.mp_degree = max(hc.get("mp_degree", 1), 1)
        self.pp_degree = max(hc.get("pp_degree", 1), 1)
        self.sharding_degree = max(hc.get("sharding_degree", 1), 1)
        self.sep_degree = max(hc.get("sep_degree", 1), 1)
        order = hc.get("order", ["dp", "pp", "sharding", "sep", "mp"])
        name_map = {"dp": "data", "pp": "pipe", "sharding": "sharding",
                    "sep": "sep", "mp": "model"}
        degree_map = {"data": self.dp_degree, "pipe": self.pp_degree,
                      "sharding": self.sharding_degree, "sep": self.sep_degree,
                      "model": self.mp_degree}
        names = [name_map[o] for o in order]
        dims = [degree_map[n] for n in names]

        from ..parallel_env import ParallelEnv

        world = ParallelEnv().world_size
        prod = 1
        for d in dims:
            prod *= d
        if prod != world:
            # auto-fill dp like the reference when degrees don't multiply out
            rest = world // max(prod // max(self.dp_degree, 1), 1)
            if "data" in names and prod != world and world % (
                    prod // self.dp_degree) == 0:
                self.dp_degree = world // (prod // self.dp_degree)
                dims[names.index("data")] = self.dp_degree
        self._topology = CommunicateTopology(names, dims)
        self._hcg = HybridCommunicateGroup(self._topology)

    @property
    def strategy(self):
        return self._strategy

    def get_hybrid_communicate_group(self):
        return self._hcg

    def worker_num(self):
        from ..parallel_env import ParallelEnv

        return ParallelEnv().world_size

    def worker_index(self):
        from ..parallel_env import ParallelEnv

        return ParallelEnv().rank

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..collective import barrier

        barrier()

    def distributed_model(self, model):
        """reference: fleet/model.py:32 — wrap by parallel mode."""
        from .meta_parallel import (PipelineParallel, ShardingParallel,
                                    TensorParallel)
        from .topology import ParallelMode
        from ..parallel import DataParallel

        if self._hcg is None:
            return model
        mode = self._hcg.get_parallel_mode()
        if mode == ParallelMode.PIPELINE_PARALLEL:
            return PipelineParallel(model, self._hcg,
                                    strategy=self._strategy)
        if mode == ParallelMode.TENSOR_PARALLEL:
            return TensorParallel(model, self._hcg, strategy=self._strategy)
        if mode == ParallelMode.SHARDING_PARALLEL:
            return ShardingParallel(model, self._hcg,
                                    strategy=self._strategy)
        if self._hcg.get_data_parallel_world_size() > 1:
            return DataParallel(
                model, group=self._hcg.get_data_parallel_group())
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """reference: fleet/optimizer.py:68."""
        from .hybrid_parallel_optimizer import HybridParallelOptimizer

        if self._hcg is None:
            return optimizer
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._strategy)

    # state io passthroughs
    def save(self, *args, **kwargs):
        from ...framework.io_utils import save as _save

        return _save(*args, **kwargs)


def init(role_maker=None, is_collective=True, strategy=None,
         log_level="INFO"):
    global _fleet
    if _fleet is None:
        _fleet = Fleet()
    return _fleet.init(role_maker, is_collective, strategy, log_level)


def _get_fleet() -> Fleet:
    global _fleet
    if _fleet is None:
        _fleet = Fleet()
    return _fleet


def distributed_model(model):
    return _get_fleet().distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _get_fleet().distributed_optimizer(optimizer, strategy)


def worker_num():
    return _get_fleet().worker_num()


def worker_index():
    return _get_fleet().worker_index()


def is_first_worker():
    return _get_fleet().is_first_worker()


def barrier_worker():
    return _get_fleet().barrier_worker()
