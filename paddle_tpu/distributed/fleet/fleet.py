"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py —
init:218, _init_hybrid_parallel_env:674, distributed_model in fleet/model.py:32,
distributed_optimizer in fleet/optimizer.py:68)."""
from __future__ import annotations

from typing import Optional

from .distributed_strategy import DistributedStrategy
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["init", "Fleet", "get_hybrid_communicate_group",
           "distributed_model", "distributed_optimizer", "worker_num",
           "worker_index", "is_first_worker", "barrier_worker"]

_fleet: Optional["Fleet"] = None
_hcg: Optional[HybridCommunicateGroup] = None


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def _apply_strategy_to_model(model, strategy):
    """Materialize DistributedStrategy model-side knobs (reference:
    distributed_strategy.py:284 — amp/recompute configs that the static
    engine applies as passes; here they transform the dygraph model).

    - ``strategy.recompute``: each sublayer named in
      ``recompute_configs["checkpoints"]`` gets its forward routed through
      the recompute engine (rematerialized in backward).
    - ``strategy.amp``: the model forward runs under ``amp.auto_cast`` at
      O2 when pure fp16/bf16 is configured, else O1.
    """
    if strategy is None:
        return model
    if getattr(strategy, "recompute", False):
        from .recompute import recompute as _rc

        ckpts = set(strategy.recompute_configs.get("checkpoints") or [])
        for name, sub in model.named_sublayers():
            if name in ckpts and not getattr(sub, "_fleet_recompute", False):
                orig = sub.forward

                def wrapped(*a, __orig=orig, **kw):
                    return _rc(__orig, *a, **kw)

                sub.forward = wrapped
                sub._fleet_recompute = True
    if getattr(strategy, "amp", False):
        from ...amp import auto_cast

        cfg = strategy.amp_configs or {}
        pure = cfg.get("use_pure_fp16") or cfg.get("use_pure_bf16")
        dtype = "bfloat16" if cfg.get("use_pure_bf16") else "float16"
        level = "O2" if pure else "O1"
        if not getattr(model, "_fleet_amp", False):
            orig_fwd = model.forward

            def amp_fwd(*a, __orig=orig_fwd, **kw):
                with auto_cast(True, level=level, dtype=dtype):
                    return __orig(*a, **kw)

            model.forward = amp_fwd
            model._fleet_amp = True
    return model


class Fleet:
    def __init__(self):
        self._is_collective = True
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        global _hcg
        from ..parallel_env import ParallelEnv, init_parallel_env

        self._role_maker = role_maker
        self._ps_runtime = None
        if role_maker is not None and not is_collective:
            # PS mode: accept the role maker so PS-style scripts role-detect
            # and reach the runtime boundary, where they fail with guidance
            # (collective-first design, SURVEY §2.4.17; ps/__init__.py)
            from ..ps import TheOnePSRuntime

            self._is_collective = False
            self._strategy = strategy or DistributedStrategy()
            self._ps_runtime = TheOnePSRuntime(role_maker)
            return self
        self._is_collective = is_collective
        self._strategy = strategy or DistributedStrategy()
        env = ParallelEnv()
        if env.world_size > 1:
            init_parallel_env()
        self._init_hybrid_parallel_env()
        _hcg = self._hcg
        return self

    # ---- PS-mode surface (stubs with guidance; reference fleet.py
    # is_server/init_server/run_server/init_worker/stop_worker) ----
    def is_server(self) -> bool:
        rm = getattr(self, "_role_maker", None)
        return bool(rm and rm.is_server())

    def is_worker(self) -> bool:
        rm = getattr(self, "_role_maker", None)
        return rm.is_worker() if rm else True

    def _ps(self):
        from ..ps import PSGuidanceError, TheOnePSRuntime

        rt = getattr(self, "_ps_runtime", None)
        if rt is None:
            raise PSGuidanceError("PS runtime (fleet.init was collective)")
        return rt

    def init_server(self, *a, **k):
        return self._ps().init_server(*a, **k)

    def run_server(self, *a, **k):
        return self._ps().run_server(*a, **k)

    def init_worker(self, *a, **k):
        return self._ps().init_worker(*a, **k)

    def stop_worker(self, *a, **k):
        return self._ps().stop_worker(*a, **k)

    def _init_hybrid_parallel_env(self):
        """reference: fleet.py:674-737."""
        hc = self._strategy.hybrid_configs
        self.dp_degree = max(hc.get("dp_degree", 1), 1)
        self.mp_degree = max(hc.get("mp_degree", 1), 1)
        self.pp_degree = max(hc.get("pp_degree", 1), 1)
        self.sharding_degree = max(hc.get("sharding_degree", 1), 1)
        self.sep_degree = max(hc.get("sep_degree", 1), 1)
        order = hc.get("order", ["dp", "pp", "sharding", "sep", "mp"])
        name_map = {"dp": "data", "pp": "pipe", "sharding": "sharding",
                    "sep": "sep", "mp": "model"}
        degree_map = {"data": self.dp_degree, "pipe": self.pp_degree,
                      "sharding": self.sharding_degree, "sep": self.sep_degree,
                      "model": self.mp_degree}
        names = [name_map[o] for o in order]
        dims = [degree_map[n] for n in names]

        from ..parallel_env import ParallelEnv

        world = ParallelEnv().world_size
        prod = 1
        for d in dims:
            prod *= d
        if prod != world:
            # auto-fill dp like the reference when degrees don't multiply out
            rest = world // max(prod // max(self.dp_degree, 1), 1)
            if "data" in names and prod != world and world % (
                    prod // self.dp_degree) == 0:
                self.dp_degree = world // (prod // self.dp_degree)
                dims[names.index("data")] = self.dp_degree
        self._topology = CommunicateTopology(names, dims)
        self._hcg = HybridCommunicateGroup(self._topology)

    @property
    def strategy(self):
        return self._strategy

    def get_hybrid_communicate_group(self):
        return self._hcg

    def worker_num(self):
        from ..parallel_env import ParallelEnv

        return ParallelEnv().world_size

    def worker_index(self):
        from ..parallel_env import ParallelEnv

        return ParallelEnv().rank

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..collective import barrier

        barrier()

    def distributed_model(self, model):
        """reference: fleet/model.py:32 — wrap by parallel mode; strategy
        transforms (recompute/amp per DistributedStrategy, reference
        distributed_strategy.py:284) apply first."""
        from .meta_parallel import (PipelineParallel, ShardingParallel,
                                    TensorParallel)
        from .topology import ParallelMode
        from ..parallel import DataParallel

        model = _apply_strategy_to_model(model, self._strategy)
        if self._hcg is None:
            return model
        mode = self._hcg.get_parallel_mode()
        if mode == ParallelMode.PIPELINE_PARALLEL:
            return PipelineParallel(model, self._hcg,
                                    strategy=self._strategy)
        if mode == ParallelMode.TENSOR_PARALLEL:
            return TensorParallel(model, self._hcg, strategy=self._strategy)
        if mode == ParallelMode.SHARDING_PARALLEL:
            return ShardingParallel(model, self._hcg,
                                    strategy=self._strategy)
        if self._hcg.get_data_parallel_world_size() > 1:
            return DataParallel(
                model, group=self._hcg.get_data_parallel_group())
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """reference: fleet/optimizer.py:68. ``strategy.sharding`` with
        ``sharding_configs={"stage": 2}`` wraps the optimizer with the
        GroupSharded stage-2 optimizer over the sharding group (stage 3
        also reshards parameters — use
        ``paddle.distributed.sharding.group_sharded_parallel``, which
        needs the model)."""
        from .hybrid_parallel_optimizer import HybridParallelOptimizer

        strategy = strategy or self._strategy
        if self._hcg is None:
            return optimizer
        if strategy is not None and getattr(strategy, "sharding", False):
            stage = int(strategy.sharding_configs.get("stage", 1))
            if stage == 2 and getattr(strategy, "gradient_merge", False):
                # stage-2 reduces grads via per-backward hooks; with
                # clear_grad deferred mid-merge each micro-step would
                # re-reduce (and re-average) the accumulated grad —
                # silently wrong. Use stage 1 or TrainStep accumulate_steps.
                raise ValueError(
                    "gradient_merge cannot compose with sharding stage 2 "
                    "(hook-based reduction re-reduces accumulated grads); "
                    "use sharding stage 1 or the compiled "
                    "TrainStep(accumulate_steps=k) path")
            if stage == 2 and \
                    self._hcg.get_sharding_parallel_world_size() > 1:
                from .sharding_optimizer import GroupShardedOptimizerStage2

                optimizer = GroupShardedOptimizerStage2(
                    list(optimizer._parameter_list), optimizer,
                    group=self._hcg.get_sharding_parallel_group())
                return HybridParallelOptimizer(optimizer, self._hcg,
                                               strategy)
        return HybridParallelOptimizer(optimizer, self._hcg, strategy)

    # state io passthroughs
    def save(self, *args, **kwargs):
        from ...framework.io_utils import save as _save

        return _save(*args, **kwargs)


def init(role_maker=None, is_collective=True, strategy=None,
         log_level="INFO"):
    global _fleet
    if _fleet is None:
        _fleet = Fleet()
    return _fleet.init(role_maker, is_collective, strategy, log_level)


def _get_fleet() -> Fleet:
    global _fleet
    if _fleet is None:
        _fleet = Fleet()
    return _fleet


def distributed_model(model):
    return _get_fleet().distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _get_fleet().distributed_optimizer(optimizer, strategy)


def worker_num():
    return _get_fleet().worker_num()


def worker_index():
    return _get_fleet().worker_index()


def is_first_worker():
    return _get_fleet().is_first_worker()


def barrier_worker():
    return _get_fleet().barrier_worker()
