"""Meta-parallel model wrappers + pipeline engine (reference:
python/paddle/distributed/fleet/meta_parallel/ — TensorParallel
tensor_parallel.py, PipelineLayer parallel_layers/pp_layers.py:258,
PipelineParallel pipeline_parallel.py:255, 1F1B at :575).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ... import nn
from ... import observability as _obs
from ...core.tensor import Tensor
from .. import collective as dist

__all__ = ["TensorParallel", "ShardingParallel", "SegmentParallel",
           "LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel", "PipelineParallelWithInterleave",
           "PipelineParallelZeroBubble"]


def _broadcast_parameters(model, group, src_rank):
    for p in model.parameters():
        if getattr(p, "is_distributed", False):
            continue
        dist.broadcast(p, src_rank, group=group)


class _MetaParallelBase(nn.Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


class TensorParallel(_MetaParallelBase):
    """Broadcast non-distributed params across mp group (reference:
    meta_parallel/tensor_parallel.py)."""

    def _prepare_for_model(self):
        hcg = self._hcg
        if hcg.get_model_parallel_world_size() > 1:
            _broadcast_parameters(
                self._layers, hcg.get_model_parallel_group(),
                hcg.get_model_parallel_group_src_rank())
        if hcg.get_data_parallel_world_size() > 1:
            _broadcast_parameters(
                self._layers, hcg.get_data_parallel_group(),
                hcg.get_data_parallel_group_src_rank())


class ShardingParallel(_MetaParallelBase):
    def _prepare_for_model(self):
        hcg = self._hcg
        if hcg.get_sharding_parallel_world_size() > 1:
            _broadcast_parameters(
                self._layers, hcg.get_sharding_parallel_group(),
                hcg.get_sharding_parallel_group_src_rank())


class SegmentParallel(_MetaParallelBase):
    """sep wrapper (reference: meta_parallel/segment_parallel.py:26):
    param broadcast across sep; attention-side seq exchange is done by the
    model via the provided all_to_all primitives."""

    def _prepare_for_model(self):
        hcg = self._hcg
        if hcg.get_sep_parallel_world_size() > 1:
            _broadcast_parameters(
                self._layers, hcg.get_sep_parallel_group(),
                hcg._sep_group[0])


class LayerDesc:
    """reference: parallel_layers/pp_layers.py LayerDesc."""

    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    """Stage-partitioned sequential model (reference: pp_layers.py:258).

    Build with a list of LayerDesc (or Layers); segmentation assigns a
    contiguous slice of layers per pp stage (uniform by count, like the
    reference's default seg_method="uniform")."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        from .fleet import get_hybrid_communicate_group

        self._hcg = get_hybrid_communicate_group()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (
            self._hcg.get_pipe_parallel_world_size() if self._hcg else 1)
        self._stage_id = (self._hcg.get_stage_id() if self._hcg else 0)
        self._recompute_interval = recompute_interval
        self._num_virtual = num_virtual_pipeline_stages or 1
        self.descs = list(layers)

        n = len(self.descs)
        total_virtual = self._num_stages * self._num_virtual
        per = [n // total_virtual] * total_virtual
        for i in range(n % total_virtual):
            per[i] += 1
        starts = np.cumsum([0] + per)
        self.segment_parts = starts.tolist()
        # virtual stage vs holds layers [starts[vs], starts[vs+1]); this
        # rank owns virtual stages stage_id + k*num_stages (interleaved
        # assignment, reference pp_layers.py _interleave)
        self._chunks: List[nn.LayerList] = []
        for k in range(self._num_virtual):
            vs = self._stage_id + k * self._num_stages
            built = []
            for i in range(int(starts[vs]), int(starts[vs + 1])):
                d = self.descs[i]
                built.append(d.build_layer() if isinstance(d, LayerDesc)
                             else d)
            self._chunks.append(nn.LayerList(built))
        # flat view for the plain (non-interleaved) path + parameters()
        self.run_function = nn.LayerList(
            [l for c in self._chunks for l in c])

    def get_num_virtual_stages(self):
        return self._num_virtual

    def forward_chunk(self, x, chunk_id: int):
        for layer in self._chunks[chunk_id]:
            x = layer(x)
        return x

    def get_stage_from_index(self, layer_idx):
        total_virtual = self._num_stages * self._num_virtual
        for vs in range(total_virtual):
            if self.segment_parts[vs] <= layer_idx \
                    < self.segment_parts[vs + 1]:
                return vs % self._num_stages
        return self._num_stages - 1

    def forward(self, x):
        if self._num_virtual > 1:
            raise RuntimeError(
                "PipelineLayer with num_virtual_pipeline_stages>1 holds "
                "non-contiguous chunks; drive it with "
                "PipelineParallelWithInterleave (forward_chunk), not the "
                "flat forward")
        for i, layer in enumerate(self.run_function):
            if self._recompute_interval > 0 and \
                    i % self._recompute_interval == 0 and self.training:
                from .recompute import recompute

                x = recompute(layer, x)
            else:
                x = layer(x)
        return x


class PipelineParallel(_MetaParallelBase):
    """1F1B micro-batch schedule over p2p send/recv
    (reference: pipeline_parallel.py:255; forward_backward_pipeline:575 —
    startup/steady/cooldown phases; p2p via SendRecvMeta handshake,
    pp_utils/p2p_communication.py:52)."""

    # solitary-p2p schedules with endpoint-asymmetric per-pair op order
    # (interleaved VPP) set this to route p2p through the backend's
    # buffered transport instead of the paired device programs
    _p2p_buffered = False

    def __init__(self, layers, hcg, strategy=None):
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        if type(self) is PipelineParallel \
                and layers.get_num_virtual_stages() > 1:
            raise ValueError(
                "layers were built with num_virtual_pipeline_stages>1; "
                "use PipelineParallelWithInterleave")
        super().__init__(layers, hcg, strategy)
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.pp_group = hcg.get_pipe_parallel_group()
        self.prev_rank = hcg.get_p2p_prev_rank()
        self.next_rank = hcg.get_p2p_next_rank()
        self.is_first = hcg.is_first_stage()
        self.is_last = hcg.is_last_stage()
        self.global_rank = hcg.get_global_rank()
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        # SendRecvMeta caches keyed by (peer, tag): fwd activations and bwd
        # grads are distinct channels (reference pp_utils SendRecvMeta)
        self._send_meta_known = {}
        self._recv_meta = {}
        # per-process construction counter scopes the store meta keys so a
        # second pipeline over the same group doesn't read the first one's
        # stale channel meta (construction order is SPMD-symmetric, like
        # the schedule itself)
        cls = PipelineParallel
        cls._instances = getattr(cls, "_instances", 0) + 1
        self._meta_nonce = cls._instances

    def _prepare_for_model(self):
        hcg = self._hcg
        if hcg.get_data_parallel_world_size() > 1:
            _broadcast_parameters(
                self._layers, hcg.get_data_parallel_group(),
                hcg.get_data_parallel_group_src_rank())

    # ---------------------------------------------------------------- p2p
    # SendRecvMeta protocol (reference pp_utils/p2p_communication.py:52):
    # shape/dtype are exchanged ONCE per channel through the TCPStore (the
    # control path), then every transfer is a bare fixed-shape tensor
    # send/recv — on the XLA backend that is a cached compiled
    # collective_permute with ZERO store traffic and zero host syncs in
    # steady state (the reference's has_send_meta/has_recv_meta caching;
    # steady-state PP is fixed-shape per SURVEY §3.5). A shape change on an
    # established channel is an error: use a distinct tag per boundary
    # shape (VPP tags carry the virtual-stage id for exactly this reason).
    def _meta_store(self):
        pg = self.pp_group.process_group
        return getattr(pg, "_store", None)

    def _meta_key(self, src, dst, tag):
        return (f"ppmeta/g{self.pp_group.id}/i{self._meta_nonce}/"
                f"{src}->{dst}/{tag}")

    def _ensure_send_meta(self, t: Tensor, peer, tag: str):
        """Publish this channel's (shape, dtype) to the store once; reject
        shape changes on an established channel (fixed-shape channels keep
        steady-state PP on the compiled device path — use a distinct tag
        per boundary shape)."""
        import pickle

        cur = (tuple(t.shape), str(t._data.dtype))
        known = self._send_meta_known.get((peer, tag))
        if known is None:
            store = self._meta_store()
            if store is not None:
                store.set(self._meta_key(self.global_rank, peer, tag),
                          pickle.dumps(cur))
            self._send_meta_known[(peer, tag)] = cur
        elif known != cur:
            raise ValueError(
                f"pipeline p2p channel ({peer}, {tag!r}) was established "
                f"with meta {known} but is now asked to carry {cur}; "
                "fixed-shape channels keep steady-state PP on the "
                "compiled device path — use a distinct tag per boundary "
                "shape")

    def _ensure_recv_meta(self, peer, tag: str):
        """Blocking one-time fetch of the channel meta the sender
        published; returns (shape, dtype)."""
        import pickle

        if (peer, tag) not in self._recv_meta:
            store = self._meta_store()
            if store is None:
                raise RuntimeError("pipeline p2p needs a store-backed "
                                   "process group for the meta handshake")
            # store.get blocks until the sender publishes (one-time)
            self._recv_meta[(peer, tag)] = pickle.loads(
                store.get(self._meta_key(peer, self.global_rank, tag)))
        return self._recv_meta[(peer, tag)]

    def _p2p_use_buffered(self, pg) -> bool:
        """Host store path when the class demands it (VPP's asymmetric op
        order) or ``PADDLE_TPU_PP_TRANSPORT=host`` forces the fallback;
        device collectives otherwise (auto/device on a capable group)."""
        from ..pipeline.transport import transport_mode

        forced_host = transport_mode() == "host"
        return (self._p2p_buffered or forced_host) and \
            hasattr(pg, "send_buffered")

    @staticmethod
    def _p2p_obs(t: Tensor, transport: str) -> None:
        if _obs.enabled():
            arr = t._data
            _obs.registry.counter(
                "pipeline.p2p_bytes", {"transport": transport}).inc(
                    int(arr.size) * arr.dtype.itemsize)
            _obs.registry.counter(
                "pipeline.p2p_messages", {"transport": transport}).inc()

    def _send_tensor(self, t: Tensor, dst, tag: str = "fwd"):
        self._ensure_send_meta(t, dst, tag)
        pg = self.pp_group.process_group
        if self._p2p_use_buffered(pg):
            with _obs.span("pp.send", cat="pipeline",
                           args={"transport": "host", "dst": dst}):
                pg.send_buffered(t, dst)
            self._p2p_obs(t, "host")
        else:
            with _obs.span("pp.send", cat="pipeline",
                           args={"transport": "device", "dst": dst}):
                dist.send(t, dst, group=self.pp_group)
            self._p2p_obs(t, "device")

    def _recv_tensor(self, src, tag: str = "fwd") -> Tensor:
        import jax.numpy as jnp

        shape, dtype = self._ensure_recv_meta(src, tag)
        buf = Tensor(jnp.zeros(shape, dtype=jnp.dtype(dtype)))
        pg = self.pp_group.process_group
        if self._p2p_use_buffered(pg):
            with _obs.span("pp.recv", cat="pipeline",
                           args={"transport": "host", "src": src}):
                pg.recv_buffered(buf, src)
            self._p2p_obs(buf, "host")
        else:
            with _obs.span("pp.recv", cat="pipeline",
                           args={"transport": "device", "src": src}):
                dist.recv(buf, src, group=self.pp_group)
            self._p2p_obs(buf, "device")
        buf.stop_gradient = False
        return buf

    def _sendrecv_tensor(self, t: Tensor, peer, send_tag: str,
                         recv_tag: str) -> Tensor:
        """Combined send+recv with one peer — the
        send_forward_recv_backward / send_backward_recv_forward analog
        (reference pp_utils/p2p_communication.py:573). On the XLA backend
        this is ONE bidirectional compiled program, which keeps the
        per-pair program order identical on both endpoints (solitary
        send+recv in opposite orders would deadlock the device queues).
        Under the forced host transport both directions ride the store
        (order-insensitive), so a sequential pair is safe there."""
        import jax.numpy as jnp

        self._ensure_send_meta(t, peer, send_tag)
        shape, dtype = self._ensure_recv_meta(peer, recv_tag)
        buf = Tensor(jnp.zeros(shape, dtype=jnp.dtype(dtype)))
        pg = self.pp_group.process_group
        if self._p2p_use_buffered(pg):
            with _obs.span("pp.send", cat="pipeline",
                           args={"transport": "host", "dst": peer}):
                pg.send_buffered(t, peer)
            with _obs.span("pp.recv", cat="pipeline",
                           args={"transport": "host", "src": peer}):
                pg.recv_buffered(buf, peer)
            self._p2p_obs(t, "host")
            self._p2p_obs(buf, "host")
        else:
            pg.sendrecv(t, buf, peer)
            self._p2p_obs(t, "device")
            self._p2p_obs(buf, "device")
        buf.stop_gradient = False
        return buf

    # ---------------------------------------------------------- schedule
    def _compute_fwd(self, i, x, micro_inputs, losses, scaler, num_micro):
        """Forward compute for one micro-batch (no communication)."""
        out = self._layers.forward(x)
        if self.is_last:
            loss_fn = self._layers._loss_fn
            if loss_fn is not None and micro_inputs:
                out = loss_fn(out, micro_inputs[i][1])
            if scaler is not None:
                out = scaler.scale(out)
            out = out / num_micro
            losses.append(out)
        return out

    def _first_input(self, i, micro_inputs):
        return micro_inputs[i][0] if micro_inputs else None

    def _input_grad(self, x):
        """Grad to ship upstream; zeros keep the p2p pairing intact when a
        stage input happens not to receive a gradient."""
        if self.is_first or x is None:
            return None
        if x.grad is None:
            import jax.numpy as jnp

            return Tensor(jnp.zeros_like(x._data))
        return x.grad

    def _sum_losses(self, losses):
        if self.is_last and losses:
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            return total.detach()
        return None

    def _run_1f1b(self, micro_inputs, fwd, bwd, post_slot=None):
        """Shared warmup/steady/cooldown comm driver for the 1F1B-family
        schedules (reference: pipeline_parallel.py:575, steady loop :649).

        ``fwd(i, x) -> out`` and ``bwd(i, grad) -> upstream grad|None``
        are compute-only callbacks; ``post_slot(n_bwd_done)`` is an
        optional per-steady-slot hook (the ZB deferred-W slot).

        Warmup/cooldown use solitary send/recv; the steady phase uses the
        COMBINED send_forward_recv_backward / send_backward_recv_forward
        ops (reference pp_utils p2p batched isend/irecv) — on the XLA
        backend each combined op is one bidirectional compiled program, so
        the per-pair program queues pair up in the same order on both
        endpoints (solitary ops in 1F1B's naturally opposite orders would
        deadlock the device queues)."""
        num_micro = self.accumulate_steps
        num_warmup = min(self.num_stages - self.stage_id - 1, num_micro)
        num_steady = num_micro - num_warmup

        fwd_i = bwd_i = 0
        for _ in range(num_warmup):
            x = self._first_input(fwd_i, micro_inputs) if self.is_first \
                else self._recv_tensor(self.prev_rank, tag="fwd")
            out = fwd(fwd_i, x)
            if not self.is_last:
                self._send_tensor(out.detach(), self.next_rank, tag="fwd")
            fwd_i += 1

        x = None
        if num_steady > 0:
            x = self._first_input(fwd_i, micro_inputs) if self.is_first \
                else self._recv_tensor(self.prev_rank, tag="fwd")
        for k in range(num_steady):
            out = fwd(fwd_i, x)
            fwd_i += 1
            grad = None if self.is_last else self._sendrecv_tensor(
                out.detach(), self.next_rank, send_tag="fwd",
                recv_tag="bwd")
            gx = bwd(bwd_i, grad)
            bwd_i += 1
            last_iter = k == num_steady - 1
            if self.is_first:
                x = None if last_iter \
                    else self._first_input(fwd_i, micro_inputs)
            elif last_iter:
                self._send_tensor(gx, self.prev_rank, tag="bwd")
            else:
                x = self._sendrecv_tensor(gx, self.prev_rank,
                                          send_tag="bwd", recv_tag="fwd")
            if post_slot is not None:
                post_slot(bwd_i)
        while bwd_i < num_micro:
            grad = None if self.is_last else \
                self._recv_tensor(self.next_rank, tag="bwd")
            gx = bwd(bwd_i, grad)
            bwd_i += 1
            if gx is not None:
                self._send_tensor(gx, self.prev_rank, tag="bwd")

    def forward_backward_pipeline(self, data, scaler=None):
        """1F1B over the shared comm driver."""
        num_micro = self.accumulate_steps
        micro_inputs = self._split_micro(data, num_micro)
        input_buffers: List[Optional[Tensor]] = []
        output_buffers: List[Optional[Tensor]] = []
        losses = []

        def fwd(i, x):
            out = self._compute_fwd(i, x, micro_inputs, losses, scaler,
                                    num_micro)
            input_buffers.append(x)
            output_buffers.append(out)
            return out

        def bwd(i, grad):
            out = output_buffers[i]
            if self.is_last:
                out.backward()
            else:
                out.backward(grad)
            output_buffers[i] = None
            gx = self._input_grad(input_buffers[i])
            input_buffers[i] = None  # cap live activations to the window
            return gx

        self._run_1f1b(micro_inputs, fwd, bwd)
        return self._sum_losses(losses)

    def _split_micro(self, data, num_micro):
        if data is None:
            return []
        from ...ops.manipulation import split as top_split

        if isinstance(data, (tuple, list)):
            xs = top_split(data[0], num_micro, axis=0) \
                if data[0] is not None else [None] * num_micro
            ys = top_split(data[1], num_micro, axis=0) \
                if len(data) > 1 and data[1] is not None \
                else [None] * num_micro
            return list(zip(xs, ys))
        xs = top_split(data, num_micro, axis=0)
        return [(x, None) for x in xs]

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference: pipeline_parallel.py:820."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        # dp gradient sync
        hcg = self._hcg
        if hcg.get_data_parallel_world_size() > 1:
            from .hybrid_parallel_util import fused_allreduce_gradients

            fused_allreduce_gradients(
                list(self._layers.parameters()),
                hcg)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from ...core.autograd import no_grad

        with no_grad():
            return self.forward_backward_pipeline(data)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved 1F1B (virtual pipeline / VPP, reference:
    pipeline_parallel.py:1174 PipelineParallelWithInterleave).

    Each rank holds ``v = num_virtual_pipeline_stages`` model chunks; virtual
    stage ``vs = chunk*p + stage``. Forward activations flow rank r -> r+1
    (wrapping p-1 -> 0 between chunks); grads flow the reverse ring. The
    Megatron iteration order is identical on every rank, and the CPU/XLA
    ProcessGroup's buffered FIFO p2p makes the schedule deadlock-free.

    For the zero-bubble B/W-split schedule see PipelineParallelZeroBubble.
    """

    # VPP's solitary op order is endpoint-asymmetric; see _p2p_buffered
    _p2p_buffered = True

    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        self.num_chunks = layers.get_num_virtual_stages()
        if self.accumulate_steps % self.num_stages != 0:
            raise ValueError(
                "interleaved schedule needs accumulate_steps divisible by "
                f"pp degree ({self.accumulate_steps} % {self.num_stages})")

    # ring peers (wrapping, unlike plain PP)
    def _ring_next(self):
        ranks = self.pp_group.ranks
        return ranks[(self.stage_id + 1) % self.num_stages]

    def _ring_prev(self):
        ranks = self.pp_group.ranks
        return ranks[(self.stage_id - 1) % self.num_stages]

    def _virt(self, k):
        """iteration index -> (chunk_id, microbatch_id); Megatron order."""
        p, v = self.num_stages, self.num_chunks
        chunk = (k // p) % v
        micro = (k // (p * v)) * p + k % p
        return chunk, micro

    def forward_backward_pipeline(self, data, scaler=None):
        p, v = self.num_stages, self.num_chunks
        num_micro = self.accumulate_steps
        total = num_micro * v
        micro_inputs = self._split_micro(data, num_micro)
        # buffers[chunk][micro] = (input, output)
        inputs = [[None] * num_micro for _ in range(v)]
        outputs = [[None] * num_micro for _ in range(v)]
        losses = []

        def is_first_vs(chunk):
            return chunk == 0 and self.stage_id == 0

        def is_last_vs(chunk):
            return chunk == v - 1 and self.stage_id == p - 1

        def fwd_step(k):
            chunk, micro = self._virt(k)
            vs = chunk * p + self.stage_id  # virtual stage id
            if is_first_vs(chunk):
                x = micro_inputs[micro][0] if micro_inputs else None
            else:
                # channel = the virtual edge (vs-1 -> vs); per-edge tags
                # keep every channel fixed-shape even when chunk
                # boundaries differ (device-path p2p requires it)
                x = self._recv_tensor(self._ring_prev(), tag=f"fwd{vs - 1}")
            out = self._layers.forward_chunk(x, chunk)
            if is_last_vs(chunk):
                loss_fn = self._layers._loss_fn
                if loss_fn is not None and micro_inputs:
                    out = loss_fn(out, micro_inputs[micro][1])
                if scaler is not None:
                    out = scaler.scale(out)
                out = out / num_micro
                losses.append(out)
            else:
                self._send_tensor(out.detach(), self._ring_next(),
                                  tag=f"fwd{vs}")
            inputs[chunk][micro] = x
            outputs[chunk][micro] = out

        def bwd_step(k):
            # backward visits virtual stages in reverse chunk order
            chunk, micro = self._virt(k)
            chunk = v - 1 - chunk
            vs = chunk * p + self.stage_id
            out = outputs[chunk][micro]
            if is_last_vs(chunk):
                out.backward()
            else:
                grad = self._recv_tensor(self._ring_next(),
                                         tag=f"bwd{vs + 1}")
                out.backward(grad)
            x = inputs[chunk][micro]
            if not is_first_vs(chunk) and x is not None \
                    and x.grad is not None:
                self._send_tensor(x.grad, self._ring_prev(), tag=f"bwd{vs}")

        warmup = min((p - self.stage_id - 1) * 2 + (v - 1) * p, total)
        fwd_k = bwd_k = 0
        for _ in range(warmup):
            fwd_step(fwd_k)
            fwd_k += 1
        for _ in range(total - warmup):
            fwd_step(fwd_k)
            fwd_k += 1
            bwd_step(bwd_k)
            bwd_k += 1
        while bwd_k < total:
            bwd_step(bwd_k)
            bwd_k += 1

        if losses:
            totl = losses[0]
            for l in losses[1:]:
                totl = totl + l
            return totl.detach()
        return None


class PipelineParallelZeroBubble(PipelineParallel):
    """Zero-bubble 1F1B (ZB-H1, reference:
    distributed/passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62).

    Backward is split per micro-batch into B (input-gradient only — the
    part downstream stages wait on, sent upstream immediately) and W
    (weight gradients — no inter-stage dependency, deferred into the
    cooldown bubble). The eager tape realizes the split with two targeted
    ``grad()`` walks over a retained graph: B = d loss/d stage-input,
    W = d loss/d stage-params accumulated into ``.grad``. On TPU the
    compiled TrainStep path subsumes the bubble win; this schedule provides
    the reference capability for the host-driven pipeline engine.
    """

    def forward_backward_pipeline(self, data, scaler=None):
        from ...core.autograd import grad as _tape_grad

        num_micro = self.accumulate_steps
        micro_inputs = self._split_micro(data, num_micro)
        inputs: List[Optional[Tensor]] = []
        outputs: List[Optional[Tensor]] = []
        pending_w: List[Optional[list]] = []   # per-micro stashed w-grads
        losses = []
        params = [p for p in self._layers.parameters()
                  if not p.stop_gradient]

        def fwd(i, x):
            out = self._compute_fwd(i, x, micro_inputs, losses, scaler,
                                    num_micro)
            inputs.append(x)
            outputs.append(out)
            return out

        def b_walk(i, g_out):
            """One backward walk; returns the INPUT grad (the inter-stage
            dependency, shipped upstream by the caller); weight grads are
            stashed for the deferred W slot (accumulation + hooks)."""
            out = outputs[i]
            x = inputs[i]
            targets = ([x] if not self.is_first and x is not None
                       else []) + params
            grads = _tape_grad([out], targets, grad_outputs=g_out,
                               retain_graph=False, allow_unused=True)
            gx = None
            if not self.is_first and x is not None:
                gx, grads = grads[0], grads[1:]
                if gx is None:
                    import jax.numpy as jnp

                    gx = Tensor(jnp.zeros_like(x._data))
            pending_w.append(list(grads))
            outputs[i] = None  # graph freed by the walk
            inputs[i] = None   # cap live activations to the window
            return gx

        def w_step(i):
            """Deferred weight-grad accumulation for micro i; fires grad
            hooks exactly like core backward() so DP/sharding/SP hook-based
            sync composes (autograd.py backward())."""
            grads = pending_w[i]
            for p, g in zip(params, grads):
                if g is None:
                    continue
                if p._grad is None:
                    p._grad = g if isinstance(g, Tensor) else Tensor(g)
                else:
                    p._grad = Tensor(p._grad._data + g._data)
                for hook in p._grad_hooks:
                    res = hook(p._grad)
                    if res is not None:
                        p._grad = res
            pending_w[i] = None

        w_state = {"w": 0}

        def post_slot(b_done):
            # ZB-H1: one deferred W per steady slot keeps memory flat
            if b_done - w_state["w"] > self.num_stages - self.stage_id:
                w_step(w_state["w"])
                w_state["w"] += 1

        self._run_1f1b(micro_inputs, fwd, b_walk, post_slot=post_slot)
        while w_state["w"] < num_micro:  # W fills the cooldown bubble
            w_step(w_state["w"])
            w_state["w"] += 1

        return self._sum_losses(losses)
