"""Gradient sync helpers (reference:
python/paddle/distributed/fleet/utils/hybrid_parallel_util.py —
fused_allreduce_gradients:249, param broadcast :287)."""
from __future__ import annotations

import os

import numpy as np

from ...core.tensor import Tensor
from .. import collective as dist

__all__ = ["fused_allreduce_gradients", "broadcast_dp_parameters",
           "broadcast_mp_parameters", "broadcast_sharding_parameters",
           "fused_allreduce_gradients_with_group"]

_FUSE_BYTES = 128 * 1024 * 1024  # bucket size for fused all-reduce


def fused_allreduce_gradients_with_group(params, group, scale=None,
                                         bucket_bytes=None):
    """Bucketed gradient all-reduce: flatten grads into contiguous buffers
    per dtype up to bucket_bytes, one all-reduce per bucket (the eager
    reducer algorithm, reference: collective/reducer.cc FusedAllReduce).

    The default bucket size follows ``PADDLE_TPU_PP_BUCKET_MB`` when set
    (the pipeline comm/compute-overlap knob — smaller buckets let each
    all-reduce dispatch overlap the remaining host-side work) and falls
    back to the classic 128 MB fuse budget otherwise.
    """
    import jax.numpy as jnp

    from ... import observability as _obs
    from ..pipeline.transport import overlap_bucket_bytes

    from ...config import knobs

    if bucket_bytes is None:
        bucket_bytes = overlap_bucket_bytes() \
            if knobs.is_set("PADDLE_TPU_PP_BUCKET_MB") else _FUSE_BYTES
    nranks = group.nranks if group is not None else 1
    if nranks <= 1:
        return
    grads = [(p, p._grad) for p in params
             if p._grad is not None and not getattr(p, "is_distributed",
                                                    False)]
    buckets = {}
    for p, g in grads:
        key = str(g._data.dtype)
        buckets.setdefault(key, []).append((p, g))
    n_buckets = 0
    for key, items in buckets.items():
        cur, cur_bytes = [], 0
        flush_list = []
        for p, g in items:
            nbytes = g.size * g.dtype.itemsize
            cur.append((p, g))
            cur_bytes += nbytes
            if cur_bytes >= bucket_bytes:
                flush_list.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            flush_list.append(cur)
        for bucket in flush_list:
            nbytes = sum(b[1].size * b[1].dtype.itemsize for b in bucket)
            with _obs.span("pp.bucket_reduce", cat="pipeline",
                           args={"bucket": n_buckets, "bytes": nbytes}):
                flat = jnp.concatenate(
                    [b[1]._data.reshape(-1) for b in bucket])
                t = Tensor(flat)
                dist.all_reduce(t, group=group)
                inv = 1.0 / nranks
                out = t._data * inv
                off = 0
                for p, g in bucket:
                    n = g.size
                    g._data = out[off:off + n].reshape(
                        g._data.shape).astype(g._data.dtype)
                    off += n
            n_buckets += 1
    if _obs.enabled():
        _obs.registry.gauge("pipeline.overlap_buckets").set(n_buckets)


def fused_allreduce_gradients(parameter_list, hcg):
    """reference: hybrid_parallel_util.py:249 — all-reduce over dp (or fused
    dp×sep) group."""
    group = None
    if hcg is not None:
        if hcg.get_sep_parallel_world_size() > 1:
            group = hcg.get_dp_sep_parallel_group()
        elif hcg.get_data_parallel_world_size() > 1:
            group = hcg.get_data_parallel_group()
    if group is None:
        return
    fused_allreduce_gradients_with_group(parameter_list, group)


def broadcast_dp_parameters(model, hcg):
    from .meta_parallel import _broadcast_parameters

    _broadcast_parameters(model, hcg.get_data_parallel_group(),
                          hcg.get_data_parallel_group_src_rank())


def broadcast_mp_parameters(model, hcg):
    from .meta_parallel import _broadcast_parameters

    _broadcast_parameters(model, hcg.get_model_parallel_group(),
                          hcg.get_model_parallel_group_src_rank())


def broadcast_sharding_parameters(model, hcg):
    from .meta_parallel import _broadcast_parameters

    _broadcast_parameters(model, hcg.get_sharding_parallel_group(),
                          hcg.get_sharding_parallel_group_src_rank())
