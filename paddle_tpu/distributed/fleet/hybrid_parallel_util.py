"""Gradient sync helpers (reference:
python/paddle/distributed/fleet/utils/hybrid_parallel_util.py —
fused_allreduce_gradients:249, param broadcast :287)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import collective as dist

__all__ = ["fused_allreduce_gradients", "broadcast_dp_parameters",
           "broadcast_mp_parameters", "broadcast_sharding_parameters",
           "fused_allreduce_gradients_with_group"]

_FUSE_BYTES = 128 * 1024 * 1024  # bucket size for fused all-reduce


def fused_allreduce_gradients_with_group(params, group, scale=None,
                                         bucket_bytes=_FUSE_BYTES):
    """Bucketed gradient all-reduce: flatten grads into contiguous buffers
    per dtype up to bucket_bytes, one all-reduce per bucket (the eager
    reducer algorithm, reference: collective/reducer.cc FusedAllReduce)."""
    import jax.numpy as jnp

    nranks = group.nranks if group is not None else 1
    if nranks <= 1:
        return
    grads = [(p, p._grad) for p in params
             if p._grad is not None and not getattr(p, "is_distributed",
                                                    False)]
    buckets = {}
    for p, g in grads:
        key = str(g._data.dtype)
        buckets.setdefault(key, []).append((p, g))
    for key, items in buckets.items():
        cur, cur_bytes = [], 0
        flush_list = []
        for p, g in items:
            nbytes = g.size * g.dtype.itemsize
            cur.append((p, g))
            cur_bytes += nbytes
            if cur_bytes >= bucket_bytes:
                flush_list.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            flush_list.append(cur)
        for bucket in flush_list:
            flat = jnp.concatenate(
                [b[1]._data.reshape(-1) for b in bucket])
            t = Tensor(flat)
            dist.all_reduce(t, group=group)
            inv = 1.0 / nranks
            out = t._data * inv
            off = 0
            for p, g in bucket:
                n = g.size
                g._data = out[off:off + n].reshape(g._data.shape).astype(
                    g._data.dtype)
                off += n


def fused_allreduce_gradients(parameter_list, hcg):
    """reference: hybrid_parallel_util.py:249 — all-reduce over dp (or fused
    dp×sep) group."""
    group = None
    if hcg is not None:
        if hcg.get_sep_parallel_world_size() > 1:
            group = hcg.get_dp_sep_parallel_group()
        elif hcg.get_data_parallel_world_size() > 1:
            group = hcg.get_data_parallel_group()
    if group is None:
        return
    fused_allreduce_gradients_with_group(parameter_list, group)


def broadcast_dp_parameters(model, hcg):
    from .meta_parallel import _broadcast_parameters

    _broadcast_parameters(model, hcg.get_data_parallel_group(),
                          hcg.get_data_parallel_group_src_rank())


def broadcast_mp_parameters(model, hcg):
    from .meta_parallel import _broadcast_parameters

    _broadcast_parameters(model, hcg.get_model_parallel_group(),
                          hcg.get_model_parallel_group_src_rank())


def broadcast_sharding_parameters(model, hcg):
    from .meta_parallel import _broadcast_parameters

    _broadcast_parameters(model, hcg.get_sharding_parallel_group(),
                          hcg.get_sharding_parallel_group_src_rank())
