"""Fleet role makers + util + data generators (reference:
python/paddle/distributed/fleet/base/role_maker.py
PaddleCloudRoleMaker/UserDefinedRoleMaker, base/util_factory.py UtilBase,
data_generator/data_generator.py MultiSlot*DataGenerator).
"""
from __future__ import annotations

import os
from typing import List

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "UtilBase", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class Role:
    """reference: role_maker.py Role enum."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Role from the launcher environment (reference: role_maker.py
    PaddleCloudRoleMaker — collective mode reads PADDLE_TRAINER_*)."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._endpoints = eps.split(",") if eps else ["127.0.0.1:0"]
        self._role = Role.WORKER

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._rank == 0

    def worker_index(self):
        return self._rank

    def role_id(self):
        return self._rank

    def worker_num(self):
        return self._size

    def server_num(self):
        return 0

    def get_trainer_endpoints(self):
        return list(self._endpoints)

    def get_pserver_endpoints(self):
        return []

    def _generate_role(self):
        pass


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit role assignment (reference: role_maker.py
    UserDefinedRoleMaker)."""

    def __init__(self, is_collective=False, init_gloo=False, *,
                 current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, **kwargs):
        super().__init__(is_collective=is_collective)
        self._rank = current_id
        self._role = role
        self._size = worker_num
        self._server_endpoints = list(server_endpoints or [])

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)


class UtilBase:
    """Cross-rank small-object utilities (reference: util_factory.py
    UtilBase) over the collective API when a group is initialized."""

    def _initialized(self):
        from ..parallel_env import is_initialized

        return is_initialized()

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        if not self._initialized():
            return input
        from .. import collective as C
        import paddle_tpu as pt

        t = pt.to_tensor(np.asarray(input))
        op = {"sum": C.ReduceOp.SUM, "max": C.ReduceOp.MAX,
              "min": C.ReduceOp.MIN}[mode]
        C.all_reduce(t, op=op)
        return t.numpy()

    def barrier(self, comm_world="worker"):
        if self._initialized():
            from .. import collective as C

            C.barrier()

    def all_gather(self, input, comm_world="worker"):
        if not self._initialized():
            return [input]
        from .. import collective as C

        out = []
        C.all_gather_object(out, input)
        return out

    def get_file_shard(self, files: List[str]):
        """Split a file list over workers (reference UtilBase
        get_file_shard)."""
        from ..parallel_env import get_rank, get_world_size

        rank, size = (get_rank(), get_world_size()) \
            if self._initialized() else (0, 1)
        n = len(files)
        base, extra = divmod(n, size)
        start = rank * base + min(rank, extra)
        count = base + (1 if rank < extra else 0)
        return files[start:start + count]

    def print_on_rank(self, message, rank_id=0):
        from ..parallel_env import get_rank

        if not self._initialized() or get_rank() == rank_id:
            print(message)


class _DataGeneratorBase:
    """Line -> slots generator protocol (reference:
    fleet/data_generator/data_generator.py): subclasses implement
    generate_sample(line) returning an iterator of
    [(slot_name, values), ...]; run_from_stdin/files format them for the
    dataset readers."""

    def __init__(self):
        self._line_limit = None

    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(line) returning an iterator of "
            "[(name, values), ...]")

    def set_batch(self, batch_size):
        self._batch = batch_size

    def _format(self, record):
        raise NotImplementedError

    def run_from_stdin(self):
        import sys

        for line in sys.stdin:
            for rec in self.generate_sample(line)():
                sys.stdout.write(self._format(rec))

    def run_from_files(self, filelist, output):
        with open(output, "w") as out:
            for fname in filelist:
                with open(fname) as f:
                    for line in f:
                        for rec in self.generate_sample(line)():
                            out.write(self._format(rec))


class MultiSlotDataGenerator(_DataGeneratorBase):
    """Numeric slots: `name:n v1..vn` per slot (reference
    MultiSlotDataGenerator._gen_str)."""

    def _format(self, record):
        parts = []
        for name, values in record:
            vals = list(values)
            parts.append(f"{len(vals)} " + " ".join(str(v) for v in vals))
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(_DataGeneratorBase):
    """String slots (reference MultiSlotStringDataGenerator)."""

    def _format(self, record):
        parts = []
        for name, values in record:
            vals = [str(v) for v in values]
            parts.append(f"{len(vals)} " + " ".join(vals))
        return " ".join(parts) + "\n"
