"""ZeRO sharding (reference:
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:54
stage-1; fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py:53 and
group_sharded_stage3.py:85 stages 2/3).

Stage 1 (optimizer-state sharding): each sharding rank owns a subset of
params; grads are reduced (reduce or reduce-scatter) to the owner, only the
owner runs the update, updated shards are broadcast back
(reduce_gradients:320, _sharding_sync_parameters:378).

Stage 2 adds gradient sharding (grads released on non-owners after reduce).
Stage 3 adds parameter sharding between steps (params gathered on use).
All three run on the public collective API only — so they work unmodified
over ProcessGroupCPU (tests) and ProcessGroupXLA (TPU pods), the property
SURVEY §2.2 calls out.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from ...core.tensor import Tensor
from .. import collective as dist

__all__ = ["DygraphShardingOptimizer", "GroupShardedOptimizerStage2",
           "GroupShardedStage2", "GroupShardedStage3"]


def _partition_params(params, nranks):
    """Greedy size-balanced partition (reference:
    dygraph_sharding_optimizer.py _partition_parameters)."""
    buckets: List[List] = [[] for _ in range(nranks)]
    sizes = [0] * nranks
    for p in sorted(params, key=lambda p: -p.size):
        i = int(np.argmin(sizes))
        buckets[i].append(p)
        sizes[i] += p.size
    return buckets


class DygraphShardingOptimizer:
    """Stage-1 (reference: dygraph_sharding_optimizer.py:54)."""

    def __init__(self, optimizer, hcg=None, group=None):
        self._inner_opt = optimizer
        if group is None:
            from .fleet import get_hybrid_communicate_group

            hcg = hcg or get_hybrid_communicate_group()
            group = hcg.get_sharding_parallel_group()
        self._group = group
        self._nranks = group.nranks
        self._rank = group.rank
        all_params = list(optimizer._parameter_list)
        self._all_params = all_params
        self._buckets = _partition_params(all_params, self._nranks)
        self._local_params = self._buckets[self._rank]
        self._param_owner: Dict[int, int] = {}
        for r, bucket in enumerate(self._buckets):
            for p in bucket:
                self._param_owner[id(p)] = r
        # the inner optimizer only updates the local shard
        optimizer._parameter_list = self._local_params

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def reduce_gradients(self):
        """reference: :320 — reduce each grad to its owner, average."""
        for r, bucket in enumerate(self._buckets):
            for p in bucket:
                if p._grad is None:
                    continue
                dist.reduce(p._grad, self._group.ranks[r], group=self._group)
                if r == self._rank:
                    p._grad._data = p._grad._data / self._nranks
                else:
                    p._grad = None  # free non-owned grads

    def _sharding_sync_parameters(self):
        """reference: :378 — broadcast updated shards from owners."""
        for r, bucket in enumerate(self._buckets):
            for p in bucket:
                dist.broadcast(p, self._group.ranks[r], group=self._group)

    def step(self):
        self.reduce_gradients()
        self._inner_opt.step()
        self._sharding_sync_parameters()

    def clear_grad(self, set_to_zero=False):
        for p in self._all_params:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """Stage-2 (reference: group_sharded_optimizer_stage2.py:53): grads
    reduce-scattered to owners as they become ready via grad hooks."""

    def __init__(self, params, optim, group=None, offload=False,
                 device="tpu", **kw):
        optim._parameter_list = list(params)
        super().__init__(optim, group=group)
        self._offload = offload
        self._register_hooks()

    def _register_hooks(self):
        for p in self._all_params:
            owner = self._param_owner[id(p)]

            def hook(grad, p=p, owner=owner):
                dist.reduce(grad, self._group.ranks[owner],
                            group=self._group)
                if owner == self._rank:
                    grad._data = grad._data / self._nranks
                    return grad
                return Tensor(np.zeros((1,), np.float32))  # freed

            p.register_hook(hook)

    def reduce_gradients(self):
        # grads already reduced by hooks
        for r, bucket in enumerate(self._buckets):
            if r == self._rank:
                continue
            for p in bucket:
                p._grad = None


class GroupShardedStage2:
    """Model wrapper for stage-2 (reference: group_sharded_stage2.py)."""

    def __init__(self, layer, sharding_optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", **kw):
        self._layer = layer
        self._sharding_optimizers = [sharding_optimizer] if not isinstance(
            sharding_optimizer, list) else sharding_optimizer

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._layer, item)


class GroupShardedStage3:
    """Stage-3: parameter sharding (reference: group_sharded_stage3.py:85).

    Params are split 1/N per rank between steps (_segment_rank_params:422);
    forward pre-hooks all-gather the full param, post-hooks release
    (:557)."""

    def __init__(self, layer, optimizer, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 15, pertrain_sync_models=True,
                 offload=False, sync_comm=False, **kw):
        import jax.numpy as jnp

        if getattr(optimizer, "_stage3_wrapped_by", None) is not None:
            # must precede any param mutation: raising after _shard_all would
            # leave the layer destructively sharded with no recovery path
            raise RuntimeError(
                "optimizer.step is already routed through a GroupShardedStage3 "
                "wrapper; sharing one optimizer across stage-3 wrappers would "
                "chain duplicate grad reduce + reshard passes. Use a separate "
                "optimizer per wrapped layer.")
        self._layer = layer
        self._optimizer = optimizer
        if group is None:
            from .fleet import get_hybrid_communicate_group

            hcg = get_hybrid_communicate_group()
            group = hcg.get_sharding_parallel_group() if hcg else None
        self._group = group
        self._nranks = group.nranks if group else 1
        self._rank = group.rank if group else 0
        self._params = [p for p in layer.parameters() if not p.stop_gradient]
        if pertrain_sync_models and self._nranks > 1:
            for p in self._params:
                dist.broadcast(p, self._group.ranks[0], group=self._group)
        self._full_shapes = {id(p): tuple(p.shape) for p in self._params}
        # stage-3 shard state runs entirely on the training thread:
        # optimizer.step is REBOUND to self.step (same-thread routing,
        # not a callback escaping to another thread), and forward hooks
        # fire synchronously inside the caller's forward
        self._sharded_ids: set = set()  # ptlint: disable=thread-escape
        self._sharded = False  # ptlint: disable=thread-escape
        if self._nranks > 1:
            self._shard_all()
            self._register_hooks()
        # reference _redefine_opt_step (group_sharded_stage3.py): the user
        # keeps calling optimizer.step(); route it through stage-3's
        # reduce+update+reshard step
        self._opt_step_orig = optimizer.step
        optimizer.step = self.step
        optimizer._stage3_wrapped_by = self

    # -- param shard/unshard ------------------------------------------------
    def _shard_param(self, p):
        import jax.numpy as jnp

        # explicit shard-state tracking: shape inference misclassifies
        # 1-element params whose shard shape equals the full shape
        if id(p) in self._sharded_ids:
            return  # already a shard (layer skipped this forward)
        flat = p._data.reshape(-1)
        n = flat.shape[0]
        per = -(-n // self._nranks)
        pad = per * self._nranks - n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        p._data = flat[self._rank * per:(self._rank + 1) * per]
        self._sharded_ids.add(id(p))

    def _unshard_param(self, p):
        import jax.numpy as jnp

        if id(p) not in self._sharded_ids:
            return  # pre-hook already materialized it this step
        outs: List[Tensor] = []
        dist.all_gather(outs, Tensor(p._data), group=self._group)
        full = jnp.concatenate([o._data for o in outs])
        shape = self._full_shapes[id(p)]
        n = int(np.prod(shape))
        p._data = full[:n].reshape(shape)
        self._sharded_ids.discard(id(p))

    def _shard_all(self):
        for p in self._params:
            self._shard_param(p)
        self._sharded = True

    def _unshard_all(self):
        for p in self._params:
            self._unshard_param(p)
        self._sharded = False

    def _register_hooks(self):
        layers_with_params = [l for l in self._layer.sublayers(
            include_self=True) if l._parameters]

        def pre_hook(layer, inputs):
            for p in layer._parameters.values():
                if p is not None and id(p) in self._sharded_ids:
                    self._unshard_param(p)
            return None

        for l in layers_with_params:
            l.register_forward_pre_hook(pre_hook)

    def __call__(self, *args, **kwargs):
        out = self._layer(*args, **kwargs)
        return out

    def __getattr__(self, item):
        return getattr(self._layer, item)

    def step(self):
        """Reduce grads to shards, update local shard, keep params sharded."""
        if self._nranks <= 1:
            self._opt_step_orig()
            return
        # params are currently full (post-forward/backward); reduce grads
        for p in self._params:
            if p._grad is None:
                continue
            dist.all_reduce(p._grad, group=self._group)
            p._grad._data = p._grad._data / self._nranks
        self._opt_step_orig()
        self._optimizer.clear_grad()
        self._shard_all()

    def state_dict(self, *a, **k):
        was_sharded = self._sharded
        if was_sharded:
            self._unshard_all()
        # snapshot values: the layer's state_dict holds live Parameter
        # references whose payload is about to be re-sharded
        sd = {key: Tensor(v._data) if isinstance(v, Tensor) else v
              for key, v in self._layer.state_dict(*a, **k).items()}
        if was_sharded:
            self._shard_all()
        return sd
