"""Elastic training manager-lite (reference:
python/paddle/distributed/fleet/elastic/manager.py:125 ElasticManager —
etcd node registry + heartbeat lease :254, fault watch :457).

TPU-native: the registry lives in the job's TCPStore (no etcd dependency);
each node heartbeats a lease key, the master watches for missing beats and
invokes the fault callback (restart is the launcher's job, as in the
reference --max_restart policy).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ElasticManager"]


class ElasticManager:
    ELASTIC_TIMEOUT = 10.0

    def __init__(self, store, node_id: str, num_nodes: int,
                 heartbeat_interval: float = 2.0,
                 timeout: Optional[float] = None,
                 on_fault: Optional[Callable[[List[str]], None]] = None):
        self._store = store
        self.node_id = node_id
        self.num_nodes = num_nodes
        self.interval = heartbeat_interval
        self.timeout = timeout or self.ELASTIC_TIMEOUT
        self.on_fault = on_fault
        self._stop = False
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------ lease
    def register(self):
        """Join the registry and start the heartbeat lease thread
        (reference: manager.py:254)."""
        self._store.set(f"elastic/nodes/{self.node_id}", b"1")
        t = threading.Thread(target=self._beat_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _beat_loop(self):
        while not self._stop:
            self._store.set(f"elastic/beat/{self.node_id}",
                            str(time.time()).encode())
            time.sleep(self.interval)

    # ------------------------------------------------------------ watch
    def watch(self, node_ids: List[str]):
        """Master-side fault watch (reference: _update_fault_tolerance
        manager.py:457)."""
        t = threading.Thread(target=self._watch_loop, args=(node_ids,),
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _watch_loop(self, node_ids):
        watch_start = time.time()
        reported = set()
        last_beats: Dict[str, float] = {}
        while not self._stop:
            time.sleep(self.interval)
            now = time.time()
            dead = []
            for nid in node_ids:
                try:
                    # check() first — get() would block on a missing key
                    if self._store.check(f"elastic/beat/{nid}"):
                        raw = self._store.get(f"elastic/beat/{nid}")
                        last = float(raw.decode())
                        last_beats[nid] = last
                    else:
                        # never heartbeat at all: dead once the grace
                        # period from watch start has passed
                        last = last_beats.get(nid, watch_start)
                except Exception:
                    # transient store error: keep the last-known beat so a
                    # healthy node is not declared dead by a blip
                    last = last_beats.get(nid, now)
                if now - last > self.timeout:
                    dead.append(nid)
                elif nid in reported:
                    reported.discard(nid)  # recovered: re-arm reporting
            fresh = [n for n in dead if n not in reported]
            reported.update(fresh)
            if fresh and self.on_fault is not None:
                self.on_fault(fresh)

    # ------------------------------------------------------- relaunch
    def enable_relaunch(self, job_id: str = "default"):
        """Wire fault detection to the launcher's restart channel: a dead
        node bumps ``launch/{job}/restart`` in the store, which every
        ``paddle_tpu.distributed.launch`` process polls — they kill their
        pods and re-rendezvous under the new generation (reference:
        manager.py:457-530 scale-in/relaunch; here the launcher owns the
        process lifecycle, the manager owns detection)."""
        prev = self.on_fault

        def _fault(dead):
            if prev is not None:
                prev(dead)
            self.request_relaunch(job_id)

        self.on_fault = _fault

    def request_relaunch(self, job_id: str = "default") -> int:
        """Bump the restart generation all launchers poll. Returns the new
        generation."""
        return self._store.add(f"launch/{job_id}/restart", 1)

    def scale(self, num_nodes: int, job_id: str = "default") -> int:
        """Record a scale-in/out target (reference manager.py:484,507) and
        trigger a relaunch so the next generation sees it. Launchers read
        ``elastic/num_nodes`` when they re-rendezvous. Returns the new
        restart generation."""
        self.num_nodes = num_nodes
        self._store.set("elastic/num_nodes", str(num_nodes).encode())
        return self.request_relaunch(job_id)

    def stop(self):
        self._stop = True
