"""Elastic training manager (reference:
python/paddle/distributed/fleet/elastic/manager.py:125 ElasticManager —
etcd node registry + heartbeat lease :254, fault watch :457).

TPU-native: the registry lives in the job's TCPStore (no etcd
dependency). This manager is the launcher-facing tier — string node
ids, a fault callback, and the ``launch/{job}/restart`` relaunch
channel. The full in-process self-healing tier (group epochs,
shrink/expand resharding, peer-replicated snapshots) lives in
:mod:`paddle_tpu.distributed.elastic`; this module shares its JSON
lease format so one watch loop can read either producer's beats.

Lease lifecycle: ``stop()`` *deregisters* — it deletes the node's
``elastic/nodes/*`` and ``elastic/beat/*`` keys and joins the
background threads with a timeout, so a cleanly-exiting node is never
reported as a fault by the survivors' watch.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ElasticManager"]


class ElasticManager:
    ELASTIC_TIMEOUT = 10.0

    def __init__(self, store, node_id: str, num_nodes: int,
                 heartbeat_interval: float = 2.0,
                 timeout: Optional[float] = None,
                 on_fault: Optional[Callable[[List[str]], None]] = None):
        # store clients are internally synchronized (LocalStore locks
        # every op; TCPStore is one request per call) — the .add/.set
        # calls below are not unguarded shared-state mutation
        self._store = store  # ptlint: disable=thread-escape
        self.node_id = node_id
        self.num_nodes = num_nodes
        self.interval = heartbeat_interval
        self.timeout = timeout or self.ELASTIC_TIMEOUT
        self.on_fault = on_fault  # guarded by: _cb_lock
        self._cb_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------ lease
    def register(self):
        """Join the registry and start the heartbeat lease thread
        (reference: manager.py:254)."""
        self._store.set(f"elastic/nodes/{self.node_id}",
                        json.dumps({"t": time.time()}).encode())
        self._beat()
        t = threading.Thread(target=self._beat_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _beat(self):
        # JSON lease payload, shared with distributed/elastic
        # membership beats (extra fields are carried, not required)
        self._store.set(f"elastic/beat/{self.node_id}",
                        json.dumps({"t": time.time()}).encode())

    def _beat_loop(self):
        while not self._stop.wait(self.interval):
            try:
                self._beat()
            except Exception:
                pass  # a store blip must not kill the lease thread

    def _try_get(self, key: str):
        fn = getattr(self._store, "try_get", None)
        if fn is not None:
            return fn(key)
        if not self._store.check(key):
            return None
        return self._store.get(key)

    @staticmethod
    def _beat_time(raw: bytes) -> float:
        """Beat timestamp from either the JSON lease payload or the
        legacy bare-float format."""
        try:
            return float(json.loads(raw.decode())["t"])
        except (ValueError, KeyError, TypeError):
            return float(raw.decode())

    # ------------------------------------------------------------ watch
    def watch(self, node_ids: List[str]):
        """Master-side fault watch (reference: _update_fault_tolerance
        manager.py:457)."""
        t = threading.Thread(target=self._watch_loop, args=(node_ids,),
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _watch_loop(self, node_ids):
        watch_start = time.time()
        reported = set()
        registered = set()
        left = set()
        last_beats: Dict[str, float] = {}
        while not self._stop.wait(self.interval):
            now = time.time()
            dead = []
            for nid in node_ids:
                try:
                    # a node whose registry key we SAW and which then
                    # deleted it deregistered cleanly: not a fault —
                    # and stays exempt until it re-registers. A node
                    # that never registered stays under beat-based
                    # detection (watched-but-silent == dead).
                    if self._store.check(f"elastic/nodes/{nid}"):
                        registered.add(nid)
                        left.discard(nid)
                    elif nid in registered:
                        registered.discard(nid)
                        left.add(nid)
                        last_beats.pop(nid, None)
                        reported.discard(nid)
                        continue
                    elif nid in left:
                        continue
                    # atomic get-or-None — check-then-get races a
                    # concurrent deregistration's delete, and get()
                    # would then block on the missing key
                    raw = self._try_get(f"elastic/beat/{nid}")
                    if raw is not None:
                        last = self._beat_time(raw)
                        last_beats[nid] = last
                    else:
                        # never heartbeat at all: dead once the grace
                        # period from watch start has passed
                        last = last_beats.get(nid, watch_start)
                except Exception:
                    # transient store error: keep the last-known beat so a
                    # healthy node is not declared dead by a blip
                    last = last_beats.get(nid, now)
                if now - last > self.timeout:
                    dead.append(nid)
                elif nid in reported:
                    reported.discard(nid)  # recovered: re-arm reporting
            fresh = [n for n in dead if n not in reported]
            reported.update(fresh)
            with self._cb_lock:
                cb = self.on_fault
            if fresh and cb is not None:
                cb(fresh)

    # ------------------------------------------------------- relaunch
    def enable_relaunch(self, job_id: str = "default"):
        """Wire fault detection to the launcher's restart channel: a dead
        node bumps ``launch/{job}/restart`` in the store, which every
        ``paddle_tpu.distributed.launch`` process polls — they kill their
        pods and re-rendezvous under the new generation (reference:
        manager.py:457-530 scale-in/relaunch; here the launcher owns the
        process lifecycle, the manager owns detection)."""
        with self._cb_lock:
            prev = self.on_fault

            def _fault(dead):
                if prev is not None:
                    prev(dead)
                self.request_relaunch(job_id)

            self.on_fault = _fault

    def request_relaunch(self, job_id: str = "default") -> int:
        """Bump the restart generation all launchers poll. Returns the new
        generation."""
        return self._store.add(f"launch/{job_id}/restart", 1)

    def scale(self, num_nodes: int, job_id: str = "default") -> int:
        """Record a scale-in/out target (reference manager.py:484,507) and
        trigger a relaunch so the next generation sees it. Launchers read
        ``elastic/num_nodes`` when they re-rendezvous. Returns the new
        restart generation."""
        self.num_nodes = num_nodes
        self._store.set("elastic/num_nodes", str(num_nodes).encode())
        return self.request_relaunch(job_id)

    def stop(self):
        """Deregister: stop + join the background threads (bounded by a
        timeout, never hangs a clean shutdown) and delete this node's
        registry and lease keys so the watch reports no phantom fault."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2 * self.interval + 1.0)
        self._threads = []
        for key in (f"elastic/nodes/{self.node_id}",
                    f"elastic/beat/{self.node_id}"):
            try:
                self._store.delete(key)
            except Exception:
                pass
