"""Megatron-style tensor-parallel layers (reference:
python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:49, ColumnParallelLinear:336, RowParallelLinear:543,
ParallelCrossEntropy:744; identity/allreduce PyLayers in mpu/mp_ops.py).

Eager backend-agnostic implementation over the collective API; the jitted
SPMD path (models/gpt.py) expresses the same math with shardings and lets
GSPMD place the collectives on ICI.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ... import nn
from ...autograd import PyLayer
from ...core.tensor import Tensor
from ...fusion import overlap_mm
from ...nn import functional as F
from .. import collective as dist

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


class _IdentityInBackwardAllReduce(PyLayer):
    """f: identity fwd, all-reduce bwd (mp_ops.py _c_identity)."""

    @staticmethod
    def forward(ctx, x, group):
        ctx.group = group
        return Tensor(x._data)

    @staticmethod
    def backward(ctx, dy):
        g = Tensor(dy._data)
        dist.all_reduce(g, group=ctx.group)
        return g


class _AllReduceInForward(PyLayer):
    """g: all-reduce fwd, identity bwd (mp_ops.py _mp_allreduce)."""

    @staticmethod
    def forward(ctx, x, group):
        out = Tensor(x._data)
        dist.all_reduce(out, group=group)
        ctx.group = group
        return out

    @staticmethod
    def backward(ctx, dy):
        return Tensor(dy._data)


class _GatherConcat(PyLayer):
    """all-gather + concat fwd; take-own-slice bwd (Megatron gather;
    mp_ops.py _c_concat semantics)."""

    @staticmethod
    def forward(ctx, x, group):
        outs = []
        dist.all_gather(outs, Tensor(x._data), group=group)
        ctx.rank = group.rank
        ctx.nranks = group.nranks
        return Tensor(jnp.concatenate([o._data for o in outs], axis=-1))

    @staticmethod
    def backward(ctx, dy):
        parts = jnp.split(dy._data, ctx.nranks, axis=-1)
        return Tensor(parts[ctx.rank])


class VocabParallelEmbedding(nn.Layer):
    """Embedding with the vocab dim split over the mp group."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        from .fleet import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        self.group = mp_group if mp_group is not None else \
            (hcg.get_model_parallel_group() if hcg else None)
        self.world_size = self.group.nranks if self.group else 1
        self.rank = self.group.rank if self.group else 0
        self.origin_num_embeddings = num_embeddings
        assert num_embeddings % self.world_size == 0
        self.per_part_size = num_embeddings // self.world_size
        self.vocab_start_index = self.rank * self.per_part_size
        self.weight = self.create_parameter(
            [self.per_part_size, embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.is_distributed = self.world_size > 1

    def forward(self, x):
        if self.world_size <= 1:
            return F.embedding(x, self.weight)
        start = self.vocab_start_index
        end = start + self.per_part_size
        from ...ops._helpers import as_tensor, run_op, unwrap

        idx = unwrap(as_tensor(x))
        mask = (idx >= start) & (idx < end)
        local_idx = jnp.where(mask, idx - start, 0)

        def fn(w):
            out = jnp.take(w, local_idx, axis=0)
            return jnp.where(mask[..., None], out, 0.0)

        out = run_op(fn, [self.weight], name="vocab_parallel_embedding")
        out = _AllReduceInForward.apply(out, self.group)
        return out


class ColumnParallelLinear(nn.Layer):
    """W [in, out/mp]; optional gather of outputs (mp_layers.py:336)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        from .fleet import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        self.group = mp_group if mp_group is not None else \
            (hcg.get_model_parallel_group() if hcg else None)
        self.world_size = self.group.nranks if self.group else 1
        self.gather_output = gather_output
        assert out_features % self.world_size == 0
        self.out_per_part = out_features // self.world_size
        self.weight = self.create_parameter(
            [in_features, self.out_per_part], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        if has_bias:
            self.bias = self.create_parameter(
                [self.out_per_part], is_bias=True)
            self.bias.is_distributed = self.world_size > 1
        else:
            self.bias = None

    def forward(self, x):
        if self.world_size > 1 and overlap_mm.route("mp_column_linear"):
            # decomposed path: chunked bwd all-reduce rides the GEMM loop
            from ..tp_overlap import column_parallel_linear

            out = column_parallel_linear(x, self.weight, self.bias,
                                         self.group)
        else:
            if self.world_size > 1:
                x = _IdentityInBackwardAllReduce.apply(x, self.group)
            out = F.linear(x, self.weight, self.bias)
        if self.gather_output and self.world_size > 1:
            out = _GatherConcat.apply(out, self.group)
        return out


class RowParallelLinear(nn.Layer):
    """W [in/mp, out]; input either already split or split here
    (mp_layers.py:543)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        from .fleet import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        self.group = mp_group if mp_group is not None else \
            (hcg.get_model_parallel_group() if hcg else None)
        self.world_size = self.group.nranks if self.group else 1
        self.rank = self.group.rank if self.group else 0
        self.input_is_parallel = input_is_parallel
        assert in_features % self.world_size == 0
        self.in_per_part = in_features // self.world_size
        self.weight = self.create_parameter(
            [self.in_per_part, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.world_size <= 1:
            return F.linear(x, self.weight, self.bias)
        if not self.input_is_parallel:
            from ...ops.manipulation import split

            x = split(x, self.world_size, axis=-1)[self.rank]
        if overlap_mm.route("mp_row_linear"):
            # decomposed path: per-chunk fwd all-reduce rides the GEMM loop
            from ..tp_overlap import row_parallel_linear

            out = row_parallel_linear(x, self.weight, self.group)
        else:
            out = F.linear(x, self.weight, None)
            out = _AllReduceInForward.apply(out, self.group)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(nn.Layer):
    """CE over vocab-split logits (mp_layers.py:744): max/subtract, local
    exp-sum, all-reduce sums, local pick of target logit.

    The per-token epilogues (exp-sum, target pick) run through
    ``fusion.chunked.chunked_epilogue`` over ``loss_chunks`` token chunks
    so the [tokens, vocab/mp] exp intermediate is never materialized in
    full — per-token math is chunk-count invariant, so the loss is bitwise
    identical at any chunk count (the same contract lm_head_chunked_ce
    carries)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100,
                 loss_chunks=4):
        super().__init__()
        from .fleet import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        self.group = mp_group if mp_group is not None else \
            (hcg.get_model_parallel_group() if hcg else None)
        self.world_size = self.group.nranks if self.group else 1
        self.rank = self.group.rank if self.group else 0
        self.ignore_index = ignore_index
        self.loss_chunks = max(1, int(loss_chunks))

    def forward(self, input, label):
        if self.world_size <= 1:
            return F.cross_entropy(input, label, reduction="none",
                                   ignore_index=self.ignore_index)
        # local stats
        from ...ops._helpers import as_tensor, run_op, unwrap

        x = as_tensor(input)
        lab = unwrap(as_tensor(label))
        vocab_per = x.shape[-1]
        start = self.rank * vocab_per

        local_max = Tensor(jnp.max(x._data, axis=-1))
        dist.all_reduce(local_max, op=dist.ReduceOp.MAX, group=self.group)
        gmax = local_max._data

        from ...fusion.chunked import chunked_epilogue

        tokens = math.prod(x.shape[:-1])
        # chunk count clamped to a divisor of the token dim so chunking
        # never changes shapes, only splits them
        chunks = max(1, math.gcd(tokens, self.loss_chunks))

        def sumexp_fn(a):
            a2 = a.reshape((tokens, vocab_per))
            g2 = gmax.reshape((tokens,))
            out = chunked_epilogue(
                lambda ac, gc: jnp.sum(jnp.exp(ac - gc[..., None]), axis=-1),
                (a2, g2), chunks)
            return out.reshape(a.shape[:-1])

        sumexp = run_op(sumexp_fn, [x], name="pce_sumexp")
        sumexp = _AllReduceInForward.apply(sumexp, self.group)

        def pick_fn(a):
            li = lab
            if li.ndim == a.ndim:
                li = jnp.squeeze(li, -1)
            a2 = a.reshape((tokens, vocab_per))
            l2 = li.reshape((tokens,))
            g2 = gmax.reshape((tokens,))

            def body(ac, lc, gc):
                inrange = (lc >= start) & (lc < start + vocab_per)
                safe = jnp.where(inrange, lc - start, 0)
                picked = jnp.take_along_axis(
                    ac, safe[..., None], axis=-1)[..., 0]
                return jnp.where(inrange, picked - gc, 0.0)

            out = chunked_epilogue(body, (a2, l2, g2), chunks)
            return out.reshape(a.shape[:-1])

        picked = run_op(pick_fn, [x], name="pce_pick")
        picked = _AllReduceInForward.apply(picked, self.group)
        loss = run_op(lambda s, p: jnp.log(s) - p,
                      [sumexp, picked], name="pce_loss")
        return loss
