"""Megatron-style sequence-parallel utilities for the eager Fleet path
(reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
— ScatterOp/GatherOp:25-85, allreduce hooks for SP params :192,
ColumnSequenceParallelLinear :429, RowSequenceParallelLinear :564).

Activations are sequence-sharded across the mp group between transformer
blocks; Column linear all-gathers the sequence before its matmul and Row
linear reduce-scatters after, so the matmuls see the full hidden dim while
norm/dropout work on 1/mp of the tokens. The SPMD/jit path expresses the
same thing with shardings (models/*.py); this module is the imperative
collective-API formulation.

Layout convention follows the reference: the SEQUENCE dim is axis 0
([s, b, h]) for the split/gather ops.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import nn
from ...autograd import PyLayer
from ...core.tensor import Tensor
from ...nn import functional as F
from .. import collective as dist

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather", "reduce_scatter",
    "mark_as_sequence_parallel_parameter",
    "is_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
]


def _mp_group(group=None):
    if group is not None:
        return group
    from .fleet import get_hybrid_communicate_group

    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_group() if hcg else None


def _split_local(arr, nranks, rank, axis=0):
    parts = jnp.split(arr, nranks, axis=axis)
    return parts[rank]


class ScatterOp(PyLayer):
    """fwd: take own seq slice; bwd: all-gather (reference :25)."""

    @staticmethod
    def forward(ctx, x, axis=0, group=None):
        g = _mp_group(group)
        ctx.group, ctx.axis = g, axis
        if g is None or g.nranks <= 1:
            return Tensor(x._data)
        return Tensor(_split_local(x._data, g.nranks, g.rank, axis))

    @staticmethod
    def backward(ctx, dy):
        g = ctx.group
        if g is None or g.nranks <= 1:
            return Tensor(dy._data)
        outs = []
        dist.all_gather(outs, Tensor(dy._data), group=g)
        return Tensor(jnp.concatenate([o._data for o in outs],
                                      axis=ctx.axis))


class GatherOp(PyLayer):
    """fwd: all-gather along seq; bwd: take own slice (reference :52)."""

    @staticmethod
    def forward(ctx, x, axis=0, group=None):
        g = _mp_group(group)
        ctx.group, ctx.axis = g, axis
        if g is None or g.nranks <= 1:
            return Tensor(x._data)
        outs = []
        dist.all_gather(outs, Tensor(x._data), group=g)
        return Tensor(jnp.concatenate([o._data for o in outs], axis=axis))

    @staticmethod
    def backward(ctx, dy):
        g = ctx.group
        if g is None or g.nranks <= 1:
            return Tensor(dy._data)
        return Tensor(_split_local(dy._data, g.nranks, g.rank, ctx.axis))


class AllGatherOp(PyLayer):
    """fwd: all-gather; bwd: reduce-scatter (reference :85 — the pair that
    makes W-grads exact when activations are seq-sharded)."""

    @staticmethod
    def forward(ctx, x, group=None):
        g = _mp_group(group)
        ctx.group = g
        if g is None or g.nranks <= 1:
            return Tensor(x._data)
        outs = []
        dist.all_gather(outs, Tensor(x._data), group=g)
        return Tensor(jnp.concatenate([o._data for o in outs], axis=0))

    @staticmethod
    def backward(ctx, dy):
        g = ctx.group
        if g is None or g.nranks <= 1:
            return Tensor(dy._data)
        parts = jnp.split(dy._data, g.nranks, axis=0)
        out = Tensor(jnp.zeros_like(parts[0]))
        dist.reduce_scatter(out, [Tensor(p) for p in parts], group=g)
        return out


class ReduceScatterOp(PyLayer):
    """fwd: reduce-scatter along seq; bwd: all-gather (reference :130)."""

    @staticmethod
    def forward(ctx, x, group=None):
        g = _mp_group(group)
        ctx.group = g
        if g is None or g.nranks <= 1:
            return Tensor(x._data)
        parts = jnp.split(x._data, g.nranks, axis=0)
        out = Tensor(jnp.zeros_like(parts[0]))
        dist.reduce_scatter(out, [Tensor(p) for p in parts], group=g)
        return out

    @staticmethod
    def backward(ctx, dy):
        g = ctx.group
        if g is None or g.nranks <= 1:
            return Tensor(dy._data)
        outs = []
        dist.all_gather(outs, Tensor(dy._data), group=g)
        return Tensor(jnp.concatenate([o._data for o in outs], axis=0))


def scatter(x, group=None, axis=0):
    return ScatterOp.apply(x, axis=axis, group=group)


def all_gather(x, group=None):
    return AllGatherOp.apply(x, group=group)


def reduce_scatter(x, group=None):
    return ReduceScatterOp.apply(x, group=group)


# --------------------------------------------------------------- SP params
def mark_as_sequence_parallel_parameter(parameter):
    """Norm/bias params that act on seq-sharded activations produce
    partial grads; mark them so the hook all-reduces (reference :175)."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """All-reduce grads of marked params across the mp group after backward
    (reference :192). With accumulation, fires every Nth backward."""
    group = _mp_group(None)
    if group is None or group.nranks <= 1:
        return

    params = [p for p in layer.parameters()
              if is_sequence_parallel_parameter(p)]
    counters = {}

    def make_hook(p):
        def hook(grad):
            c = counters.get(id(p), 0) + 1
            counters[id(p)] = c
            if c % accumulation_steps == 0:
                g = Tensor(grad._data) if isinstance(grad, Tensor) \
                    else Tensor(grad)
                dist.all_reduce(g, group=group)
                return g
            return grad

        return hook

    for p in params:
        p.register_hook(make_hook(p))


# ------------------------------------------------------------ SP linears
class ColumnSequenceParallelLinear(nn.Layer):
    """All-gather seq -> matmul with column-split W [in, out/mp]
    (reference :429). Input [s/mp, b, in]; output [s, b, out/mp]."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.group = _mp_group(mp_group)
        self.world_size = self.group.nranks if self.group else 1
        assert gather_output is False, (
            "ColumnSequenceParallelLinear feeds RowSequenceParallelLinear; "
            "gather_output is not supported (matches reference assert :478)")
        assert out_features % self.world_size == 0
        self.out_per_part = out_features // self.world_size
        self.weight = self.create_parameter(
            [in_features, self.out_per_part], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        if has_bias:
            self.bias = self.create_parameter(
                [self.out_per_part], is_bias=True)
            self.bias.is_distributed = self.world_size > 1
        else:
            self.bias = None

    def forward(self, x):
        if self.world_size <= 1:
            return F.linear(x, self.weight, self.bias)
        from ...fusion import overlap_mm

        if overlap_mm.route("sp_column_linear"):
            # decomposed all-gather-matmul: each seq chunk's gather rides
            # the previous chunk's GEMM (bitwise == the serial pair below)
            from ..tp_overlap import all_gather_matmul_eager

            return all_gather_matmul_eager(x, self.weight, self.bias,
                                           self.group)
        x = AllGatherOp.apply(x, group=self.group)
        return F.linear(x, self.weight, self.bias)


class RowSequenceParallelLinear(nn.Layer):
    """Matmul with row-split W [in/mp, out] -> reduce-scatter seq
    (reference :564). Input [s, b, in/mp]; output [s/mp, b, out]."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.group = _mp_group(mp_group)
        self.world_size = self.group.nranks if self.group else 1
        assert input_is_parallel, (
            "RowSequenceParallelLinear expects column-parallel input "
            "(matches reference assert :597)")
        assert in_features % self.world_size == 0
        self.in_per_part = in_features // self.world_size
        self.weight = self.create_parameter(
            [self.in_per_part, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.is_distributed = self.world_size > 1
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            # bias applies after reduce-scatter on seq-sharded activations:
            # its grad is partial across mp -> needs the SP allreduce hook
            mark_as_sequence_parallel_parameter(self.bias)
        else:
            self.bias = None

    def forward(self, x):
        if self.world_size <= 1:
            return F.linear(x, self.weight, self.bias)
        from ...fusion import overlap_mm

        if overlap_mm.route("sp_row_linear"):
            # decomposed matmul-reduce-scatter: per-chunk reduce-scatter
            # rides the next chunk's GEMM (bitwise == the serial pair)
            from ..tp_overlap import matmul_reduce_scatter_eager

            out = matmul_reduce_scatter_eager(x, self.weight, self.group)
        else:
            out = F.linear(x, self.weight, None)
            out = ReduceScatterOp.apply(out, group=self.group)
        if self.bias is not None:
            out = out + self.bias
        return out
