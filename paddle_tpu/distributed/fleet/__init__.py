"""paddle_tpu.distributed.fleet (reference: python/paddle/distributed/fleet/)."""
from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet import (  # noqa: F401
    Fleet,
    barrier_worker,
    distributed_model,
    distributed_optimizer,
    get_hybrid_communicate_group,
    init,
    is_first_worker,
    worker_index,
    worker_num,
)
from .topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode  # noqa: F401
from .base_role import (  # noqa: F401
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
    UtilBase,
)
from . import meta_parallel  # noqa: F401
from . import recompute as _recompute_mod  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .meta_parallel import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SharedLayerDesc,
    TensorParallel,
)
from .sharding_optimizer import (  # noqa: F401
    DygraphShardingOptimizer,
    GroupShardedOptimizerStage2,
    GroupShardedStage2,
    GroupShardedStage3,
)
from . import hybrid_parallel_util  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .hybrid_parallel_optimizer import HybridParallelOptimizer  # noqa: F401

# namespace parity: fleet.utils / fleet.layers.mpu / fleet.base
from . import mp_layers as _mpu  # noqa: F401


class _Utils:
    hybrid_parallel_util = hybrid_parallel_util


utils = _Utils()


class _MPU:
    VocabParallelEmbedding = VocabParallelEmbedding
    ColumnParallelLinear = ColumnParallelLinear
    RowParallelLinear = RowParallelLinear
    ParallelCrossEntropy = ParallelCrossEntropy


class _Layers:
    mpu = _MPU()


layers = _Layers()
