"""DistributedStrategy (reference:
python/paddle/distributed/fleet/base/distributed_strategy.py:284; the
reference backs it with a protobuf — here a plain config tree with the same
field names)."""
from __future__ import annotations

__all__ = ["DistributedStrategy"]


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
        }
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 65536.0,
            "use_pure_fp16": False,
            "use_pure_bf16": False,
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.lamb = False
        self.dgc = False
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.heter_ccl_mode = False
        self.auto = False
        self.a_sync = False

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()}
        return f"DistributedStrategy({fields})"
