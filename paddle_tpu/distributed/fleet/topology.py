"""Hybrid-parallel topology (reference:
python/paddle/distributed/fleet/base/topology.py — CommunicateTopology:70,
HybridCommunicateGroup:189).

Pure rank arithmetic + group creation; backend-agnostic (works over
ProcessGroupCPU for tests and ProcessGroupXLA on TPU pods).
"""
from __future__ import annotations

import collections
import itertools
from functools import reduce
from typing import Dict, List

import numpy as np

__all__ = ["ParallelMode", "CommunicateTopology", "HybridCommunicateGroup"]


class ParallelMode:
    """reference: topology.py:42."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class CommunicateTopology:
    """reference: topology.py:70."""

    def __init__(self,
                 hybrid_group_names=("data", "pipe", "sharding", "sep",
                                     "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple(
            "Coordinate", self._parallel_names)
        self._world_size = reduce(lambda x, y: x * y, self._dims, 1)
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in itertools.product(*ranges)]
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **args):
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = [self._coord2rank[c] for c in self._coord2rank
                 if c[axis] == index]
        return sorted(ranks)

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: list of rank-lists."""
        axis = self._parallel_names.index(axis_name)
        other_axes = [i for i in range(len(self._dims)) if i != axis]
        comm_list = []
        for other in itertools.product(
                *[range(self._dims[i]) for i in other_axes]):
            ranks = []
            for v in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, o in zip(other_axes, other):
                    coord[i] = o
                coord[axis] = v
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            comm_list.append(ranks)
        return comm_list

    def get_fused_ranks(self, fused_axes):
        """Groups over the cartesian product of several axes (e.g. dp×sep
        gradient group, reference topology.py get_fused_ranks)."""
        non_fused = [n for n in self._parallel_names if n not in fused_axes]
        comm_list = []
        for other in itertools.product(
                *[range(self.get_dim(n)) for n in non_fused]):
            ranks = []
            for fused in itertools.product(
                    *[range(self.get_dim(n)) for n in fused_axes]):
                kw = dict(zip(non_fused, other))
                kw.update(dict(zip(fused_axes, fused)))
                ranks.append(self.get_rank(**kw))
            comm_list.append(sorted(ranks))
        return comm_list


class HybridCommunicateGroup:
    """reference: topology.py:189. Creates one comm group per axis (and the
    fused dp×sep gradient group and pp p2p neighbors)."""

    def __init__(self, topology: CommunicateTopology):
        from ..collective import new_group
        from ..parallel_env import ParallelEnv

        self._topo = topology
        self.global_rank = ParallelEnv().rank
        self._dp_degree = self._topo.get_dim("data")
        self._mp_degree = self._topo.get_dim("model")
        self._pp_degree = self._topo.get_dim("pipe")
        self._sharding_degree = self._topo.get_dim("sharding")
        self._sep_degree = self._topo.get_dim("sep")
        self.nranks = topology.world_size()

        # per-axis groups
        self._dp_group, self._dp_comm_group = self._set_comm_group("data")
        self._mp_group, self._mp_comm_group = self._set_comm_group("model")
        self._pp_group, self._pp_comm_group = self._set_comm_group("pipe")
        self._sharding_group, self._sharding_comm_group = \
            self._set_comm_group("sharding")
        self._sep_group, self._sep_comm_group = self._set_comm_group("sep")

        # fused dp×sep group for gradient all-reduce (topology.py:551)
        if self._sep_degree > 1:
            self._dp_sep_comm_group = self._set_fused_group(["data", "sep"])
        else:
            self._dp_sep_comm_group = self._dp_comm_group

        # pp p2p neighbors
        self._pp_prev_rank = None
        self._pp_next_rank = None
        if self._pp_degree > 1:
            self._set_p2p_neighbors()

        # pp position
        coord = self._topo.get_coord(self.global_rank)
        self.stage_id = coord.pipe
        self._is_first_stage = self.stage_id == 0
        self._is_last_stage = self.stage_id == (self._pp_degree - 1)

    def _set_comm_group(self, axis_name):
        from ..collective import new_group

        comm_lists = self._topo.get_comm_list(axis_name)
        my_group_ranks = None
        my_group = None
        for ranks in comm_lists:
            grp = new_group(ranks)
            if self.global_rank in ranks:
                my_group_ranks = ranks
                my_group = grp
        return my_group_ranks, my_group

    def _set_fused_group(self, axes):
        from ..collective import new_group

        my_group = None
        for ranks in self._topo.get_fused_ranks(axes):
            grp = new_group(ranks)
            if self.global_rank in ranks:
                my_group = grp
        return my_group

    def _set_p2p_neighbors(self):
        ranks = self._pp_group
        idx = ranks.index(self.global_rank)
        self._pp_next_rank = ranks[(idx + 1) % len(ranks)]
        self._pp_prev_rank = ranks[(idx - 1) % len(ranks)]

    # ------------------------------------------------------------ queries
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1 and self._dp_degree > 1:
            return ParallelMode.DATA_PARALLEL
        if self._mp_degree > 1 and self._pp_degree == 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).data

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_comm_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).model

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_comm_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group[0]

    # pipe parallel
    def get_stage_id(self):
        return self.stage_id

    def get_pipe_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).pipe

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_comm_group

    def is_first_stage(self):
        return self._is_first_stage

    def is_last_stage(self):
        return self._is_last_stage

    def get_p2p_next_rank(self):
        return self._pp_next_rank

    def get_p2p_prev_rank(self):
        return self._pp_prev_rank

    # sharding
    def get_sharding_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).sharding

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_comm_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group[0]

    # sep
    def get_sep_parallel_rank(self):
        return self._topo.get_coord(self.global_rank).sep

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_comm_group

    def get_dp_sep_parallel_group(self):
        return self._dp_sep_comm_group
