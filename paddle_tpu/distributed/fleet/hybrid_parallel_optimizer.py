"""HybridParallelOptimizer (reference:
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py —
hybrid-aware global-norm clip :103, _insert_sync :373, step :525)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.clip import ClipGradByGlobalNorm
from .. import collective as dist

__all__ = ["HybridParallelOptimizer", "HybridParallelGradScaler"]


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._sharding_enable = hcg.get_sharding_parallel_world_size() > 1
        # gradient merge (reference: distributed_strategy.py gradient_merge
        # configs): apply the update every k_steps; in-between steps keep
        # accumulating grads (clear_grad is deferred to the apply step)
        self._gm_k = 1
        self._gm_avg = True
        if strategy is not None and getattr(strategy, "gradient_merge",
                                            False):
            self._gm_k = int(
                strategy.gradient_merge_configs.get("k_steps", 1))
            self._gm_avg = bool(
                strategy.gradient_merge_configs.get("avg", True))
        self._gm_count = 0
        # snapshot the FULL param list now: a sharding wrapper later
        # replaces _parameter_list with the local shard, but the merge
        # average must scale every param's grad on every rank (peer
        # contributions are reduced to owners before the local update)
        self._gm_params = list(getattr(optimizer, "_parameter_list",
                                       None) or [])
        # wrap global-norm clip with the cross-group norm reduction
        clip = getattr(optimizer, "_grad_clip", None)
        if isinstance(clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = _HybridClip(clip, hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        if self._gm_k > 1:
            self._gm_count += 1
            if self._gm_count % self._gm_k:
                return  # accumulate; user's clear_grad is deferred too
            if self._gm_avg:
                # reference gradient_merge avg=True (default): the applied
                # gradient is the microbatch MEAN, not the k-step sum
                for p in self._gm_params:
                    if p.grad is not None:
                        p.grad.scale_(1.0 / self._gm_k)
        if self._sharding_enable:
            from .sharding_optimizer import DygraphShardingOptimizer

            if not isinstance(self._inner_opt, DygraphShardingOptimizer):
                # shard on first use
                self._inner_opt = DygraphShardingOptimizer(
                    self._inner_opt, self._hcg)
        # mp: sync params that are replicated across mp (non-distributed)
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        if self._gm_k > 1 and self._gm_count % self._gm_k:
            return  # mid-merge: keep accumulated grads
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, *args, **kwargs):
        loss.backward()
        self.step()
        return None, None


class _HybridClip:
    """Global-norm clip whose squared-norm is all-reduced across mp/pp/
    sharding groups so every rank clips by the TRUE global norm
    (reference: hybrid_parallel_optimizer.py:103 _dygraph_clip)."""

    def __init__(self, clip: ClipGradByGlobalNorm, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        # local sq-norm of distributed (mp-sharded) params needs reduction
        # across mp; non-distributed params are identical on mp ranks.
        dist_sq = jnp.zeros((), jnp.float32)
        rep_sq = jnp.zeros((), jnp.float32)
        for p, g in params_grads:
            if g is None:
                continue
            s = jnp.sum(g._data.astype(jnp.float32) ** 2)
            if getattr(p, "is_distributed", False):
                dist_sq = dist_sq + s
            else:
                rep_sq = rep_sq + s
        hcg = self._hcg
        total_dist = Tensor(dist_sq)
        if hcg.get_model_parallel_world_size() > 1:
            dist.all_reduce(total_dist, group=hcg.get_model_parallel_group())
        total = Tensor(total_dist._data + rep_sq)
        if hcg.get_pipe_parallel_world_size() > 1:
            dist.all_reduce(total, group=hcg.get_pipe_parallel_group())
        if hcg.get_sharding_parallel_world_size() > 1:
            dist.all_reduce(total, group=hcg.get_sharding_parallel_group())
        gnorm = jnp.sqrt(total._data)
        scale = jnp.minimum(self._clip.clip_norm / jnp.maximum(gnorm, 1e-12),
                            1.0)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(
                    g._data.dtype))))
        return out


class HybridParallelGradScaler:
    def __init__(self, scaler, hcg):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self._scaler, item)
