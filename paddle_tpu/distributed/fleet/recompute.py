"""Activation recompute (reference:
python/paddle/distributed/fleet/recompute/recompute.py:124 RecomputeFunction).

TPU-native: jax.checkpoint (rematerialization) IS this feature inside jit;
the eager path re-runs the function under the saved RNG state in backward —
same contract as the reference PyLayer."""
from __future__ import annotations

from ...autograd.py_layer import PyLayer
from ...core import random as _rng
from ...core.autograd import no_grad
from ...core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


class _RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.preserve_rng = preserve_rng_state
        ctx.rng_state = _rng.get_rng_state()
        ctx.inputs = [a.detach() if isinstance(a, Tensor) else a
                      for a in args]
        for orig, det in zip(args, ctx.inputs):
            if isinstance(orig, Tensor):
                det.stop_gradient = orig.stop_gradient
        with no_grad():
            out = run_function(*ctx.inputs)
        return out

    @staticmethod
    def backward(ctx, *grads):
        from ...core.autograd import backward as run_backward

        saved_state = _rng.get_rng_state()
        if ctx.preserve_rng:
            _rng.set_rng_state(ctx.rng_state)
        try:
            inputs = [Tensor(a._data, stop_gradient=a.stop_gradient)
                      if isinstance(a, Tensor) else a for a in ctx.inputs]
            # re-run forward WITH grad to rebuild the local tape
            out = ctx.run_function(*inputs)
        finally:
            if ctx.preserve_rng:
                _rng.set_rng_state(saved_state)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        run_backward(list(outs), list(grads))
        # PyLayer contract: one grad per *tensor* input, in order
        return tuple(t._grad if t._grad is not None else None
                     for t in inputs if isinstance(t, Tensor))


def recompute(function, *args, **kwargs):
    """reference: recompute.py:124. kwargs: preserve_rng_state, use_reentrant."""
    preserve = kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", None)
    if kwargs:
        fn = lambda *a: function(*a, **kwargs)  # noqa: E731
    else:
        fn = function
    return _RecomputeFunction.apply(fn, preserve, *args)


def recompute_sequential(ctx, functions, *args):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if isinstance(functions, (list, tuple)):
        layers = list(functions)
    else:
        layers = list(functions)
    n = len(layers)
    per = max(n // segments, 1)
    x = args[0] if len(args) == 1 else args

    def run_segment(start, end):
        def seg_fn(inp):
            out = inp
            for l in layers[start:end]:
                out = l(out)
            return out

        return seg_fn

    i = 0
    while i < n:
        end = min(i + per, n)
        x = recompute(run_segment(i, end), x)
        i = end
    return x
