"""Collective watchdog: hang/timeout detection for comm ops
(reference: phi/core/distributed/comm_task_manager.h:37 CommTaskManager,
NCCLCommTask::IsTimeout nccl_comm_task.cc:234, AbortComm :240).

Enable with ``PADDLE_TPU_COMM_TIMEOUT=<seconds>`` or ``enable(timeout)``:
every ProcessGroup collective is registered as a CommTask; a daemon thread
flags tasks that exceed the timeout, dumps the in-flight trace (op name,
group, start time — the FLAGS_enable_async_trace analog) and calls the
abort callback. The default abort routes through
``resilience.emergency.abort_process`` — abort interceptors (the
elastic membership coordinator's hang report) may claim it and keep
the process alive for an epoch-change rejoin; unclaimed aborts exit
124 like the reference's AbortComm teardown, so a hung ring cannot
wedge the job silently either way.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..config import knobs

__all__ = ["CommTask", "CommTaskManager", "enable", "disable", "watch"]


class CommTask:
    def __init__(self, op_name: str, group_id: int, timeout: float):
        self.op_name = op_name
        self.group_id = group_id
        self.start = time.monotonic()
        self.timeout = timeout
        self.done = False

    def is_timeout(self) -> bool:
        return not self.done and \
            (time.monotonic() - self.start) > self.timeout

    def __repr__(self):
        age = time.monotonic() - self.start
        return (f"CommTask(op={self.op_name}, group={self.group_id}, "
                f"age={age:.1f}s, timeout={self.timeout}s)")


class CommTaskManager:
    """reference: comm_task_manager.h:37 — polls async comm tasks."""

    _instance: Optional["CommTaskManager"] = None

    def __init__(self, poll_interval: float = 1.0):
        self._tasks: Dict[int, CommTask] = {}  # guarded by: _lock
        self._lock = threading.Lock()
        self._next_id = 0  # guarded by: _lock
        self._poll = poll_interval
        self._stop = threading.Event()
        self.on_timeout: Callable[[CommTask], None] = self._default_abort
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @classmethod
    def instance(cls) -> "CommTaskManager":
        if cls._instance is None:
            cls._instance = CommTaskManager()
        return cls._instance

    def register(self, op_name: str, group_id: int, timeout: float) -> int:
        with self._lock:
            tid = self._next_id
            self._next_id += 1
            self._tasks[tid] = CommTask(op_name, group_id, timeout)
            return tid

    def complete(self, tid: int):
        with self._lock:
            t = self._tasks.pop(tid, None)
            if t is not None:
                t.done = True

    def in_flight(self):
        with self._lock:
            return list(self._tasks.values())

    def _loop(self):
        while not self._stop.wait(self._poll):
            with self._lock:
                expired = [(tid, t) for tid, t in self._tasks.items()
                           if t.is_timeout()]
                # fire once per task: drop before invoking the handler
                for tid, _ in expired:
                    self._tasks.pop(tid, None)
            for _, t in expired:
                self._dump_trace(t)
                self.on_timeout(t)

    def _dump_trace(self, task: CommTask):
        import sys

        print(f"[comm-watchdog] TIMEOUT: {task}", file=sys.stderr)
        in_flight = self.in_flight()
        for t in in_flight:
            print(f"[comm-watchdog]   in-flight: {t}", file=sys.stderr)
        # full post-mortem BEFORE the abort callback (default os._exit
        # would otherwise take every diagnostic with it): metrics
        # snapshot + flight-recorder ring + span trace + the in-flight
        # CommTask table land under $PADDLE_TPU_DUMP_DIR
        try:
            from ..observability import flight_recorder

            d = flight_recorder.default_dump_dir()
            if d:
                rank = os.environ.get("PADDLE_TRAINER_ID", "0")
                bundle = os.path.join(
                    d, f"watchdog_rank{rank}_pid{os.getpid()}")
                out = flight_recorder.dump_debug_bundle(
                    bundle, reason=f"comm watchdog timeout: {task!r}",
                    extra={"timed_out": repr(task),
                           "in_flight": [repr(t) for t in in_flight]})
                if out:
                    print(f"[comm-watchdog] debug bundle: {out}",
                          file=sys.stderr)
        except Exception:
            import traceback

            traceback.print_exc()
        # best-effort emergency checkpoint next to the debug bundle —
        # the Engine registers a synchronous save hook during fit()
        try:
            from .resilience import emergency

            saved = emergency.trigger(f"comm watchdog timeout: {task!r}")
            for p in saved:
                print(f"[comm-watchdog] emergency checkpoint: {p}",
                      file=sys.stderr)
        except Exception:
            import traceback

            traceback.print_exc()

    def _default_abort(self, task: CommTask):
        # reference AbortComm — but routed through the shared abort
        # path instead of a bare os._exit: an elastic membership
        # coordinator (or any registered interceptor) can claim the
        # abort and convert the hang into an epoch change; otherwise
        # the process exits 124 as before. _dump_trace already laid the
        # forensic trail (debug bundle + emergency checkpoint), so the
        # abort path must not duplicate it.
        from .resilience import emergency

        emergency.abort_process(
            f"comm watchdog timeout: {task!r}", exit_code=124,
            forensics_done=True)

    def shutdown(self):
        self._stop.set()


_UNSET = object()
_timeout = _UNSET  # _UNSET: follow env var; None: explicitly disabled


def _env_timeout() -> Optional[float]:
    return knobs.get_float("PADDLE_TPU_COMM_TIMEOUT")


def enable(timeout: float, on_timeout=None):
    global _timeout
    _timeout = timeout
    mgr = CommTaskManager.instance()
    if on_timeout is not None:
        mgr.on_timeout = on_timeout


def disable():
    """Explicitly off — overrides PADDLE_TPU_COMM_TIMEOUT (e.g. around a
    first-compile collective that legitimately exceeds the deadline)."""
    global _timeout
    _timeout = None


def get_timeout() -> Optional[float]:
    if _timeout is _UNSET:
        return _env_timeout()
    return _timeout


class watch:
    """Context manager wrapping one collective invocation."""

    def __init__(self, op_name: str, group_id: int = 0):
        self.op_name = op_name
        self.group_id = group_id
        self._tid = None

    def __enter__(self):
        t = get_timeout()
        if t is not None:
            self._tid = CommTaskManager.instance().register(
                self.op_name, self.group_id, t)
        return self

    def __exit__(self, *exc):
        if self._tid is not None:
            CommTaskManager.instance().complete(self._tid)
