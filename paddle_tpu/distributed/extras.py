"""Top-level distributed API completions (reference:
python/paddle/distributed/__init__.py exports not covered elsewhere:
alltoall_single, mp split op, ReduceType, gloo_* bootstrap, is_available,
shard_scaler, entry attrs).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor, unwrap

__all__ = ["alltoall_single", "split", "ReduceType", "is_available",
           "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
           "shard_scaler", "EntryAttr", "ProbabilityEntry",
           "CountFilterEntry", "ShowClickEntry"]


class ReduceType:
    """reference: phi/core/distributed/reduce_type (paddle.base.core
    ReduceType enum surfaced as paddle.distributed.ReduceType)."""

    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


def is_available():
    """reference: distributed/__init__.py is_available — whether the
    distributed stack can be used (always true: the CPU/XLA backends are
    in-process)."""
    return True


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Scatter row-chunks of in_tensor to every rank and gather theirs
    (reference: distributed/communication/all_to_all.py:78).

    Equal split when sizes are None; otherwise in_split_sizes[i] rows go
    to rank i and out_split_sizes[i] rows arrive from rank i.
    """
    from .collective import all_to_all, get_group

    g = get_group(group)
    n = g.nranks if g is not None else 1
    x = as_tensor(in_tensor)
    if in_split_sizes is None:
        rows = x.shape[0]
        if rows % n:
            raise ValueError(
                f"alltoall_single: dim 0 ({rows}) not divisible by "
                f"world size {n}")
        sizes_in = [rows // n] * n
    else:
        sizes_in = [int(s) for s in in_split_sizes]
    chunks = []
    start = 0
    a = unwrap(x)
    for s in sizes_in:
        chunks.append(Tensor(a[start:start + s]))
        start += s
    outs = [None] * n
    all_to_all(outs, chunks, group=group, sync_op=sync_op)
    cat = jnp.concatenate([unwrap(as_tensor(o)) for o in outs], axis=0)
    out_tensor._data = cat
    return out_tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel split op (reference:
    distributed/fleet/layers/mpu/mp_ops.py:714): builds the matching
    parallel layer over the current model-parallel group and applies it.

    - operation='embedding': vocab-parallel embedding, size=(N, M)
    - operation='linear', axis=0: row-parallel linear (input split)
    - operation='linear', axis=1: column-parallel linear (weight cols
      split; gather_out controls the final all-gather)
    """
    from .fleet import mp_layers

    if operation == "embedding":
        layer = mp_layers.VocabParallelEmbedding(
            size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError("operation must be 'linear' or 'embedding'")
    if axis == 0:
        layer = mp_layers.RowParallelLinear(
            size[0], size[1], weight_attr=weight_attr,
            input_is_parallel=False,
            has_bias=bias_attr is not False)
        return layer(x)
    layer = mp_layers.ColumnParallelLinear(
        size[0], size[1], weight_attr=weight_attr,
        gather_output=gather_out, has_bias=bias_attr is not False)
    return layer(x)


# ---------------------------------------------------------------- gloo shims
def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference: distributed/parallel.py gloo_init_parallel_env — CPU
    barrier bootstrap. Maps to init_parallel_env on the cpu backend with a
    TCPStore rendezvous at server_endpoint."""
    import os

    from .parallel_env import init_parallel_env

    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    os.environ.setdefault("MASTER_ENDPOINT", server_endpoint)
    init_parallel_env(backend="cpu")


def gloo_barrier():
    """reference: distributed/parallel.py gloo_barrier."""
    from .collective import barrier

    barrier()


def gloo_release():
    """reference: distributed/parallel.py gloo_release — tear down the
    bootstrap store."""
    from .collective import destroy_process_group

    destroy_process_group()


def shard_scaler(scaler):
    """Make a GradScaler hybrid-parallel-aware (reference:
    distributed/auto_parallel/api.py shard_scaler): the found-inf flag
    must agree across ranks before the scale update.

    On this stack the scaler reads grads that are either replicated
    DistTensors or process-local shards; we wrap its nan/inf scan to
    all-reduce the flag over the default group when one is initialized.
    """
    orig_found_inf = getattr(scaler, "_found_inf_fn", None)

    def _allreduce_found_inf(flag: bool) -> bool:
        from .parallel_env import is_initialized

        if not is_initialized():
            return flag
        from .collective import all_reduce

        t = Tensor(jnp.asarray([1.0 if flag else 0.0]))
        all_reduce(t)
        return bool(float(unwrap(t)[0]) > 0)

    if orig_found_inf is not None:
        scaler._found_inf_fn = lambda f: _allreduce_found_inf(
            orig_found_inf(f))
    else:
        scaler._dist_found_inf_hook = _allreduce_found_inf
    return scaler


# ------------------------------------------------------------- entry attrs
class EntryAttr:
    """Sparse-feature admission/eviction config for large-scale embedding
    (reference: distributed/entry_attr.py). Value objects consumed by the
    parameter-server tier (see distributed/ps for the TPU stance)."""

    def _to_attr(self):
        raise NotImplementedError("EntryAttr is base class")


class ProbabilityEntry(EntryAttr):
    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self._name = "probability_entry"
        self._probability = float(probability)

    def _to_attr(self):
        return f"{self._name}:{self._probability}"


class CountFilterEntry(EntryAttr):
    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._name = "count_filter_entry"
        self._count_filter = int(count_filter)

    def _to_attr(self):
        return f"{self._name}:{self._count_filter}"


class ShowClickEntry(EntryAttr):
    def __init__(self, show_name, click_name):
        if not isinstance(show_name, str) or not isinstance(click_name,
                                                            str):
            raise ValueError("show_name/click_name must be str")
        self._name = "show_click_entry"
        self._show = show_name
        self._click = click_name

    def _to_attr(self):
        return f"{self._name}:{self._show}:{self._click}"
