"""Fleet datasets: InMemoryDataset / QueueDataset (reference:
python/paddle/distributed/fleet/dataset/dataset.py over the C++
MultiSlotDataset).

TPU-native: these feed CTR-style slot data. The C++ dataset runtime
(channels, merge-by-lineid, Hogwild readers) served the parameter-server
CPU trainers; here the same API surface is backed by a host-side reader:
text slot files -> per-slot numpy batches, with in-memory global/local
shuffle for InMemoryDataset and streaming iteration for QueueDataset.
"""
from __future__ import annotations

import random
from typing import List, Sequence

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


class DatasetBase:
    def __init__(self):
        self._filelist: List[str] = []
        self._use_vars: Sequence = []
        self._batch_size = 1
        self._thread_num = 1
        self._pipe_command = "cat"

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             **kwargs):
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        self._use_vars = use_var or []
        self._pipe_command = pipe_command

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def get_filelist(self):
        return list(self._filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_use_var(self, use_vars):
        self._use_vars = list(use_vars)

    # -------------------------------------------------------- record IO
    def _parse_line(self, line):
        """MultiSlot text format: space-separated tokens; the reference's
        pipe_command preprocesses — here lines are `v v v ...` per
        sample, one slot per use_var consuming one token each (ints for
        sparse slots, floats otherwise)."""
        toks = line.strip().split()
        return [float(t) for t in toks]

    def _iter_records(self):
        for fname in self._filelist:
            with open(fname) as f:
                for line in f:
                    if line.strip():
                        yield self._parse_line(line)


class InMemoryDataset(DatasetBase):
    """reference: fleet/dataset InMemoryDataset — load all records to
    memory, shuffle globally/locally, then iterate batches."""

    def __init__(self):
        super().__init__()
        self._records: List = []
        self._loaded = False

    def load_into_memory(self):
        self._records = list(self._iter_records())
        self._loaded = True

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._records)

    def local_shuffle(self):
        random.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=12):
        """With a process group initialized this would alltoall records by
        hash; single-host semantics are a full shuffle."""
        random.shuffle(self._records)

    def release_memory(self):
        self._records = []
        self._loaded = False

    def __iter__(self):
        if not self._loaded:
            self.load_into_memory()
        for i in range(0, len(self._records), self._batch_size):
            batch = self._records[i:i + self._batch_size]
            yield np.asarray(batch, np.float32)


class QueueDataset(DatasetBase):
    """reference: fleet/dataset QueueDataset — streaming one-pass reader,
    nothing resident in memory."""

    def __iter__(self):
        batch = []
        for rec in self._iter_records():
            batch.append(rec)
            if len(batch) == self._batch_size:
                yield np.asarray(batch, np.float32)
                batch = []
        if batch:
            yield np.asarray(batch, np.float32)
