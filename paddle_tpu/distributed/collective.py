"""Python collective API (reference: python/paddle/distributed/communication/
+ collective.py — Group at communication/group.py:29, new_group at
collective.py:194)."""
from __future__ import annotations

import os
from typing import List, Optional

from ..core.tensor import Tensor
from .process_group import ProcessGroup, ProcessGroupSingle, ReduceOp

__all__ = ["Group", "ReduceOp", "new_group", "get_group", "all_reduce",
           "all_gather", "all_gather_object", "all_to_all", "alltoall",
           "broadcast", "broadcast_object_list", "reduce", "reduce_scatter",
           "scatter", "scatter_object_list", "gather", "send", "recv",
           "isend", "irecv", "barrier", "wait", "split_group",
           "destroy_process_group", "batch_isend_irecv", "P2POp",
           "get_backend", "stream"]

_group_map = {}
_next_gid = 1
_default_group: Optional["Group"] = None


class Group:
    """reference: python/paddle/distributed/communication/group.py:29."""

    def __init__(self, rank_in_group: int, gid: int, ranks: List[int],
                 pg: Optional[ProcessGroup] = None, name=None):
        self.rank = rank_in_group
        self.id = gid
        self.ranks = ranks
        self.process_group = pg
        self._name = name or f"group_{gid}"

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def name(self):
        return self._name

    def is_member(self) -> bool:
        return self.rank >= 0

    def get_group_rank(self, global_rank: int) -> int:
        try:
            return self.ranks.index(global_rank)
        except ValueError:
            return -1

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, rank={self.rank})"


def _register_default_group(pg: ProcessGroup, env) -> Group:
    global _default_group
    g = Group(env.rank, 0, list(range(env.world_size)), pg)
    _default_group = g
    _group_map[0] = g
    return g


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        # lazy single-process default
        from .parallel_env import ParallelEnv, init_parallel_env

        env = ParallelEnv()
        if env.world_size > 1:
            init_parallel_env()
        else:
            _register_default_group(ProcessGroupSingle(0), env)
    return _default_group


def get_group(gid: int = 0) -> Optional[Group]:
    if gid == 0:
        return _get_default_group()
    return _group_map.get(gid)


def get_backend(group=None) -> str:
    g = group or _get_default_group()
    return type(g.process_group).__name__


def new_group(ranks=None, backend=None, timeout=900) -> Group:
    """reference: python/paddle/distributed/collective.py:194."""
    global _next_gid
    default = _get_default_group()
    from .parallel_env import ParallelEnv

    env = ParallelEnv()
    if ranks is None:
        ranks = list(range(env.world_size))
    ranks = sorted(ranks)
    gid = _next_gid
    _next_gid += 1
    my_rank = env.rank
    if my_rank in ranks:
        group_rank = ranks.index(my_rank)
        if len(ranks) <= 1:
            pg = ProcessGroupSingle(gid)
        else:
            from .process_group import new_process_group_impl
            from .store import create_or_get_global_tcp_store

            be = backend or os.environ.get("PADDLE_DIST_BACKEND", "cpu")
            import jax

            if not backend and jax.default_backend() == "tpu":
                be = "xla"
            store = create_or_get_global_tcp_store()
            pg = new_process_group_impl(be, store, group_rank, len(ranks),
                                        gid=gid, group_ranks=ranks)
        g = Group(group_rank, gid, ranks, pg)
    else:
        g = Group(-1, gid, ranks, None)
    _group_map[gid] = g
    return g


def split_group(parent=None, split_sizes=None, backend=None):
    parent = parent or _get_default_group()
    out = []
    off = 0
    for sz in split_sizes:
        out.append(new_group(parent.ranks[off:off + sz], backend))
        off += sz
    return out


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _group_map.clear()
        _default_group = None
        import paddle_tpu.distributed.parallel_env as pe

        pe._initialized = False
        pe._default_group = None
    else:
        _group_map.pop(group.id, None)


def _pg(group) -> ProcessGroup:
    g = group or _get_default_group()
    if g.process_group is None:
        raise RuntimeError(f"rank is not a member of group {g.id}")
    return g.process_group


def _as_tensor(t):
    return t if isinstance(t, Tensor) else Tensor(t)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    return _pg(group).all_reduce(_as_tensor(tensor), op, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    return _pg(group).all_gather(tensor_list, _as_tensor(tensor), sync_op)


def all_gather_object(object_list, obj, group=None):
    import pickle

    import numpy as np

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    g = group or _get_default_group()
    # variable length: publish sizes first
    size = Tensor(np.asarray([payload.size], dtype=np.int64))
    sizes: List[Tensor] = []
    _pg(group).all_gather(sizes, size)
    maxlen = max(int(s.numpy()[0]) for s in sizes)
    padded = np.zeros(maxlen, dtype=np.uint8)
    padded[:payload.size] = payload
    outs: List[Tensor] = []
    _pg(group).all_gather(outs, Tensor(padded))
    object_list.clear()
    for s, o in zip(sizes, outs):
        n = int(s.numpy()[0])
        object_list.append(pickle.loads(o.numpy()[:n].tobytes()))


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    return _pg(group).all_to_all(out_tensor_list,
                                 [_as_tensor(t) for t in in_tensor_list],
                                 sync_op)


alltoall = all_to_all


def broadcast(tensor, src, group=None, sync_op=True):
    return _pg(group).broadcast(_as_tensor(tensor), src, sync_op)


def broadcast_object_list(object_list, src, group=None):
    import pickle

    import numpy as np

    g = group or _get_default_group()
    if src not in g.ranks:
        raise ValueError(
            f"broadcast_object_list: src={src} (global rank) is not a "
            f"member of the group (ranks={g.ranks})")
    src_group_rank = g.get_group_rank(src)
    if g.rank == src_group_rank:
        payload = pickle.dumps(object_list)
        size = Tensor(np.asarray([len(payload)], dtype=np.int64))
    else:
        size = Tensor(np.asarray([0], dtype=np.int64))
    _pg(group).broadcast(size, src)
    n = int(size.numpy()[0])
    if g.rank == src_group_rank:
        buf = Tensor(np.frombuffer(pickle.dumps(object_list), dtype=np.uint8))
    else:
        buf = Tensor(np.zeros(n, dtype=np.uint8))
    _pg(group).broadcast(buf, src)
    if g.rank != src_group_rank:
        loaded = pickle.loads(buf.numpy().tobytes())
        object_list.clear()
        object_list.extend(loaded)


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    return _pg(group).reduce(_as_tensor(tensor), dst, op, sync_op)


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    return _pg(group).reduce_scatter(_as_tensor(tensor),
                                     [_as_tensor(t) for t in tensor_list],
                                     op, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    return _pg(group).scatter(_as_tensor(tensor),
                              [_as_tensor(t) for t in (tensor_list or [])],
                              src, sync_op)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    # src is a GLOBAL rank (paddle convention)
    g0 = group or _get_default_group()
    objs = [None]
    if g0.rank == g0.get_group_rank(src):
        objs = list(in_object_list)
    bc = [objs]
    broadcast_object_list(bc, src, group)
    g = group or _get_default_group()
    out_object_list.clear()
    out_object_list.append(bc[0][g.rank])


def get_group_rank_safe(group):
    g = group or _get_default_group()
    return g.rank


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    return _pg(group).gather(_as_tensor(tensor), gather_list, dst, sync_op)


def send(tensor, dst=0, group=None, sync_op=True):
    return _pg(group).send(_as_tensor(tensor), dst, sync_op)


def recv(tensor, src=0, group=None, sync_op=True):
    return _pg(group).recv(_as_tensor(tensor), src, sync_op)


def isend(tensor, dst, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=None, group=None):
    return recv(tensor, src, group, sync_op=False)


class P2POp:
    """reference: python/paddle/distributed/communication/batch_isend_irecv.py."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks


def barrier(group=None):
    return _pg(group).barrier()


def wait(tensor, group=None, use_calc_stream=True):
    import jax

    if isinstance(tensor, Tensor) and isinstance(tensor._data, jax.Array):
        tensor._data.block_until_ready()


class _StreamNamespace:
    """paddle.distributed.stream.* parity (use_calc_stream variants map to
    the same issue-ordered XLA stream)."""

    @staticmethod
    def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
                   use_calc_stream=False):
        return all_reduce(tensor, op, group, sync_op)

    @staticmethod
    def all_gather(tensor_or_list, tensor, group=None, sync_op=True,
                   use_calc_stream=False):
        if isinstance(tensor_or_list, list):
            return all_gather(tensor_or_list, tensor, group, sync_op)
        # tensor output variant: gather into one stacked tensor
        outs: List[Tensor] = []
        t = all_gather(outs, tensor, group, sync_op)
        import jax.numpy as jnp

        tensor_or_list._data = jnp.concatenate([o._data for o in outs], axis=0)
        return t

    @staticmethod
    def reduce_scatter(tensor, tensor_or_list, op=ReduceOp.SUM, group=None,
                       sync_op=True, use_calc_stream=False):
        if isinstance(tensor_or_list, Tensor):
            g = group or _get_default_group()
            from ..ops.manipulation import split

            tensor_or_list = split(tensor_or_list, g.nranks, axis=0)
        return reduce_scatter(tensor, tensor_or_list, op, group, sync_op)

    @staticmethod
    def broadcast(tensor, src, group=None, sync_op=True,
                  use_calc_stream=False):
        return broadcast(tensor, src, group, sync_op)

    @staticmethod
    def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
        return send(tensor, dst, group, sync_op)

    @staticmethod
    def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
        return recv(tensor, src, group, sync_op)

    @staticmethod
    def alltoall(out_list, in_list, group=None, sync_op=True,
                 use_calc_stream=False):
        return all_to_all(out_list, in_list, group, sync_op)


stream = _StreamNamespace()
