"""Program-level pass tier (reference: python/paddle/distributed/passes/
— pass_base.py PassBase/register_pass/new_pass/PassManager, the
auto_parallel_{amp,recompute}.py program passes and
pipeline_scheduler_pass/).

TPU-native: a "program" is the captured op-DAG (static/graph.py OpNode
closures). A pass rewrites that DAG — cloning nodes through a transform
with memoization — and returns new fetch handles; the Executor then
compiles the transformed program exactly like the original. This is the
program-rewrite tier the reference implements over PIR; XLA still does
instruction-level optimization below it.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ...core.tensor import Tensor
from ...static import graph as _g

__all__ = ["PassBase", "PassContext", "PassManager", "register_pass",
           "new_pass", "rewrite_program"]

_PASS_REGISTRY: Dict[str, type] = {}


def register_pass(name: str):
    """reference: pass_base.py register_pass decorator."""

    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name: str, pass_attrs: Optional[dict] = None):
    """reference: pass_base.py new_pass."""
    if name not in _PASS_REGISTRY:
        raise ValueError(
            f"unknown pass {name!r}; registered: "
            f"{sorted(_PASS_REGISTRY)}")
    p = _PASS_REGISTRY[name]()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassContext:
    """reference: pass_base.py PassContext."""

    def __init__(self):
        self.attrs = {}


class PassBase:
    """A program pass: apply(fetches) -> new fetches over a rewritten
    DAG (reference pass_base.py PassBase._apply_single_impl)."""

    name = "base"

    def __init__(self):
        self._attrs = {}

    def set_attr(self, k, v):
        self._attrs[k] = v
        return self

    def get_attr(self, k, default=None):
        return self._attrs.get(k, default)

    def _check_self(self):
        return True

    def apply(self, fetches: List[Tensor],
              context: Optional[PassContext] = None) -> List[Tensor]:
        raise NotImplementedError


class PassManager:
    """reference: pass_base.py PassManager — ordered composition."""

    def __init__(self, passes: List[PassBase]):
        self.passes = list(passes)
        self.context = PassContext()

    def apply(self, fetches: List[Tensor]) -> List[Tensor]:
        for p in self.passes:
            fetches = p.apply(fetches, self.context)
        return fetches

    @property
    def names(self):
        return [p.name for p in self.passes]


# ------------------------------------------------------------ DAG rewrite
def rewrite_program(fetches: List[Tensor],
                    node_transform: Callable) -> List[Tensor]:
    """Clone the op-DAG under ``fetches``, passing every OpNode through
    ``node_transform(node, new_parents) -> OpNode`` (memoized, so shared
    subgraphs stay shared). Feed leaves / parameters pass through."""
    memo: Dict[int, _g.OpNode] = {}

    def clone(node):
        if not isinstance(node, _g.OpNode):
            return node
        if id(node) in memo:
            return memo[id(node)]
        new_parents = []
        for p in node.parents:
            if isinstance(p, tuple):
                new_parents.append((clone(p[0]), p[1]))
            else:
                new_parents.append(p)
        new_node = node_transform(node, new_parents)
        memo[id(node)] = new_node
        return new_node

    out = []
    for t in fetches:
        if not _g.is_symbolic(t):
            out.append(t)
            continue
        node, idx = t._sym_node
        if isinstance(node, _g.FeedLeaf):
            out.append(t)
            continue
        out.append(_g.make_symbolic(clone(node), idx,
                                    name=getattr(t, "name", None)))
    return out


def _identity_clone(node, new_parents):
    return _g.OpNode(node.fn, new_parents, node.out_avals, node.name,
                     node.single, attrs=node.attrs)


# --------------------------------------------------------------- amp pass
# op-name sets mirror amp/__init__.py O1 lists (matmul-family compute in
# bf16; numerically-sensitive reductions stay f32)
_AMP_WHITE = {"matmul", "bmm", "mm", "conv1d", "conv2d", "conv3d",
              "linear", "einsum", "flash_attention"}
_AMP_BLACK = {"softmax", "log_softmax", "cross_entropy", "layer_norm",
              "batch_norm", "rms_norm", "logsumexp", "mean", "sum",
              "exp", "log", "norm", "cumsum"}


@register_pass("auto_parallel_amp")
@register_pass("auto_parallel_fp16")
class AMPPass(PassBase):
    """Cast white-list op inputs to the amp dtype at the PROGRAM level
    (reference: distributed/passes/auto_parallel_amp.py). attrs:
    dtype ('bfloat16'|'float16')."""

    def apply(self, fetches, context=None):
        import jax.numpy as jnp

        from ...core.dtype import to_jax_dtype

        amp_dt = to_jax_dtype(self.get_attr("dtype", "bfloat16"))

        def transform(node, new_parents):
            if node.name not in _AMP_WHITE:
                return _identity_clone(node, new_parents)
            fn = node.fn

            def amp_fn(*vals, _fn=fn):
                cast = [v.astype(amp_dt)
                        if hasattr(v, "dtype")
                        and jnp.issubdtype(v.dtype, jnp.floating) else v
                        for v in vals]
                out = _fn(*cast)
                if isinstance(out, tuple):
                    return tuple(o.astype(jnp.float32) for o in out)
                return out.astype(jnp.float32)

            # recompute output avals under the cast
            import jax

            avals_in = _avals_of(new_parents)
            out = jax.eval_shape(amp_fn, *avals_in)
            outs = (out,) if not isinstance(out, (tuple, list)) \
                else tuple(out)
            return _g.OpNode(amp_fn, new_parents, list(outs), node.name,
                             node.single, attrs=node.attrs)

        return rewrite_program(fetches, transform)


# ---------------------------------------------------------- recompute pass
@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Mark op families for rematerialization (reference:
    distributed/passes/auto_parallel_recompute.py): wrapped ops save
    nothing for backward — jax.checkpoint recomputes them. attrs:
    op_names (set, default matmul-family + activations)."""

    DEFAULT = {"matmul", "bmm", "mm", "linear", "einsum", "gelu", "relu",
               "tanh", "softmax", "flash_attention"}

    def apply(self, fetches, context=None):
        import jax

        names = set(self.get_attr("op_names", self.DEFAULT))

        def transform(node, new_parents):
            if node.name not in names:
                return _identity_clone(node, new_parents)
            fn = jax.checkpoint(node.fn)
            return _g.OpNode(fn, new_parents, node.out_avals, node.name,
                             node.single, attrs=node.attrs)

        return rewrite_program(fetches, transform)


def _avals_of(parents):
    import jax

    avals = []
    for p in parents:
        if isinstance(p, tuple):
            avals.append(p[0].out_avals[p[1]])
        elif isinstance(p, _g.FeedLeaf):
            avals.append(p.aval)
        elif isinstance(p, Tensor):
            avals.append(jax.ShapeDtypeStruct(tuple(p._data.shape),
                                              p._data.dtype))
        else:
            avals.append(p)
    return avals


from .pipeline_scheduler_pass import (  # noqa: E402,F401
    Pipeline1F1BPass,
    PipelineFThenBPass,
    StagedProgram,
)

__all__ += ["StagedProgram", "PipelineFThenBPass", "Pipeline1F1BPass"]
