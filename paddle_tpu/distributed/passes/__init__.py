"""Program-level pass tier (reference: python/paddle/distributed/passes/
— pass_base.py PassBase/register_pass/new_pass/PassManager, the
auto_parallel_{amp,recompute}.py program passes and
pipeline_scheduler_pass/).

TPU-native: a "program" is the captured op-DAG (static/graph.py OpNode
closures). A pass rewrites that DAG — cloning nodes through a transform
with memoization — and returns new fetch handles; the Executor then
compiles the transformed program exactly like the original. This is the
program-rewrite tier the reference implements over PIR; XLA still does
instruction-level optimization below it.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ...core.tensor import Tensor
from ...static import graph as _g

__all__ = ["PassBase", "PassContext", "PassManager", "register_pass",
           "new_pass", "rewrite_program"]

_PASS_REGISTRY: Dict[str, type] = {}


def register_pass(name: str):
    """reference: pass_base.py register_pass decorator."""

    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name: str, pass_attrs: Optional[dict] = None):
    """reference: pass_base.py new_pass."""
    if name not in _PASS_REGISTRY:
        raise ValueError(
            f"unknown pass {name!r}; registered: "
            f"{sorted(_PASS_REGISTRY)}")
    p = _PASS_REGISTRY[name]()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassContext:
    """reference: pass_base.py PassContext."""

    def __init__(self):
        self.attrs = {}


class PassBase:
    """A program pass: apply(fetches) -> new fetches over a rewritten
    DAG (reference pass_base.py PassBase._apply_single_impl)."""

    name = "base"

    def __init__(self):
        self._attrs = {}

    def set_attr(self, k, v):
        self._attrs[k] = v
        return self

    def get_attr(self, k, default=None):
        return self._attrs.get(k, default)

    def _check_self(self):
        return True

    def configure(self, context: PassContext) -> None:
        """Record this pass's strategy interpretation in the context.
        The Engine composes its TrainStep from the configured context —
        the pass, not the Engine, owns what a strategy knob means
        (reference analog: passes writing program attrs / dist_attrs
        that the executor later consumes)."""

    def apply(self, fetches: List[Tensor],
              context: Optional[PassContext] = None) -> List[Tensor]:
        raise NotImplementedError


class PassManager:
    """reference: pass_base.py PassManager — ordered composition."""

    def __init__(self, passes: List[PassBase]):
        self.passes = list(passes)
        self.context = PassContext()

    def apply(self, fetches: List[Tensor]) -> List[Tensor]:
        for p in self.passes:
            fetches = p.apply(fetches, self.context)
        return fetches

    def configure(self) -> PassContext:
        """Run every pass's configure() in order; returns the context
        the step builder consumes."""
        for p in self.passes:
            p.configure(self.context)
        return self.context

    @property
    def names(self):
        return [p.name for p in self.passes]


# ------------------------------------------------------------ DAG rewrite
def rewrite_program(fetches: List[Tensor],
                    node_transform: Callable) -> List[Tensor]:
    """Clone the op-DAG under ``fetches``, passing every OpNode through
    ``node_transform(node, new_parents) -> OpNode`` (memoized, so shared
    subgraphs stay shared). Feed leaves / parameters pass through."""
    memo: Dict[int, _g.OpNode] = {}

    def clone(node):
        if not isinstance(node, _g.OpNode):
            return node
        if id(node) in memo:
            return memo[id(node)]
        new_parents = []
        for p in node.parents:
            if isinstance(p, tuple):
                new_parents.append((clone(p[0]), p[1]))
            else:
                new_parents.append(p)
        new_node = node_transform(node, new_parents)
        memo[id(node)] = new_node
        return new_node

    out = []
    for t in fetches:
        if not _g.is_symbolic(t):
            out.append(t)
            continue
        node, idx = t._sym_node
        if isinstance(node, _g.FeedLeaf):
            out.append(t)
            continue
        out.append(_g.make_symbolic(clone(node), idx,
                                    name=getattr(t, "name", None)))
    return out


def _identity_clone(node, new_parents):
    return _g.OpNode(node.fn, new_parents, node.out_avals, node.name,
                     node.single, attrs=node.attrs)


# --------------------------------------------------------------- amp pass
@register_pass("auto_parallel_amp")
@register_pass("auto_parallel_fp16")
class AMPPass(PassBase):
    """Cast white-list op inputs to the amp dtype at the PROGRAM level
    (reference: distributed/passes/auto_parallel_amp.py). The op lists
    are the SAME objects the eager auto_cast tier uses
    (amp/__init__.py WHITE_LIST/BLACK_LIST mirroring
    python/paddle/amp/amp_lists.py) — a program gets exactly the amp
    treatment its eager twin would. attrs: dtype
    ('bfloat16'|'float16'), custom_white_list, custom_black_list."""

    def _lists(self):
        from ...amp import effective_lists

        return effective_lists(self.get_attr("custom_white_list", ()),
                               self.get_attr("custom_black_list", ()))

    def configure(self, context):
        context.attrs["amp"] = {
            "enable": True,
            "dtype": self.get_attr("dtype", "bfloat16"),
            "level": self.get_attr("level", "O2"),
            "custom_white_list": set(
                self.get_attr("custom_white_list", ())),
            "custom_black_list": set(
                self.get_attr("custom_black_list", ())),
        }

    def apply(self, fetches, context=None):
        import jax.numpy as jnp

        from ...core.dtype import to_jax_dtype

        amp_dt = to_jax_dtype(self.get_attr("dtype", "bfloat16"))
        white, black = self._lists()

        def transform(node, new_parents):
            if node.name not in white and node.name not in black:
                return _identity_clone(node, new_parents)
            fn = node.fn
            # white ops compute in the amp dtype; black ops are forced
            # UP to f32 (same contract as eager auto_cast O1 — e.g. a
            # softmax fed bf16 activations runs its reduction in f32)
            in_dt = amp_dt if node.name in white else jnp.float32

            def amp_fn(*vals, _fn=fn, _dt=in_dt):
                cast = [v.astype(_dt)
                        if hasattr(v, "dtype")
                        and jnp.issubdtype(v.dtype, jnp.floating) else v
                        for v in vals]
                out = _fn(*cast)
                if isinstance(out, tuple):
                    return tuple(o.astype(jnp.float32) for o in out)
                return out.astype(jnp.float32)

            # recompute output avals under the cast
            import jax

            avals_in = _avals_of(new_parents)
            out = jax.eval_shape(amp_fn, *avals_in)
            outs = (out,) if not isinstance(out, (tuple, list)) \
                else tuple(out)
            return _g.OpNode(amp_fn, new_parents, list(outs), node.name,
                             node.single, attrs=node.attrs)

        return rewrite_program(fetches, transform)


# ---------------------------------------------------------- recompute pass
@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Mark op families for rematerialization (reference:
    distributed/passes/auto_parallel_recompute.py): wrapped ops save
    nothing for backward — jax.checkpoint recomputes them. attrs:
    op_names (set, default matmul-family + activations)."""

    DEFAULT = {"matmul", "bmm", "mm", "linear", "einsum", "gelu", "relu",
               "tanh", "softmax", "flash_attention"}

    def configure(self, context):
        context.attrs["recompute"] = True

    def apply(self, fetches, context=None):
        import jax

        names = set(self.get_attr("op_names", self.DEFAULT))

        def transform(node, new_parents):
            if node.name not in names:
                return _identity_clone(node, new_parents)
            fn = jax.checkpoint(node.fn)
            return _g.OpNode(fn, new_parents, node.out_avals, node.name,
                             node.single, attrs=node.attrs)

        return rewrite_program(fetches, transform)


# ----------------------------------------------------------- sharding pass
@register_pass("auto_parallel_sharding")
class ShardingPass(PassBase):
    """ZeRO-style sharding as a program pass (reference:
    distributed/passes/auto_parallel_sharding.py — there the pass
    rewrites the program to slice optimizer states/params across dp;
    here the program-level half annotates every PARAMETER leaf with a
    sharding constraint so GSPMD lays it out sharded, and configure()
    records the stage/axis the TrainStep builder uses for optimizer-
    state placement). attrs: stage (1|2|3), axis ('dp'), mesh (a
    jax Mesh for the DAG rewrite; without one apply() is the identity
    since a constraint needs a mesh to bind to)."""

    def configure(self, context):
        stage = int(self.get_attr("stage", 1))
        context.attrs["sharding_stage"] = stage
        context.attrs["sharding_axis"] = self.get_attr("axis", "dp")
        if stage >= 2:
            context.attrs["fsdp_axis"] = self.get_attr("axis", "dp")

    def apply(self, fetches, context=None):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.get_attr("mesh")
        axis = self.get_attr("axis", "dp")
        if mesh is None or axis not in getattr(mesh, "axis_names", ()):
            return fetches
        nshard = mesh.shape[axis]

        def shard_spec(aval):
            # first dim divisible by the axis size gets the shard; a
            # param with no divisible dim stays replicated (exactly what
            # GSPMD would do with an unsatisfiable annotation, minus the
            # warning noise)
            for i, d in enumerate(aval.shape):
                if d % nshard == 0 and d >= nshard:
                    return P(*([None] * i + [axis]))
            return None

        def transform(node, new_parents):
            wrapped = []
            changed = False
            for p in new_parents:
                if isinstance(p, Tensor) and getattr(p, "trainable",
                                                     False):
                    spec = shard_spec(p._data)
                    if spec is not None:
                        sh = NamedSharding(mesh, spec)
                        leaf = _g.OpNode(
                            (lambda v, _s=sh:
                             jax.lax.with_sharding_constraint(v, _s)),
                            [p],
                            [jax.ShapeDtypeStruct(tuple(p._data.shape),
                                                  p._data.dtype)],
                            "shard_param", True)
                        wrapped.append((leaf, 0))
                        changed = True
                        continue
                wrapped.append(p)
            if not changed:
                return _identity_clone(node, new_parents)
            return _g.OpNode(node.fn, wrapped, node.out_avals, node.name,
                             node.single, attrs=node.attrs)

        return rewrite_program(fetches, transform)


# ------------------------------------------------------ gradient merge pass
@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(PassBase):
    """Gradient accumulation over k micro-steps (reference:
    distributed/passes/auto_parallel_gradient_merge.py — there the pass
    inserts gradient buffers + a mod-k conditional optimizer update into
    the program; here the captured forward DAG is untouched and
    configure() hands k to the TrainStep builder, whose lax.scan over
    micro-batches IS the merged update — one compiled region instead of
    program-inserted buffer ops). attrs: k_steps, avg."""

    def configure(self, context):
        context.attrs["accumulate_steps"] = max(
            int(self.get_attr("k_steps", 1)), 1)
        context.attrs["gradient_merge_avg"] = bool(
            self.get_attr("avg", True))

    def apply(self, fetches, context=None):
        return fetches


def _avals_of(parents):
    import jax

    avals = []
    for p in parents:
        if isinstance(p, tuple):
            avals.append(p[0].out_avals[p[1]])
        elif isinstance(p, _g.FeedLeaf):
            avals.append(p.aval)
        elif isinstance(p, Tensor):
            avals.append(jax.ShapeDtypeStruct(tuple(p._data.shape),
                                              p._data.dtype))
        else:
            avals.append(p)
    return avals


from .pipeline_scheduler_pass import (  # noqa: E402,F401
    Pipeline1F1BPass,
    PipelineFThenBPass,
    StagedProgram,
)

__all__ += ["StagedProgram", "PipelineFThenBPass", "Pipeline1F1BPass",
            "AMPPass", "RecomputePass", "ShardingPass",
            "GradientMergePass"]
