"""Static pipeline schedule passes (reference: python/paddle/distributed/
passes/pipeline_scheduler_pass/{pipeline_fthenb,pipeline_1f1b}.py over
pipeline_pass_base.py).

The reference pass reorders a stage-partitioned static program's jobs
into an execution plan ("job list") the executor then runs. Here the
same structure is explicit: a :class:`StagedProgram` holds per-stage pure
functions + parameters (each stage optionally pinned to its own device),
and a schedule pass emits the ordered job list [("F"|"B", stage,
micro_batch)] and an executor that runs it with jax.vjp — forward jobs
stash activations/vjp closures, backward jobs consume them and
accumulate parameter grads. FThenB and 1F1B produce bit-identical grads;
they differ in when backward jobs run (1F1B drains activations early —
the memory behavior the schedule exists for).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["StagedProgram", "PipelineFThenBPass", "Pipeline1F1BPass"]


class StagedProgram:
    """A pipeline-partitioned program.

    stages: list of pure fns ``stage_fn(params, x) -> y``;
    params:  per-stage parameter pytrees;
    loss_fn: ``loss_fn(y_last, label_mb) -> scalar`` (mean over the
             micro-batch; grads are averaged over micro-batches);
    devices: optional per-stage jax devices — stage params/compute pinned
             there (the multi-chip placement the schedule models).
    """

    def __init__(self, stages: Sequence[Callable], params: Sequence,
                 loss_fn: Callable, devices: Optional[Sequence] = None):
        assert len(stages) == len(params)
        self.stages = list(stages)
        self.loss_fn = loss_fn
        self.devices = list(devices) if devices is not None else None
        if self.devices is not None:
            assert len(self.devices) == len(self.stages)
            params = [jax.device_put(p, d)
                      for p, d in zip(params, self.devices)]
        self.params = list(params)

    @property
    def num_stages(self):
        return len(self.stages)


class _PipelineSchedulePassBase:
    """Shared executor: subclasses emit the job list (reference
    pipeline_pass_base.py _create_job_list)."""

    name = "pipeline_scheduler_base"

    def _job_list(self, n_stages: int, n_micro: int) \
            -> List[Tuple[str, int, int]]:
        raise NotImplementedError

    def apply(self, program: StagedProgram, micro_batches, labels):
        """Run the schedule. Returns (mean loss, per-stage grad pytrees,
        job list actually executed)."""
        S = program.num_stages
        M = len(micro_batches)
        jobs = self._job_list(S, M)
        self._validate(jobs, S, M)

        acts = {}       # (stage, mb) -> stage input
        vjps = {}       # (stage, mb) -> vjp closure
        outs = {}       # (stage, mb) -> stage output
        grads = [None] * S
        cots = {}       # (stage, mb) -> cotangent flowing into stage
        losses = []

        def put(stage, x):
            if program.devices is not None:
                return jax.device_put(x, program.devices[stage])
            return x

        for kind, s, m in jobs:
            if kind == "F":
                x = put(s, micro_batches[m] if s == 0 else outs[(s - 1, m)])
                acts[(s, m)] = x
                y, vjp = jax.vjp(program.stages[s], program.params[s], x)
                vjps[(s, m)] = vjp
                outs[(s, m)] = y
                if s == S - 1:
                    loss, lvjp = jax.vjp(
                        lambda yy: program.loss_fn(yy, labels[m]), y)
                    losses.append(loss)
                    (cot,) = lvjp(jnp.ones_like(loss) / M)
                    cots[(s, m)] = cot
            else:  # "B"
                cot = put(s, cots.pop((s, m)))
                g_param, g_x = vjps.pop((s, m))(cot)
                grads[s] = g_param if grads[s] is None else jax.tree.map(
                    jnp.add, grads[s], g_param)
                if s > 0:
                    cots[(s - 1, m)] = g_x
                # activations for this (stage, mb) are now dead — the
                # point of 1F1B's early drains
                acts.pop((s, m), None)
                outs.pop((s, m), None)
        mean_loss = sum(losses) / M
        return mean_loss, grads, jobs

    @staticmethod
    def _validate(jobs, S, M):
        seen = set()
        for kind, s, m in jobs:
            if kind == "F":
                assert s == 0 or ("F", s - 1, m) in seen, \
                    f"F{s},{m} before its upstream forward"
            else:
                assert ("F", s, m) in seen, f"B{s},{m} before F{s},{m}"
                assert s == S - 1 or ("B", s + 1, m) in seen, \
                    f"B{s},{m} before its downstream backward"
            seen.add((kind, s, m))
        assert len(seen) == 2 * S * M, "schedule missed jobs"


class PipelineFThenBPass(_PipelineSchedulePassBase):
    """All forwards, then all backwards (reference:
    pipeline_scheduler_pass/pipeline_fthenb.py)."""

    name = "pipeline_scheduler_FThenB"

    def _job_list(self, S, M):
        jobs = [("F", s, m) for m in range(M) for s in range(S)]
        jobs += [("B", s, m) for m in range(M)
                 for s in range(S - 1, -1, -1)]
        return jobs


class Pipeline1F1BPass(_PipelineSchedulePassBase):
    """Warmup / steady 1F1B / drain (reference:
    pipeline_scheduler_pass/pipeline_1f1b.py:39). Job order follows the
    last stage's view: after its warmup, each forward is immediately
    followed by a backward, bounding live activations per stage at
    (S - stage) micro-batches instead of M."""

    name = "pipeline_scheduler_1F1B"

    def _job_list(self, S, M):
        # simulate the classic per-stage 1F1B clock: at every tick each
        # stage runs its next job; ordering jobs by completion tick gives
        # a valid global order with the 1F1B interleaving property.
        jobs = []
        done_f = [0] * S   # forwards issued per stage
        done_b = [0] * S   # backwards issued per stage
        bwd_ready = [set() for _ in range(S)]
        # iterate ticks until all B jobs issued
        while sum(done_b) < S * M:
            progressed = False
            for s in range(S):
                # prefer backward when available past warmup (1F1B rule)
                can_b = done_b[s] < M and done_b[s] in bwd_ready[s]
                can_f = (done_f[s] < M
                         and (s == 0 or done_f[s] < done_f[s - 1]))
                steady = done_f[s] - done_b[s] >= min(S - s, M)
                if can_b and (steady or not can_f):
                    m = done_b[s]
                    jobs.append(("B", s, m))
                    done_b[s] += 1
                    if s > 0:
                        bwd_ready[s - 1].add(m)
                    progressed = True
                elif can_f:
                    m = done_f[s]
                    jobs.append(("F", s, m))
                    done_f[s] += 1
                    if s == S - 1:
                        bwd_ready[s].add(m)
                    progressed = True
            assert progressed, "1F1B schedule deadlocked"
        return jobs
