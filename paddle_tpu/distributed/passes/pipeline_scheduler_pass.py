"""Static pipeline schedule passes (reference: python/paddle/distributed/
passes/pipeline_scheduler_pass/{pipeline_fthenb,pipeline_1f1b,
pipeline_vpp,pipeline_zero_bubble}.py over pipeline_pass_base.py).

The reference pass reorders a stage-partitioned static program's jobs
into an execution plan ("job list") the executor then runs. Here the
same structure is explicit: a :class:`StagedProgram` holds per-stage pure
functions + parameters (each stage optionally pinned to its own device),
and a schedule pass emits the ordered job list [("F"|"B", stage,
micro_batch)] and an executor that runs it with jax.vjp — forward jobs
stash activations/vjp closures, backward jobs consume them and
accumulate parameter grads. FThenB and 1F1B produce bit-identical grads;
they differ in when backward jobs run (1F1B drains activations early —
the memory behavior the schedule exists for).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["StagedProgram", "PipelineFThenBPass", "Pipeline1F1BPass",
           "PipelineVPPPass", "PipelineZeroBubblePass"]


class StagedProgram:
    """A pipeline-partitioned program.

    stages: list of pure fns ``stage_fn(params, x) -> y``;
    params:  per-stage parameter pytrees;
    loss_fn: ``loss_fn(y_last, label_mb) -> scalar`` (mean over the
             micro-batch; grads are averaged over micro-batches);
    devices: optional per-stage jax devices — stage params/compute pinned
             there (the multi-chip placement the schedule models);
    last_takes_label: the final stage computes the loss itself as
             ``stage_fn(params, x, label) -> scalar`` (used when the
             program partitioner folds a parameterized loss tail into
             the last stage so its params receive grads).
    """

    def __init__(self, stages: Sequence[Callable], params: Sequence,
                 loss_fn: Optional[Callable], devices: Optional[Sequence]
                 = None, last_takes_label: bool = False):
        assert len(stages) == len(params)
        self.stages = list(stages)
        self.loss_fn = loss_fn
        self.last_takes_label = last_takes_label
        if not last_takes_label:
            assert loss_fn is not None
        self.devices = list(devices) if devices is not None else None
        if self.devices is not None:
            assert len(self.devices) == len(self.stages)
            params = [jax.device_put(p, d)
                      for p, d in zip(params, self.devices)]
        self.params = list(params)

    @property
    def num_stages(self):
        return len(self.stages)


class _PipelineSchedulePassBase:
    """Shared executor: subclasses emit the job list (reference
    pipeline_pass_base.py _create_job_list)."""

    name = "pipeline_scheduler_base"
    emits_w = False   # ZB-style passes split backward into B + W jobs

    def _job_list(self, n_stages: int, n_micro: int) \
            -> List[Tuple[str, int, int]]:
        raise NotImplementedError

    def apply(self, program: StagedProgram, micro_batches, labels):
        """Run the schedule. Returns (mean loss, per-stage grad pytrees,
        job list actually executed)."""
        S = program.num_stages
        M = len(micro_batches)
        jobs = self._job_list(S, M)
        self._validate(jobs, S, M, with_w=self.emits_w)

        acts = {}       # (stage, mb) -> stage input
        vjps = {}       # (stage, mb) -> vjp closure
        outs = {}       # (stage, mb) -> stage output
        grads = [None] * S
        cots = {}       # (stage, mb) -> cotangent flowing into stage
        pending_w = {}  # (stage, mb) -> deferred weight grads (ZB)
        losses = []

        def put(stage, x):
            if program.devices is not None:
                return jax.device_put(x, program.devices[stage])
            return x

        def accum(s, g_param):
            grads[s] = g_param if grads[s] is None else jax.tree.map(
                jnp.add, grads[s], g_param)

        for kind, s, m in jobs:
            if kind == "F":
                x = put(s, micro_batches[m] if s == 0 else outs[(s - 1, m)])
                acts[(s, m)] = x
                if s == S - 1 and program.last_takes_label:
                    loss, vjp = jax.vjp(
                        lambda pp, xx: program.stages[s](pp, xx,
                                                         labels[m]),
                        program.params[s], x)
                    vjps[(s, m)] = vjp
                    losses.append(loss)
                    cots[(s, m)] = jnp.ones_like(loss) / M
                    continue
                y, vjp = jax.vjp(program.stages[s], program.params[s], x)
                vjps[(s, m)] = vjp
                outs[(s, m)] = y
                if s == S - 1:
                    loss, lvjp = jax.vjp(
                        lambda yy: program.loss_fn(yy, labels[m]), y)
                    losses.append(loss)
                    (cot,) = lvjp(jnp.ones_like(loss) / M)
                    cots[(s, m)] = cot
            elif kind == "B":
                cot = put(s, cots.pop((s, m)))
                g_param, g_x = vjps.pop((s, m))(cot)
                if self.emits_w:
                    # ZB: the input grad ships upstream NOW; the weight
                    # grad waits for this micro's W job (filling the
                    # bubble), mirroring PipelineParallelZeroBubble
                    pending_w[(s, m)] = g_param
                else:
                    accum(s, g_param)
                if s > 0:
                    cots[(s - 1, m)] = g_x
                # activations for this (stage, mb) are now dead — the
                # point of 1F1B's early drains
                acts.pop((s, m), None)
                outs.pop((s, m), None)
            else:  # "W": deferred weight-grad accumulation
                accum(s, pending_w.pop((s, m)))
        assert not pending_w, "W jobs missed pending weight grads"
        mean_loss = sum(losses) / M
        return mean_loss, grads, jobs

    @staticmethod
    def _validate(jobs, S, M, with_w=False):
        seen = set()
        for kind, s, m in jobs:
            if kind == "F":
                assert s == 0 or ("F", s - 1, m) in seen, \
                    f"F{s},{m} before its upstream forward"
            elif kind == "B":
                assert ("F", s, m) in seen, f"B{s},{m} before F{s},{m}"
                assert s == S - 1 or ("B", s + 1, m) in seen, \
                    f"B{s},{m} before its downstream backward"
            else:
                assert with_w, "W job from a non-ZB schedule"
                assert ("B", s, m) in seen, f"W{s},{m} before B{s},{m}"
            seen.add((kind, s, m))
        kinds = 3 if with_w else 2
        assert len(seen) == kinds * S * M, "schedule missed jobs"


class PipelineFThenBPass(_PipelineSchedulePassBase):
    """All forwards, then all backwards (reference:
    pipeline_scheduler_pass/pipeline_fthenb.py)."""

    name = "pipeline_scheduler_FThenB"

    def _job_list(self, S, M):
        jobs = [("F", s, m) for m in range(M) for s in range(S)]
        jobs += [("B", s, m) for m in range(M)
                 for s in range(S - 1, -1, -1)]
        return jobs


class Pipeline1F1BPass(_PipelineSchedulePassBase):
    """Warmup / steady 1F1B / drain (reference:
    pipeline_scheduler_pass/pipeline_1f1b.py:39). Job order follows the
    last stage's view: after its warmup, each forward is immediately
    followed by a backward, bounding live activations per stage at
    (S - stage) micro-batches instead of M."""

    name = "pipeline_scheduler_1F1B"

    def _job_list(self, S, M):  # noqa: C901
        return self._one_f_one_b(S, M)

    @staticmethod
    def _one_f_one_b(S, M):
        # simulate the classic per-stage 1F1B clock: at every tick each
        # stage runs its next job; ordering jobs by completion tick gives
        # a valid global order with the 1F1B interleaving property.
        jobs = []
        done_f = [0] * S   # forwards issued per stage
        done_b = [0] * S   # backwards issued per stage
        bwd_ready = [set() for _ in range(S)]
        # iterate ticks until all B jobs issued
        while sum(done_b) < S * M:
            progressed = False
            for s in range(S):
                # prefer backward when available past warmup (1F1B rule)
                can_b = done_b[s] < M and done_b[s] in bwd_ready[s]
                can_f = (done_f[s] < M
                         and (s == 0 or done_f[s] < done_f[s - 1]))
                steady = done_f[s] - done_b[s] >= min(S - s, M)
                if can_b and (steady or not can_f):
                    m = done_b[s]
                    jobs.append(("B", s, m))
                    done_b[s] += 1
                    if s > 0:
                        bwd_ready[s - 1].add(m)
                    progressed = True
                elif can_f:
                    m = done_f[s]
                    jobs.append(("F", s, m))
                    done_f[s] += 1
                    if s == S - 1:
                        bwd_ready[s].add(m)
                    progressed = True
            assert progressed, "1F1B schedule deadlocked"
        return jobs


class PipelineVPPPass(_PipelineSchedulePassBase):
    """Interleaved virtual pipeline (VPP, reference:
    pipeline_scheduler_pass/pipeline_vpp.py; Megatron interleaved
    schedule). The StagedProgram holds ``num_stages * num_virtual``
    VIRTUAL stages; virtual stage ``sv`` lives on physical stage
    ``sv % num_stages`` (the interleaved chunk assignment of
    pp_layers.py _interleave). Each physical rank runs the Megatron
    per-rank order (deep warmup covering every chunk, then 1F1B, then
    drain); the global job list is their dependency-respecting merge.
    """

    name = "pipeline_scheduler_VPP"

    def __init__(self, num_stages: int, num_virtual: int):
        self.num_stages = int(num_stages)
        self.num_virtual = int(num_virtual)

    def _job_list(self, S, M):
        P, v = self.num_stages, self.num_virtual
        assert S == P * v, \
            f"StagedProgram has {S} virtual stages, want {P}*{v}"
        assert M % P == 0, "VPP needs micro-batches divisible by pp degree"

        def fwd_seq(rank):
            # i-th forward this rank runs: cycle chunks in groups of P
            # micro-batches (Megatron get_model_chunk_id)
            seq = []
            for i in range(M * v):
                group, within = divmod(i, P * v)
                chunk, pos = divmod(within, P)
                seq.append((chunk * P + rank, group * P + pos))
            return seq

        def bwd_seq(rank):
            seq = []
            for i in range(M * v):
                group, within = divmod(i, P * v)
                chunk, pos = divmod(within, P)
                seq.append(((v - 1 - chunk) * P + rank, group * P + pos))
            return seq

        local = []
        for r in range(P):
            warmup = min((P - r - 1) * 2 + (v - 1) * P, M * v)
            f, b = fwd_seq(r), bwd_seq(r)
            seq = [("F",) + f[i] for i in range(warmup)]
            fi, bi = warmup, 0
            while fi < len(f):
                seq.append(("F",) + f[fi])
                fi += 1
                seq.append(("B",) + b[bi])
                bi += 1
            while bi < len(b):
                seq.append(("B",) + b[bi])
                bi += 1
            local.append(seq)

        # dependency-respecting merge of the per-rank orders
        jobs, issued = [], set()
        ptr = [0] * P
        while any(ptr[r] < len(local[r]) for r in range(P)):
            progressed = False
            for r in range(P):
                while ptr[r] < len(local[r]):
                    kind, sv, m = local[r][ptr[r]]
                    if kind == "F":
                        ready = sv == 0 or ("F", sv - 1, m) in issued
                    else:
                        ready = ("F", sv, m) in issued and (
                            sv == S - 1 or ("B", sv + 1, m) in issued)
                    if not ready:
                        break
                    jobs.append((kind, sv, m))
                    issued.add((kind, sv, m))
                    ptr[r] += 1
                    progressed = True
            assert progressed, "VPP merge deadlocked"
        return jobs


class PipelineZeroBubblePass(Pipeline1F1BPass):
    """ZB-H1 (reference: pipeline_scheduler_pass/pipeline_zero_bubble.py:62).
    The 1F1B order, with each micro's weight-grad accumulation split out
    as a W job deferred into the cooldown bubble — identical job-order
    policy to the dygraph PipelineParallelZeroBubble (W fires once a
    stage is more than ``S - stage`` backwards ahead of its W count,
    remaining W fill the drain)."""

    name = "pipeline_scheduler_ZBH1"
    emits_w = True

    def _job_list(self, S, M):
        base = self._one_f_one_b(S, M)
        jobs = []
        done_b = [0] * S
        done_w = [0] * S
        for j in base:
            jobs.append(j)
            if j[0] == "B":
                s = j[1]
                done_b[s] += 1
                while done_b[s] - done_w[s] > S - s:
                    jobs.append(("W", s, done_w[s]))
                    done_w[s] += 1
        for s in range(S):
            while done_w[s] < M:
                jobs.append(("W", s, done_w[s]))
                done_w[s] += 1
        return jobs
