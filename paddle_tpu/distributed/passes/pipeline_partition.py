"""Automatic pipeline stage partitioning (VERDICT r4 missing #1;
reference: python/paddle/distributed/auto_parallel/static/engine.py:655
``_parallel_pir`` composes the pipeline schedule pass into the plan;
pp_layers.py segmentation feeds it on the dygraph side).

Two partition sources produce a :class:`StagedProgram` the schedule
passes (pipeline_scheduler_pass.py) execute:

* :func:`stage_program_from_layers` — segments a sequential model
  (``PipelineLayer``, ``nn.Sequential`` or any layer whose children
  compose as a chain) into ``n_stages`` contiguous groups, balanced by
  parameter count (the reference's default seg_method="uniform" is the
  fallback). Each stage becomes a PURE function over its own parameter
  arrays — the same swap-in trick jit.TrainStep uses — so jax.vjp
  drives the schedule's backward jobs.

* :func:`partition_program` — cuts a captured op-DAG program
  (static/graph.py) at single-tensor articulation points into
  ``n_stages`` segments balanced by output-element cost, re-feeding the
  boundary tensor of each cut as the next stage's input. This is the
  op-level analog of the reference's static partitioner.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax

from ...static import graph as _g
from .pipeline_scheduler_pass import StagedProgram

__all__ = ["stage_program_from_layers", "partition_program"]


# ------------------------------------------------------------------ layers
def _flatten_chain(model):
    """The model's sequential unit list: PipelineLayer's run_function,
    Sequential's children, else the model itself as one unit."""
    from ...distributed.fleet.meta_parallel import PipelineLayer
    from ... import nn

    if isinstance(model, PipelineLayer):
        return list(model.run_function)
    if isinstance(model, nn.Sequential):
        return list(model)
    kids = list(getattr(model, "children", lambda: [])())
    if len(kids) > 1:
        return kids
    return [model]


def _param_count(layer):
    return sum(int(p.size) for p in layer.parameters()) or 1


def _balanced_segments(units, n_stages: int) -> List[int]:
    """Boundary indices [0, b1, ..., len(units)] with stage param counts
    as even as greedy contiguity allows."""
    costs = [_param_count(u) for u in units]
    total = sum(costs)
    bounds = [0]
    acc = 0
    target = total / n_stages
    for i, c in enumerate(costs):
        acc += c
        # close the segment when at/above its pro-rata share, keeping
        # enough units for the remaining stages
        remaining_stages = n_stages - len(bounds)
        remaining_units = len(units) - (i + 1)
        if len(bounds) < n_stages and acc >= target * len(bounds) \
                and remaining_units >= remaining_stages:
            bounds.append(i + 1)
    while len(bounds) < n_stages:
        bounds.append(bounds[-1] + 1)
    bounds.append(len(units))
    return bounds


def stage_program_from_layers(model, n_stages: int, loss_fn: Callable,
                              devices: Optional[Sequence] = None,
                              seg_method: str = "param_count"
                              ) -> StagedProgram:
    """Partition ``model`` into a StagedProgram (reference:
    pp_layers.py segmentation -> static pipeline plan).

    ``loss_fn(y_last, labels) -> scalar``. ``devices``: optional one jax
    device per stage (e.g. a mesh's pp axis).
    """
    units = _flatten_chain(model)
    if len(units) < n_stages:
        raise ValueError(
            f"model has {len(units)} sequential units, cannot make "
            f"{n_stages} pipeline stages")
    if seg_method == "uniform":
        per = [len(units) // n_stages] * n_stages
        for i in range(len(units) % n_stages):
            per[i] += 1
        bounds = [0]
        for p in per:
            bounds.append(bounds[-1] + p)
    else:
        bounds = _balanced_segments(units, n_stages)

    stages, params = [], []
    for s in range(n_stages):
        seg = units[bounds[s]:bounds[s + 1]]
        seg_params = [p for u in seg for p in u.parameters()]

        def stage_fn(param_arrays, x, _seg=seg, _ps=seg_params):
            from ...core.tensor import Tensor

            saved = [p._data for p in _ps]
            for p, a in zip(_ps, param_arrays):
                p._data = a
            try:
                t = x if isinstance(x, Tensor) else Tensor(x)
                for u in _seg:
                    t = u(t)
                return t._data
            finally:
                for p, a in zip(_ps, saved):
                    p._data = a

        stages.append(stage_fn)
        params.append([p._data for p in seg_params])

    def wrapped_loss(y, label):
        from ...core.tensor import Tensor

        out = loss_fn(Tensor(y), Tensor(label) if not isinstance(
            label, Tensor) else label)
        return out._data if isinstance(out, Tensor) else out

    prog = StagedProgram(stages, params, wrapped_loss, devices=devices)
    # keep the segment->layer map so callers can write updated params back
    prog.segments = [units[bounds[s]:bounds[s + 1]]
                     for s in range(n_stages)]
    prog.segment_params = [
        [p for u in seg for p in u.parameters()] for seg in prog.segments]
    return prog


# ----------------------------------------------------------------- program
def _topo_order(root) -> List:
    order, seen = [], set()
    stack = [(root, False)]
    while stack:
        node, done = stack.pop()
        if not isinstance(node, _g.OpNode):
            continue
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for p in node.parents:
            if isinstance(p, tuple):
                stack.append((p[0], False))
    return order


def partition_program(loss_fetch, input_name: str, label_name: str,
                      n_stages: int,
                      devices: Optional[Sequence] = None) -> StagedProgram:
    """Cut the captured program producing the scalar ``loss_fetch`` into
    ``n_stages`` stages at single-tensor articulation points of its
    op-DAG (reference: auto_parallel/static/ partitioner over PIR).

    Contract: the ``input_name`` feed reaches only the first segment and
    ``label_name`` only the last (the canonical backbone+loss shape); a
    cut point is an op whose single output is the ONLY value crossing
    the prefix/suffix boundary.
    """
    node0, idx0 = loss_fetch._sym_node
    order = _topo_order(node0)
    pos = {id(n): i for i, n in enumerate(order)}

    # feeds: only input_name/label_name are representable — stage fns
    # have a (params, x[, label]) signature, so any other feed would
    # KeyError at schedule time; reject it here with a clear message
    feed_names = set()
    feed_use = {}      # name -> (first consumer pos, last consumer pos)
    max_cons = {}      # producer id -> last consumer position
    for i, n in enumerate(order):
        for p in n.parents:
            if isinstance(p, tuple):
                prev = max_cons.get(id(p[0]), -1)
                max_cons[id(p[0])] = max(prev, i)
            elif isinstance(p, _g.FeedLeaf):
                feed_names.add(p.name)
                lo, hi = feed_use.get(p.name, (i, i))
                feed_use[p.name] = (min(lo, i), max(hi, i))
    extra = feed_names - {input_name, label_name}
    if extra:
        raise ValueError(
            f"partition_program supports exactly two feeds "
            f"({input_name!r}, {label_name!r}); program also feeds "
            f"{sorted(extra)}")
    last_input_use = feed_use.get(input_name, (0, -1))[1]
    first_label_use = feed_use.get(label_name, (len(order), len(order)))[0]

    # single forward sweep: 'open' producers whose value is still needed
    # past position i; a valid cut at i is open == {order[i]} (O(n+e))
    by_close = {}
    for nid, last in max_cons.items():
        by_close.setdefault(last, []).append(nid)
    open_ids = set()
    cut_positions = []
    for i, n in enumerate(order[:-1]):
        for nid in by_close.get(i, ()):   # fully consumed AT i
            open_ids.discard(nid)
        if max_cons.get(id(n), -1) > i:
            open_ids.add(id(n))
        if not n.single or open_ids != {id(n)}:
            continue
        # label must not be consumed before the cut (it belongs to the
        # loss tail), input not after (it belongs to stage 0)
        if i < last_input_use or i >= first_label_use:
            continue
        cut_positions.append(i)
    if len(cut_positions) < n_stages - 1:
        raise ValueError(
            f"program has {len(cut_positions)} articulation points; "
            f"cannot cut into {n_stages} stages")

    # balance by cumulative output-element cost
    cost = [0.0]
    for n in order:
        c = sum(float(jax_size(a)) for a in n.out_avals)
        cost.append(cost[-1] + c)
    total = cost[-1]
    chosen = []
    cands = list(cut_positions)
    for k in range(1, n_stages):
        tgt = total * k / n_stages
        best = min(cands, key=lambda i: abs(cost[i + 1] - tgt))
        chosen.append(best)
        cands = [c for c in cands if c > best]
        if not cands and k < n_stages - 1:
            raise ValueError("not enough articulation points after "
                             "balancing; lower n_stages")
    chosen.sort()

    # build per-segment traces: boundary value re-fed as "pp_in"
    bounds = [-1] + chosen + [len(order) - 1]
    stages, params = [], []
    for s in range(n_stages):
        lo, hi = bounds[s], bounds[s + 1]
        seg_nodes = order[lo + 1:hi + 1]
        boundary_in = order[lo] if lo >= 0 else None
        out_node = order[hi]
        feed_in = None
        if boundary_in is not None:
            feed_in = _g.FeedLeaf("pp_in", boundary_in.out_avals[0])
        memo = {}

        def clone(n, _feed=feed_in, _bid=(id(boundary_in)
                                          if boundary_in is not None
                                          else None), _memo=memo):
            if id(n) in _memo:
                return _memo[id(n)]
            new_parents = []
            for p in n.parents:
                if isinstance(p, tuple):
                    if id(p[0]) == _bid:
                        new_parents.append(_feed)
                    else:
                        new_parents.append((clone(p[0]), p[1]))
                else:
                    new_parents.append(p)
            nn_ = _g.OpNode(n.fn, new_parents, n.out_avals, n.name,
                            n.single, attrs=n.attrs)
            _memo[id(n)] = nn_
            return nn_

        seg_root = clone(out_node)
        run, feed_names, plist = _g.trace([(seg_root, 0 if out_node.single
                                            else idx0)])
        if s == n_stages - 1:
            # the last segment computes the LOSS itself (its trainable
            # tail params get real grads through the schedule's vjp):
            # stage_fn(params, x, label) with last_takes_label=True
            def last_fn(param_arrays, x, label, _run=run,
                        _feeds=feed_names):
                feeds = {}
                for name in _feeds:
                    if name == "pp_in":
                        feeds[name] = x
                    elif name == label_name:
                        feeds[name] = label
                return _run(feeds, list(param_arrays))[0]

            stages.append(last_fn)
        else:
            def stage_fn(param_arrays, x, _run=run, _feeds=feed_names):
                feeds = {}
                for name in _feeds:
                    if name in ("pp_in", input_name):
                        feeds[name] = x
                return _run(feeds, list(param_arrays))[0]

            stages.append(stage_fn)
        params.append([p._data for p in plist])
    return StagedProgram(stages, params, loss_fn=None, devices=devices,
                         last_takes_label=True)


def jax_size(aval) -> int:
    try:
        out = 1
        for s in aval.shape:
            out *= int(s)
        return out
    except Exception:
        return 1
