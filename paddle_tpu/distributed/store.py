"""TCPStore: rendezvous + KV for process-group bootstrap
(reference: paddle/phi/core/distributed/store/tcp_store.h:45 MasterDaemon,
TCPServer:84; kept as a pure-socket component exactly as SURVEY §2.4.10
recommends).

Protocol: length-prefixed msgpack-free binary frames:
  [1B op][4B key_len][key][8B value_len][value]
ops: SET=0 GET=1 ADD=2 WAIT=3 CHECK=4 DEL=5
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Dict, Optional

from ..config import knobs

__all__ = ["TCPStore", "MasterDaemon", "PrefixStore",
           "create_or_get_global_tcp_store"]

_OP_SET, _OP_GET, _OP_ADD, _OP_WAIT, _OP_CHECK, _OP_DEL = 0, 1, 2, 3, 4, 5


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return buf


def _send_frame(sock, op: int, key: bytes, value: bytes):
    sock.sendall(struct.pack(">BI", op, len(key)) + key
                 + struct.pack(">Q", len(value)) + value)


def _recv_frame(sock):
    hdr = _recv_exact(sock, 5)
    op, klen = struct.unpack(">BI", hdr)
    key = _recv_exact(sock, klen) if klen else b""
    (vlen,) = struct.unpack(">Q", _recv_exact(sock, 8))
    value = _recv_exact(sock, vlen) if vlen else b""
    return op, key, value


class MasterDaemon(threading.Thread):
    """KV server run by rank 0 (reference: tcp_store.h:45)."""

    def __init__(self, port: int, world_size: int = 1):
        super().__init__(daemon=True)
        self._kv: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._stop = False
        self.start()

    @property
    def port(self):
        return self._port

    def run(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                op, key, value = _recv_frame(conn)
                if op == _OP_SET:
                    with self._cond:
                        self._kv[key] = value
                        self._cond.notify_all()
                    _send_frame(conn, op, b"", b"ok")
                elif op == _OP_GET:
                    with self._lock:
                        v = self._kv.get(key, b"")
                    _send_frame(conn, op, b"", v)
                elif op == _OP_ADD:
                    (delta,) = struct.unpack(">q", value)
                    with self._cond:
                        cur = int(self._kv.get(key, b"0"))
                        cur += delta
                        self._kv[key] = str(cur).encode()
                        self._cond.notify_all()
                    _send_frame(conn, op, b"", struct.pack(">q", cur))
                elif op == _OP_WAIT:
                    (timeout_ms,) = struct.unpack(">q", value)
                    deadline = time.time() + timeout_ms / 1000.0
                    ok = True
                    with self._cond:
                        while key not in self._kv:
                            remaining = deadline - time.time()
                            if remaining <= 0:
                                ok = False
                                break
                            self._cond.wait(min(remaining, 1.0))
                    _send_frame(conn, op, b"", b"1" if ok else b"0")
                elif op == _OP_CHECK:
                    with self._lock:
                        ok = key in self._kv
                    _send_frame(conn, op, b"", b"1" if ok else b"0")
                elif op == _OP_DEL:
                    with self._lock:
                        existed = self._kv.pop(key, None) is not None
                    _send_frame(conn, op, b"", b"1" if existed else b"0")
        except (ConnectionError, OSError):
            pass

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


class TCPStore:
    """Client (rank 0 also hosts the daemon).
    (reference: phi/core/distributed/store/tcp_store.h TCPStore)

    Uses the native C++ daemon/client (native/tcp_store.cc via
    core.native) when the shared library is available; the wire protocol
    is identical, so native and Python endpoints interoperate. Set
    PADDLE_TPU_PURE_PY_STORE=1 to force the Python implementation."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 900.0):
        from ..core import native as _native

        self._native = (_native.available()
                        and not knobs.get_bool("PADDLE_TPU_PURE_PY_STORE"))
        self._daemon = None
        if is_master:
            if self._native:
                self._daemon = _native.NativeStoreServer(port)
            else:
                self._daemon = MasterDaemon(port, world_size)
            port = self._daemon.port
        self._host = host
        self._port = port
        self._timeout = timeout
        if self._native:
            # thread safety lives in the C++ StoreClient's own mutex
            self._client = _native.NativeStoreClient(host, port, timeout)
            return
        deadline = time.time() + timeout
        last_err = None
        while time.time() < deadline:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                self._sock.settimeout(timeout)
                break
            except OSError as e:
                last_err = e
                time.sleep(0.05)
        else:
            raise ConnectionError(
                f"cannot connect to TCPStore {host}:{port}: {last_err}")
        self._lock = threading.Lock()

    @property
    def port(self):
        return self._port

    # ------------------------------------------------------------ transport
    def _reconnect(self):
        """Replace a dead client socket (daemon restarts keep the KV, so
        reconnect-and-retry makes every op survive a dropped socket)."""
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=5)
        self._sock.settimeout(self._timeout)

    def _roundtrip(self, op: int, key: bytes, value: bytes):
        """One frame exchange under the shared retry policy: a
        mid-operation ``ConnectionError``/``OSError`` (peer reset,
        closed socket, injected drop) reconnects and retries instead of
        failing the collective bootstrap outright. Ops are idempotent
        enough for at-least-once delivery (set/get/wait/check are pure;
        ADD may double-apply only when the reply itself is lost)."""
        from .resilience import faults as _faults, retry as _retry

        def attempt():
            with self._lock:
                act = _faults.check("store.op")
                if act is not None:
                    if act.kind == "drop":
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        raise ConnectionError(
                            "fault-injected store socket drop")
                    _faults.apply(act)
                try:
                    _send_frame(self._sock, op, key, value)
                    return _recv_frame(self._sock)
                except (ConnectionError, OSError):
                    # reconnect NOW (under the lock) so the next attempt
                    # starts on a fresh socket; a failed reconnect
                    # becomes this attempt's error and is retried
                    self._reconnect()
                    raise

        return _retry.call_with_retry(attempt, site="store.op")

    def _native_op(self, fn, *args):
        from .resilience import faults as _faults, retry as _retry

        def attempt():
            act = _faults.check("store.op")
            if act is not None and act.kind != "drop":
                _faults.apply(act)
            return fn(*args)

        return _retry.call_with_retry(attempt, site="store.op")

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        if self._native:
            self._native_op(self._client.set, key.encode(), bytes(value))
            return
        self._roundtrip(_OP_SET, key.encode(), bytes(value))

    def get(self, key: str) -> bytes:
        self.wait([key])
        if self._native:
            return self._native_op(self._client.get, key.encode())
        _, _, v = self._roundtrip(_OP_GET, key.encode(), b"")
        return v

    def add(self, key: str, delta: int) -> int:
        if self._native:
            return self._native_op(self._client.add, key.encode(), delta)
        _, _, v = self._roundtrip(_OP_ADD, key.encode(),
                                  struct.pack(">q", delta))
        return struct.unpack(">q", v)[0]

    def wait(self, keys, timeout: Optional[float] = None) -> None:
        timeout = timeout if timeout is not None else self._timeout
        for key in keys:
            if self._native:
                ok = self._native_op(self._client.wait, key.encode(),
                                     int(timeout * 1000))
                if not ok:
                    raise TimeoutError(
                        f"TCPStore wait timed out on key {key!r}")
                continue
            # only the frame exchange is retried; the server answering
            # "not set within the timeout" is an application timeout and
            # must surface immediately, not be retried
            _, _, v = self._roundtrip(_OP_WAIT, key.encode(),
                                      struct.pack(">q", int(timeout * 1000)))
            if v != b"1":
                raise TimeoutError(f"TCPStore wait timed out on key {key!r}")

    def try_get(self, key: str) -> Optional[bytes]:
        """Atomic get-or-None: a raw GET answered from the server's
        current table in one round trip — never blocks on a missing key
        (the GET op returns an empty frame for one). The check-then-get
        idiom is racy against a concurrent ``delete`` — the key can
        vanish between the two round trips and ``get`` then blocks for
        the full store timeout — so pollers of deletable keys (heartbeat
        leases, consumed mailboxes) must use this instead. Caveat: a
        deliberately-stored empty value is indistinguishable from a
        missing key."""
        if self._native:
            v = self._native_op(self._client.get, key.encode())
        else:
            _, _, v = self._roundtrip(_OP_GET, key.encode(), b"")
        return v if v else None

    def delete(self, key: str) -> bool:
        """Remove a key (protocol op 5); True if it existed. Long-lived
        control planes (rpc) use this to reclaim consumed mailbox keys."""
        if self._native:
            return self._native_op(self._client.delete, key.encode())
        _, _, v = self._roundtrip(_OP_DEL, key.encode(), b"")
        return v == b"1"

    def check(self, key: str) -> bool:
        if self._native:
            return self._native_op(self._client.check, key.encode())
        _, _, v = self._roundtrip(_OP_CHECK, key.encode(), b"")
        return v == b"1"

    def barrier(self, prefix: str, world_size: int, rank: int,
                timeout: Optional[float] = None):
        """Barrier-with-deadline: ``timeout`` bounds the wait for the
        last arrival (TimeoutError on expiry — a dead peer must surface
        as a typed failure, never a hang); None uses the store default.
        """
        n = self.add(f"{prefix}/barrier", 1)
        if n == world_size:
            self.set(f"{prefix}/barrier_done", b"1")
        self.wait([f"{prefix}/barrier_done"], timeout)


class PrefixStore:
    """Key-namespacing wrapper (reference: phi/core/distributed/store/
    prefix_store). Used to scope worker keys by restart generation when the
    store daemon outlives worker generations (multi-node launch): without
    it, a restarted rank would consume the dead generation's barrier and
    gather values."""

    def __init__(self, prefix: str, store):
        self._p = prefix
        self._s = store

    def _k(self, key: str) -> str:
        return f"{self._p}{key}"

    def set(self, key, value):
        return self._s.set(self._k(key), value)

    def get(self, key):
        return self._s.get(self._k(key))

    def add(self, key, delta):
        return self._s.add(self._k(key), delta)

    def wait(self, keys, timeout=None):
        return self._s.wait([self._k(k) for k in keys], timeout)

    def try_get(self, key):
        return self._s.try_get(self._k(key))

    def delete(self, key):
        return self._s.delete(self._k(key))

    def check(self, key):
        return self._s.check(self._k(key))

    def barrier(self, prefix, world_size, rank, timeout=None):
        return self._s.barrier(self._k(prefix), world_size, rank,
                               timeout)


_global_store: Optional[TCPStore] = None


def create_or_get_global_tcp_store() -> TCPStore:
    """reference: phi/core/distributed/store/store_utils.h:33."""
    global _global_store
    if _global_store is not None:
        return _global_store
    ep = os.environ.get("PADDLE_MASTER",
                        os.environ.get("MASTER_ENDPOINT", "127.0.0.1:0"))
    host, port = ep.rsplit(":", 1)
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    # multi-node launch: the launcher already hosts the master daemon at
    # PADDLE_MASTER (it needed it for rendezvous before any worker ran) —
    # every worker, including global rank 0, connects as a client
    hosted = os.environ.get("PADDLE_STORE_HOSTED") == "1"
    _global_store = TCPStore(host, int(port),
                             is_master=(rank == 0 and not hosted),
                             world_size=world)
    if hosted:
        # the launcher-hosted daemon outlives restart generations: scope
        # every worker key by the generation so stale values are invisible
        gen = os.environ.get("PADDLE_RESTART_GEN", "0")
        _global_store = PrefixStore(f"wg{gen}/", _global_store)
    return _global_store
