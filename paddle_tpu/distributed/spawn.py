"""paddle.distributed.spawn (reference: python/paddle/distributed/spawn.py:463)."""
from __future__ import annotations

import multiprocessing as mp
import os
import socket

from ..config import knobs

__all__ = ["spawn"]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker(func, rank, nprocs, master, backend, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_MASTER"] = master
    os.environ["PADDLE_LOCAL_RANK"] = str(rank)
    if backend:
        os.environ["PADDLE_DIST_BACKEND"] = backend
    if not knobs.get_bool("PADDLE_TPU_KEEP_BACKEND_LOGS"):
        # demote jaxlib's C++ "[Gloo] Rank N is connected..." fd-2 spam
        # to the framework logger at DEBUG before anything inits jax
        from .log_utils import install_stderr_filter

        install_stderr_filter()
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, backend=None,
          **options):
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master = f"127.0.0.1:{_free_port()}"
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, master, backend, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(
                    f"spawned process exited with code {p.exitcode}")
    return procs
