"""Distributed checkpoint: sharded save/load with dedup + load-time reshard
(reference: python/paddle/distributed/checkpoint/save_state_dict.py:145,
load_state_dict.py, metadata.py)."""
from __future__ import annotations

import os
import pickle
import threading
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "wait_async_save",
           "LocalTensorMetadata", "Metadata", "SaveTicket"]


@dataclass
class LocalTensorMetadata:
    """reference: checkpoint/metadata.py."""

    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str


@dataclass
class Metadata:
    state_dict_metadata: Dict[str, List[LocalTensorMetadata]] = field(
        default_factory=dict)
    storage_metadata: Dict[str, str] = field(default_factory=dict)
    flat_mapping: Dict[str, str] = field(default_factory=dict)


def _local_view(t: Tensor):
    """Return (local numpy array, global_offset, global_shape) for a
    possibly-sharded tensor."""
    import jax

    data = t._data
    if isinstance(data, jax.Array) and len(data.devices()) > 1:
        # take this process's addressable shards
        shards = [s for s in data.addressable_shards]
        # single-controller: serialize shard 0's slice per device, dedup later
        arrs = []
        for s in shards:
            idx = s.index
            offset = tuple(sl.start or 0 for sl in idx)
            arrs.append((np.asarray(s.data), offset))
        return arrs, tuple(data.shape)
    return [(np.asarray(data), (0,) * data.ndim)], tuple(data.shape)


_async_lock = threading.Lock()
_async_threads: List[threading.Thread] = []

# in-flight async saves are joined on clean interpreter exit so a
# checkpoint started near the end of a run is never silently lost
import atexit as _atexit

_atexit.register(lambda: wait_async_save())


class SaveTicket:
    """Handle returned by :func:`save_state_dict`: ``report`` maps each
    written filename to its intended ``{"crc32", "size"}`` (computed
    from the in-memory bytes BEFORE they hit disk, so later on-disk
    corruption — torn writes, bit rot, injected faults — is detectable
    against it). For async saves the report fills in on the writer
    thread; ``wait()`` blocks until it is complete."""

    def __init__(self):
        self.report: Dict[str, Dict[str, int]] = {}
        self.error: Optional[BaseException] = None
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None):
        self._done.wait(timeout)
        if self.error is not None:
            raise self.error
        return self.report


def _corrupt_file(fname, act):
    """Apply an injected ``ckpt.write`` fault to the FINAL file (after
    the atomic rename): models damage the manifest CRC must catch."""
    size = os.path.getsize(fname)
    if act.kind == "truncate":
        with open(fname, "r+b") as f:
            f.truncate(max(size // 2, 1))
    elif act.kind == "bitflip" and size:
        with open(fname, "r+b") as f:
            f.seek(size // 3)
            b = f.read(1)
            f.seek(size // 3)
            f.write(bytes([b[0] ^ 0x40]))


def _atomic_dump(obj, fname):
    # write-to-temp + rename so a crash/exit mid-write never leaves a
    # truncated file visible under the final name
    blob = pickle.dumps(obj, protocol=4)
    crc = zlib.crc32(blob) & 0xFFFFFFFF
    tmp = fname + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, fname)
    from ..resilience import faults as _faults

    act = _faults.check("ckpt.write")
    if act is not None:
        if act.kind in ("truncate", "bitflip"):
            _corrupt_file(fname, act)
        else:
            _faults.apply(act)
    return {"crc32": crc, "size": len(blob)}


def _flush_payload(path, fname, shards_payload, meta, is_coordinator,
                   ticket: Optional[SaveTicket] = None):
    try:
        report = {os.path.basename(fname):
                  _atomic_dump(shards_payload, fname)}
        if is_coordinator:
            report["0.metadata"] = _atomic_dump(
                meta, os.path.join(path, "0.metadata"))
        if ticket is not None:
            ticket.report.update(report)
    except BaseException as e:
        if ticket is None:
            raise
        ticket.error = e
    finally:
        if ticket is not None:
            ticket._done.set()


def wait_async_save():
    """Join all pending async checkpoint writes (reference analog: the
    async save queue drain in save_state_dict.py:46)."""
    with _async_lock:
        pending = list(_async_threads)
        _async_threads.clear()
    for t in pending:
        t.join()


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """reference: save_state_dict.py:145 (dedup_tensor :117 — only the
    owner rank writes each shard; async queue :46 — ``async_save=True``
    snapshots to host then writes on a background thread; call
    ``wait_async_save()`` before exiting). Returns a :class:`SaveTicket`
    whose ``report`` carries per-file CRC32/size (complete immediately
    for sync saves, after the writer thread finishes for async)."""
    from ..parallel_env import get_rank

    os.makedirs(path, exist_ok=True)
    rank = get_rank()
    meta = Metadata()
    shards_payload = {}
    for key, val in state_dict.items():
        if not isinstance(val, Tensor):
            shards_payload.setdefault("_objects", {})[key] = val
            continue
        locals_, gshape = _local_view(val)
        metas = []
        seen_offsets = set()
        for arr, offset in locals_:
            if offset in seen_offsets:
                continue  # dedup replicated shards
            seen_offsets.add(offset)
            metas.append(LocalTensorMetadata(offset, tuple(arr.shape),
                                             str(arr.dtype)))
            shards_payload[("shard", key, offset)] = arr
        meta.state_dict_metadata[key] = metas
        meta.storage_metadata[key] = f"{rank}_0.distcp"
    fname = os.path.join(path, f"{rank}_0.distcp")
    is_coord = rank == coordinator_rank
    ticket = SaveTicket()
    if async_save:
        # tensor shards are already host numpy snapshots (_local_view);
        # deep-copy objects/metadata so caller mutations after return
        # cannot tear the checkpoint
        import copy

        if "_objects" in shards_payload:
            shards_payload["_objects"] = copy.deepcopy(
                shards_payload["_objects"])
        meta = copy.deepcopy(meta)
        t = threading.Thread(target=_flush_payload,
                             args=(path, fname, shards_payload, meta,
                                   is_coord, ticket), daemon=True)
        t.start()
        with _async_lock:
            _async_threads.append(t)
        return ticket
    _flush_payload(path, fname, shards_payload, meta, is_coord, ticket)
    if ticket.error is not None:
        raise ticket.error
    return ticket


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """reference: load_state_dict.py — reads all shard files, reassembles
    each tensor, reshards onto the target tensor's current sharding."""
    import jax
    import jax.numpy as jnp

    files = [f for f in os.listdir(path) if f.endswith(".distcp")]
    all_shards: Dict[str, list] = {}
    objects = {}
    for fn in files:
        with open(os.path.join(path, fn), "rb") as f:
            payload = pickle.load(f)
        for k, v in payload.items():
            if k == "_objects":
                objects.update(v)
                continue
            if isinstance(k, tuple):
                _, name, offset = k  # ("shard", key, offset-tuple)
            else:
                # legacy "key|(off, ...)" string layout
                name, off_s = k.rsplit("|", 1)
                offset = tuple(
                    int(x) for x in off_s.strip("()").split(",") if x.strip())
            all_shards.setdefault(name, []).append((offset, v))
    for key, t in state_dict.items():
        if not isinstance(t, Tensor):
            if key in objects:
                state_dict[key] = objects[key]
            continue
        if key not in all_shards:
            continue
        shards = all_shards[key]
        gshape = tuple(t.shape)
        full = np.zeros(gshape, dtype=shards[0][1].dtype)
        for offset, arr in shards:
            slices = tuple(slice(o, o + s)
                           for o, s in zip(offset, arr.shape))
            full[slices] = arr
        new = jnp.asarray(full).astype(t._data.dtype)
        if isinstance(t._data, jax.Array) and hasattr(t._data, "sharding") \
                and len(t._data.devices()) > 1:
            new = jax.device_put(new, t._data.sharding)
        t._data = new
