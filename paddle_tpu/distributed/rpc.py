"""RPC API (reference: python/paddle/distributed/rpc/rpc.py — init_rpc:85
with TCPStore barrier, rpc_sync:160, rpc_async:206, WorkerInfo,
get_worker_info, shutdown; C++ brpc agent fluid/distributed/rpc/).

TPU-native-lite: the transport is the job's TCPStore (the brpc agent's
role); each worker runs a dispatcher thread polling its mailbox, executing
pickled (fn, args, kwargs) requests and posting pickled results. Suited to
control-plane RPC (the reference's primary use); bulk tensors should ride
the collective path.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
import traceback
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, Optional

from ..config import knobs

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str = "127.0.0.1"
    port: int = 0


class _RpcAgent:
    def __init__(self, name: str, rank: int, world_size: int, store,
                 generation: int):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        # generation namespace: a fresh init_rpc on the same store must not
        # replay a previous agent's mailboxes or stale replies
        self._ns = f"rpc{generation}"
        self._send_seq: Dict[str, int] = {}
        self._futures: Dict[str, Future] = {}
        self._orphans: Dict[str, float] = {}  # call_id -> give-up deadline
        # retransmit state per in-flight call (at-least-once delivery:
        # a lost request is re-posted on a backoff schedule; the server
        # dedups by call_id so duplicates never re-execute)
        self._call_meta: Dict[str, dict] = {}
        self._handled: set = set()
        self._handled_order: deque = deque()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # registry: name -> rank
        store.set(f"{self._ns}/worker/{rank}", name.encode())
        self.workers: Dict[str, WorkerInfo] = {}
        for r in range(world_size):
            wname = store.get(f"{self._ns}/worker/{r}").decode()
            self.workers[wname] = WorkerInfo(wname, r)
        self._dispatcher = threading.Thread(target=self._serve, daemon=True)
        self._dispatcher.start()
        self._replies = threading.Thread(target=self._collect, daemon=True)
        self._replies.start()

    # ------------------------------------------------------------ transport
    def _post(self, to_rank: int, payload: dict):
        from .resilience import faults as _faults

        act = _faults.check("rpc.post")
        if act is not None:
            if act.kind in ("loss", "drop"):
                return  # message silently lost in transit
            _faults.apply(act)
        key = f"{self._ns}/mbox/{to_rank}"
        with self._lock:
            seq = self._send_seq.get(key, 0)
            self._send_seq[key] = seq + 1
        self.store.set(f"{key}/{self.rank}/{seq}",
                       pickle.dumps(payload, protocol=4))

    def _serve(self):
        """Execute incoming requests."""
        seqs = {r: 0 for r in range(self.world_size)}
        while not self._stop.is_set():
            progressed = False
            for r in range(self.world_size):
                key = f"{self._ns}/mbox/{self.rank}"
                try:
                    if not self.store.check(f"{key}/{r}/{seqs[r]}"):
                        continue
                    raw = self.store.get(f"{key}/{r}/{seqs[r]}")
                except Exception:
                    if self._stop.is_set():
                        return
                    continue
                consumed_key = f"{key}/{r}/{seqs[r]}"
                seqs[r] += 1
                progressed = True
                # guard the WHOLE message path: a poison message must not
                # kill the dispatcher thread
                try:
                    msg = pickle.loads(raw)
                    if msg.get("kind") != "call":
                        continue
                    # at-least-once dedup: a retransmitted request whose
                    # original was delivered must not re-execute (the
                    # reply is still in / was already read from the
                    # store, keyed by call_id)
                    cid = msg.get("call_id")
                    if cid in self._handled:
                        continue
                    if len(self._handled_order) >= 8192:
                        self._handled.discard(
                            self._handled_order.popleft())
                    self._handled.add(cid)
                    self._handled_order.append(cid)
                    try:
                        from .. import observability as _obs

                        fn = pickle.loads(msg["fn"])
                        # adopt the caller's trace context so the
                        # server-side span joins the caller's trace
                        with _obs.activate_context(msg.get("ctx")):
                            with _obs.span(
                                    "rpc.handle", cat="rpc",
                                    args={"fn": getattr(
                                        fn, "__name__", "?"),
                                        "src": r}):
                                result = fn(*msg.get("args", ()),
                                            **msg.get("kwargs", {}))
                        reply = {"ok": True, "value": result}
                    except Exception as e:  # ship the error back
                        reply = {"ok": False,
                                 "error": f"{e}\n{traceback.format_exc()}"}
                    try:
                        blob = pickle.dumps(reply, protocol=4)
                    except Exception as e:  # unpicklable result
                        blob = pickle.dumps(
                            {"ok": False,
                             "error": f"result not picklable: {e}"},
                            protocol=4)
                    self.store.set(
                        f"{self._ns}/reply/{r}/{msg['call_id']}", blob)
                except Exception:
                    traceback.print_exc()
                finally:
                    # reclaim the consumed mailbox key (store op DEL)
                    try:
                        self.store.delete(consumed_key)
                    except Exception:
                        pass
            if not progressed:
                time.sleep(0.01)

    def _deadlines_and_resends(self):
        """Expire calls past their deadline (TimeoutError on the future)
        and re-post calls whose retransmit backoff elapsed."""
        now = time.monotonic()
        expired, resend = [], []
        with self._lock:
            for cid, meta in list(self._call_meta.items()):
                fut = self._futures.get(cid)
                if fut is None:                    # resolved or dropped
                    self._call_meta.pop(cid, None)
                    continue
                if meta["deadline"] is not None and now > meta["deadline"]:
                    self._futures.pop(cid, None)
                    self._call_meta.pop(cid, None)
                    # watch for the late reply for 10 min, then give up
                    self._orphans[cid] = now + 600.0
                    expired.append((cid, fut, meta))
                    continue
                if meta["resend_at"] is not None and now >= meta["resend_at"]:
                    meta["attempt"] += 1
                    policy = meta["policy"]
                    if meta["attempt"] >= policy.max_attempts - 1:
                        meta["resend_at"] = None   # out of retransmits
                    else:
                        meta["resend_at"] = now + policy.delay(
                            meta["attempt"] + 1, meta["rng"])
                    resend.append((cid, meta))
        for cid, fut, meta in expired:
            fut.set_exception(TimeoutError(
                f"rpc call {cid} got no reply within "
                f"{meta['timeout']}s ({meta['attempt']} retransmits)"))
        for cid, meta in resend:
            try:
                from .. import observability as _obs

                if _obs.enabled():
                    _obs.registry.counter(
                        "resilience.retries",
                        tags={"site": "rpc.resend"}).inc()
                    _obs.flight_recorder.record(
                        "resilience.retry", site="rpc.resend",
                        call_id=cid, attempt=meta["attempt"])
            except Exception:
                pass
            try:
                self._post(meta["to"], meta["payload"])
            except Exception:
                pass  # next backoff (or the deadline) handles it

    def _collect(self):
        """Resolve futures as replies land."""
        while not self._stop.is_set():
            self._deadlines_and_resends()
            done = []
            with self._lock:
                items = list(self._futures.items())
                now = time.monotonic()
                # bounded: give up deleting a late reply after its TTL
                # (dead peer will never write it)
                for cid, dl in list(self._orphans.items()):
                    if now > dl:
                        self._orphans.pop(cid, None)
                orphans = list(self._orphans)
            # late replies for timed-out calls: delete, don't resolve
            for cid in orphans:
                try:
                    k = f"{self._ns}/reply/{self.rank}/{cid}"
                    if self.store.check(k):
                        self.store.delete(k)
                        with self._lock:
                            self._orphans.pop(cid, None)
                except Exception:
                    pass
            for call_id, fut in items:
                try:
                    if self.store.check(f"{self._ns}/reply/{self.rank}/{call_id}"):
                        raw = self.store.get(
                            f"{self._ns}/reply/{self.rank}/{call_id}")
                        reply = pickle.loads(raw)
                        if reply["ok"]:
                            fut.set_result(reply["value"])
                        else:
                            fut.set_exception(RuntimeError(reply["error"]))
                        done.append(call_id)
                        try:
                            self.store.delete(
                                f"{self._ns}/reply/{self.rank}/{call_id}")
                        except Exception:
                            pass
                except Exception:
                    if self._stop.is_set():
                        return
            with self._lock:
                for c in done:
                    self._futures.pop(c, None)
            if not done:
                time.sleep(0.01)

    # ------------------------------------------------------------ calls
    _call_counter = 0

    def call(self, to: str, fn, args, kwargs,
             timeout: Optional[float] = None,
             retry_policy=None) -> Future:
        from .. import observability as _obs
        from .resilience import retry as _retry

        info = self.workers[to]
        with self._lock:
            _RpcAgent._call_counter += 1
            call_id = f"{self.rank}_{_RpcAgent._call_counter}"
            fut: Future = Future()
            self._futures[call_id] = fut
        payload = {
            "kind": "call", "call_id": call_id,
            "fn": pickle.dumps(fn, protocol=4),
            "args": args, "kwargs": kwargs,
        }
        # retransmit schedule: the rpc timeout becomes the DEADLINE of
        # the retry policy; until it expires, a silently lost request is
        # re-posted on exponential backoff (server dedups by call_id)
        policy = retry_policy or _retry.default_policy(
            deadline=timeout,
            max_attempts=knobs.get_int("PADDLE_TPU_RPC_RETRIES"),
            base_delay=knobs.get_float(
                "PADDLE_TPU_RPC_RETRY_BASE_DELAY"),
            max_delay=4.0)
        now = time.monotonic()
        rng = _retry._jitter_rng(f"rpc.resend/{call_id}")
        with self._lock:
            self._call_meta[call_id] = {
                "to": info.rank, "payload": payload, "attempt": 0,
                "timeout": timeout, "policy": policy, "rng": rng,
                "deadline": None if timeout is None else now + timeout,
                "resend_at": (now + policy.delay(1, rng)
                              if policy.max_attempts > 1 else None),
            }
        if _obs.enabled():
            # stamp the caller's trace context; the peer's dispatcher
            # adopts it, stitching client and server spans
            payload["ctx"] = _obs.current_context()
            with _obs.span("rpc.call", cat="rpc",
                           args={"to": to, "fn": getattr(
                               fn, "__name__", "?")}):
                self._post(info.rank, payload)
        else:
            self._post(info.rank, payload)
        return fut

    def stop(self):
        self._stop.set()


_agent: Optional[_RpcAgent] = None
_endpoint_stores: Dict[str, object] = {}


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None, master_endpoint=None,
             timeout: Optional[float] = None):
    """reference: rpc.py:85 — registers this worker and barriers until the
    full world joined. ``timeout`` bounds the rendezvous (TimeoutError)
    — pass it when the rest of the world may legitimately never come up
    (e.g. PS init probing)."""
    global _agent
    import os

    from .store import create_or_get_global_tcp_store

    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    world_size = world_size if world_size is not None else int(
        os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if master_endpoint:
        # dedicated store on the requested endpoint (rank 0 hosts);
        # cached so re-init after shutdown reuses the live daemon instead
        # of re-binding the port
        from .store import TCPStore

        store = _endpoint_stores.get(master_endpoint)
        if store is None:
            host, port = master_endpoint.rsplit(":", 1)
            store = TCPStore(host, int(port), is_master=(rank == 0),
                             world_size=world_size)
            _endpoint_stores[master_endpoint] = store
    else:
        store = create_or_get_global_tcp_store()
    # generation-consistent rendezvous: the n-th init across the job maps
    # to generation (n-1)//world_size + 1; wait until the whole world has
    # joined this generation (reference: init_rpc's TCPStore barrier)
    n = store.add("rpc/init_count", 1)
    gen = (n - 1) // world_size + 1
    deadline = None if timeout is None else time.monotonic() + timeout
    while store.add("rpc/init_count", 0) < gen * world_size:
        if deadline is not None and time.monotonic() > deadline:
            # withdraw our join or the generation arithmetic is poisoned
            # for every later init against this store (a late peer would
            # see the count satisfied and hang in the ready barrier)
            store.add("rpc/init_count", -1)
            raise TimeoutError(
                f"rpc rendezvous: fewer than {world_size} peers joined "
                f"generation {gen} within {timeout}s")
        time.sleep(0.02)
    _agent = _RpcAgent(name, rank, world_size, store, gen)
    store.barrier(f"rpc{gen}_ready", world_size, rank)
    return _agent


def _require_agent() -> _RpcAgent:
    if _agent is None:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")
    return _agent


def rpc_sync(to: str, fn, args=(), kwargs=None, timeout: float = 60.0):
    """reference: rpc.py:160. ``timeout`` is both the result deadline
    and the retransmit budget (see :func:`rpc_async`)."""
    fut = rpc_async(to, fn, args, kwargs, timeout=timeout)
    try:
        # the agent's deadline sweep fails the future at ~timeout; the
        # small slack keeps the two timers from racing
        return fut.result(timeout=timeout + 5.0)
    except Exception:
        # drop the orphaned future; remember the call_id so _collect
        # deletes the late reply instead of leaking it in the store
        agent = _require_agent()
        with agent._lock:
            for cid, f in list(agent._futures.items()):
                if f is fut:
                    agent._futures.pop(cid, None)
                    # watch for the late reply for 10 min, then give up
                    agent._orphans[cid] = time.monotonic() + 600.0
        raise


def rpc_async(to: str, fn, args=(), kwargs=None, timeout=None,
              retry_policy=None) -> Future:
    """reference: rpc.py:206. Returns a concurrent.futures.Future with
    .result()/.wait() semantics (the reference FutureWrapper analog).

    ``timeout`` (seconds) is propagated as the DEADLINE of the retry
    policy governing retransmits: unacknowledged calls are re-posted on
    exponential backoff until the deadline, after which the future fails
    with TimeoutError. Without it, resends stop after
    PADDLE_TPU_RPC_RETRIES attempts and the future waits indefinitely."""
    return _require_agent().call(to, fn, args, kwargs or {},
                                 timeout=timeout,
                                 retry_policy=retry_policy)


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    agent = _require_agent()
    if name is None:
        return agent.workers[agent.name]
    return agent.workers[name]


def get_all_worker_infos():
    return list(_require_agent().workers.values())


def shutdown(graceful: bool = True, timeout: float = 120.0,
             dead_ranks=None):
    """``dead_ranks`` (iterable of rpc ranks, or a zero-arg callable
    returning one) names peers the caller observed die: the graceful
    barrier stops waiting for their arrival flags. Re-read on every
    poll, so a death detected mid-barrier still releases everyone.
    Flags are per-rank (not a count) because long-lived serving ranks
    — e.g. a parameter server whose ``run()`` IS this barrier — arrive
    at startup: a count can't tell a dead peer's early arrival from
    the live peer everyone is actually waiting on."""
    global _agent
    if _agent is not None:
        if graceful:
            # POLLING barrier, not store.barrier: the blocking wait()
            # would hold the store client's mutex until every rank
            # arrives, starving this agent's own dispatcher threads —
            # a peer still streaming rpc work through us (e.g. a
            # FleetExecutor pipeline draining) would deadlock the job.
            # Bounded: a crashed peer must fail the barrier loudly, not
            # hang every surviving rank forever.
            ns = f"{_agent._ns}_shutdown"
            world = _agent.world_size

            def _dead() -> set:
                if dead_ranks is None:
                    return set()
                d = dead_ranks() if callable(dead_ranks) else dead_ranks
                return set() if d is None else set(d)

            _agent.store.set(f"{ns}/rank/{_agent.rank}", b"1")
            deadline = time.monotonic() + timeout
            while True:
                dead = _dead()
                try:
                    waiting = [r for r in range(world)
                               if r not in dead
                               and not _agent.store.check(
                                   f"{ns}/rank/{r}")]
                except (ConnectionError, OSError):
                    # the master store died mid-poll. Its host rank only
                    # exits after seeing EVERY arrival flag (ours
                    # included), so losing the store here proves the
                    # barrier completed — finish shutting down instead
                    # of crashing the tail rank.
                    break
                if not waiting:
                    break
                if time.monotonic() > deadline:
                    _agent.stop()
                    _agent = None
                    raise TimeoutError(
                        f"rpc.shutdown barrier: ranks {waiting} never "
                        f"arrived within {timeout}s (a peer likely "
                        "crashed)")
                time.sleep(0.02)
        _agent.stop()
        _agent = None
