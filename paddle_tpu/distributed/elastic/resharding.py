"""Shrink/expand resharding: deterministic param->rank remap.

The data-parallel optimizer state is sharded ZeRO-1 style: params are
replicated, optimizer moments are partitioned by *param index* into
contiguous, element-count-balanced ranges — the 1-D analog of the
``distributed/checkpoint`` shard math, where every shard is a
(global_offset, local_shape) interval and a load is the intersection
of saved and wanted intervals. On a world-size change the new
partition is recomputed from the same pure function, so the remap
(which old rank holds each piece a new rank needs) is a deterministic
function of (sizes, old_world, new_world): a 4->3 shrink and the
3->4 rejoin both land on the layouts those worlds always had.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

__all__ = ["partition_ranges", "range_for_rank", "plan_remap",
           "shard_opt_state", "merge_opt_shards"]


def partition_ranges(sizes: Sequence[int],
                     world: int) -> List[Tuple[int, int]]:
    """Split params ``0..len(sizes)`` into ``world`` contiguous
    half-open index ranges, balanced by element count (each boundary is
    placed where the cumulative size first reaches its quota). Pure and
    deterministic: the same (sizes, world) always yields the same
    layout, which is what makes shrink->rejoin restore the original
    partition exactly."""
    if world <= 0:
        raise ValueError(f"world must be positive, got {world}")
    total = sum(int(s) for s in sizes)
    bounds = [0]
    cum = 0
    i = 0
    n = len(sizes)
    for w in range(1, world):
        quota = total * w / world
        while i < n and cum + int(sizes[i]) <= quota:
            cum += int(sizes[i])
            i += 1
        bounds.append(i)
    bounds.append(n)
    return [(bounds[k], bounds[k + 1]) for k in range(world)]


def range_for_rank(sizes: Sequence[int], members: Sequence[int],
                   rank: int) -> Tuple[int, int]:
    """The param-index range ``rank`` owns under the partition for the
    (sorted) member list."""
    ms = sorted(members)
    return partition_ranges(sizes, len(ms))[ms.index(rank)]


def plan_remap(old_parts: Sequence[Tuple[int, int]],
               new_parts: Sequence[Tuple[int, int]]
               ) -> List[List[Tuple[int, int, int]]]:
    """For each new shard, the ``(old_index, lo, hi)`` interval
    intersections that assemble it — which old holder to read, and
    which slice of its range. Every new element maps to exactly one
    old interval (both partitions cover the same index space)."""
    plan: List[List[Tuple[int, int, int]]] = []
    for nlo, nhi in new_parts:
        pieces = []
        for oi, (olo, ohi) in enumerate(old_parts):
            lo, hi = max(nlo, olo), min(nhi, ohi)
            if lo < hi:
                pieces.append((oi, lo, hi))
        plan.append(pieces)
    return plan


def shard_opt_state(state: Dict, lo: int, hi: int,
                    n_params: int) -> Dict:
    """Slice one rank's shard out of a functional optimizer state:
    any list/tuple entry of length ``n_params`` (per-param moments) is
    sliced to ``[lo:hi]``; scalar entries (step counters) replicate."""
    out = {}
    for k, v in state.items():
        if isinstance(v, (list, tuple)) and len(v) == n_params:
            out[k] = list(v[lo:hi])
        else:
            out[k] = v
    return out


def merge_opt_shards(shards: Sequence[Tuple[Tuple[int, int], Dict]],
                     n_params: int) -> Dict:
    """Reassemble a full optimizer state from ``((lo, hi), shard)``
    pieces covering ``0..n_params``. Scalar entries must agree across
    shards (they are per-step, not per-param)."""
    pieces = sorted(shards, key=lambda x: x[0][0])
    covered = 0
    for (lo, hi), _ in pieces:
        if lo != covered:
            raise ValueError(
                f"opt shard gap: expected lo={covered}, got {lo}")
        covered = hi
    if covered != n_params:
        raise ValueError(
            f"opt shards cover {covered} of {n_params} params")
    out: Dict = {}
    for (lo, hi), shard in pieces:
        for k, v in shard.items():
            if isinstance(v, (list, tuple)) and len(v) == hi - lo:
                out.setdefault(k, []).extend(v)
            else:
                prev = out.get(k, v)
                out[k] = v
                if isinstance(prev, (int, float)) and prev != v:
                    raise ValueError(
                        f"opt shards disagree on scalar {k!r}: "
                        f"{prev} != {v}")
    return out
