"""Elastic ZeRO-1 data-parallel trainer: the training-tier analog of
the serving cluster's drain-and-replay (PR 12).

Params are replicated; the functional optimizer state is partitioned
across the current epoch's members by the deterministic
``resharding.partition_ranges`` layout. Each step is two
store-transported collectives (gradient gather, updated-param
all-gather), both **barrier-with-deadline**: every wait polls the
membership coordinator and raises the typed :class:`EpochChanged`
instead of hanging when a peer dies mid-step. Recovery is a pure
function of the store: survivors (and rejoiners) restore the latest
common peer-replicated snapshot, remap optimizer shards onto the new
world via ``plan_remap``, and replay forward — so a shrink resumes the
very next step, and a rejoin restores the original layout.

Gradient exactness across world sizes: ``grad_fn`` returns the SUM of
per-row losses/grads over its contiguous row shard, and the combined
gradient divides the member-ordered total by the fixed global batch
size — the full-batch gradient is the same mathematical quantity at
any world size, which is what makes shrink/expand trajectories
reproducible and drill-checkable.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..control_plane import keyspace as _ks
from ..resilience import faults as _faults
from .membership import ElasticConfig, EpochChanged, \
    MembershipCoordinator, try_get
from .resharding import partition_ranges, plan_remap, range_for_rank, \
    shard_opt_state
from .snapshots import PeerReplicator, SnapshotCorrupt, encode, decode, \
    fetch_best

__all__ = ["ElasticDataParallel"]


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


class ElasticDataParallel:
    """One instance per rank.

    Parameters
    ----------
    store : TCPStore-like (set/get/add/check/delete)
    rank, world_hint : this rank and the initial world size
    params : list of np.ndarray, identical on every rank at step 0
    grad_fn : ``(params, X, Y) -> (loss_sum, [grad_sum, ...])`` over a
        row shard (sums, not means — see module docstring)
    data_fn : ``step -> (X, Y)`` the full deterministic global batch
    optimizer : functional optimizer (``init_state`` / ``update``)
    ckpt_mgr : optional CheckpointManager for the disk fallback
    rejoin : True when this process replaces a dead rank mid-job
    expand_at : admit joiners only once the group has reached this step
        (pins the expansion point so trajectories replay exactly)
    """

    def __init__(self, store, rank: int, world_hint: int,
                 params: Sequence[np.ndarray],
                 grad_fn: Callable, data_fn: Callable, optimizer,
                 lr: Optional[float] = None,
                 config: Optional[ElasticConfig] = None,
                 ckpt_mgr=None, rejoin: bool = False,
                 expand_at: Optional[int] = None,
                 namespace: str = "elastic",
                 watchdog_hook: bool = False):
        self.store = store
        self.rank = int(rank)
        self.cfg = config or ElasticConfig()
        self.params: List[np.ndarray] = [np.asarray(p) for p in params]
        self.grad_fn = grad_fn
        self.data_fn = data_fn
        self.optimizer = optimizer
        self.lr = lr
        self.ckpt_mgr = ckpt_mgr
        self.rejoin = bool(rejoin)
        self.ns = namespace
        self._watchdog_hook = bool(watchdog_hook)
        self.coord = MembershipCoordinator(
            store, self.rank, world_hint, config=self.cfg,
            namespace=namespace)
        self.replicator = PeerReplicator(
            store, self.rank, namespace=namespace,
            snap_freq=self.cfg.snap_freq)
        if expand_at is not None:
            self.coord.set_expand_gate(int(expand_at))
        self.opt_shard: Optional[Dict] = None
        self.steps_done = 0
        self.history: List[float] = []
        self.epoch_log: List[Dict] = []     # committed epoch timeline
        self.recoveries: List[Dict] = []    # source/step/latency rows
        self._booted = False

    # ---------------------------------------------------------- keys
    def _xkey(self, epoch: int, tag: str, step: int, rank: int) -> str:
        return _ks.xchg(self.ns, epoch, tag, step, rank)

    # ------------------------------------------------------ bootstrap
    def _sizes(self) -> List[int]:
        return [int(p.size) for p in self.params]

    def _my_range(self):
        return range_for_rank(self._sizes(), self.coord.members,
                              self.rank)

    def _snapshot_payload(self) -> Dict:
        lo, hi = self._my_range()
        return {"params": [np.asarray(p) for p in self.params],
                "range": (lo, hi),
                "opt_shard": {
                    k: [np.asarray(e) for e in v]
                    if isinstance(v, (list, tuple)) else np.asarray(v)
                    for k, v in (self.opt_shard or {}).items()}}

    def _log_epoch(self, rec: Dict) -> None:
        self.epoch_log.append({"epoch": rec["epoch"],
                               "members": list(rec["members"]),
                               "from_step": self.steps_done + 1,
                               "reason": rec.get("reason", "")})

    def _bootstrap(self) -> None:
        self.coord.register()
        if self._watchdog_hook:
            self.coord.install_watchdog_hook()
        if self.rejoin:
            self.coord.request_join()
            while True:
                rec = self.coord.join()
                if self.rank in rec["members"]:
                    break
                time.sleep(0.05)
            self._adopt(rec)
        else:
            rec = self.coord.form_initial()
            if self.rank not in rec["members"]:
                raise RuntimeError(
                    f"rank {self.rank} excluded from initial epoch "
                    f"{rec}")
            lo, hi = self._my_range()
            full = self.optimizer.init_state(
                [np.asarray(p) for p in self.params])
            self.opt_shard = shard_opt_state(full, lo, hi,
                                             len(self.params))
            self._log_epoch(rec)
            # seed the replica ring before the first step so a kill at
            # step 1 is already recoverable from peer memory
            self.replicator.push(0, self.coord.members,
                                 self._snapshot_payload())
        self._booted = True

    # ----------------------------------------------------- collectives
    def _gather(self, tag: str, step: int, payload: bytes
                ) -> Dict[int, Dict]:
        """Post mine, collect everyone's — deadline-bounded, epoch-aware
        (the typed-escape path the watchdog can only approximate for
        opaque device collectives)."""
        epoch = self.coord.epoch
        members = list(self.coord.members)
        self.store.set(self._xkey(epoch, tag, step, self.rank), payload)
        deadline = time.monotonic() + self.cfg.collective_deadline
        out: Dict[int, Dict] = {}
        lease_checked = 0.0
        for r in members:
            key = self._xkey(epoch, tag, step, r)
            raw = None
            while raw is None:
                raw = try_get(self.store, key)
                if raw is not None:
                    break
                # hang_only: a pending proposal must not tear the step
                # mid-collective — a dead peer is caught by the lease
                # probe below or, worst case, the deadline
                self.coord.poll(hang_only=True)
                now = time.monotonic()
                if r != self.rank and now - lease_checked > 0.1:
                    lease_checked = now
                    if not self.coord.lease_fresh(r):
                        self.coord.suspect(r, f"{tag}@{step}")
                        raise EpochChanged(
                            self.coord.refresh_pending(),
                            f"peer {r} lease expired during "
                            f"{tag}@{step}")
                if now > deadline:
                    self.coord.suspect(r, f"{tag}@{step}")
                    raise EpochChanged(
                        self.coord.refresh_pending(),
                        f"peer {r} missed {tag}@{step} within "
                        f"{self.cfg.collective_deadline}s")
                time.sleep(0.005)
            out[r] = decode(raw)
            if "__epoch_abort__" in out[r]:
                # the peer bailed out at its step boundary for an epoch
                # change and left this marker so we escape NOW instead
                # of sitting out the collective deadline
                raise EpochChanged(
                    self.coord.refresh_pending(),
                    f"peer {r} aborted {tag}@{step} for epoch change")
        # everyone has read step-1 keys once they posted step: reclaim
        if step > 1:
            try:
                self.store.delete(
                    self._xkey(epoch, tag, step - 1, self.rank))
            except Exception:
                pass
        return out

    # ------------------------------------------------------- training
    def _train_one(self, step: int) -> float:
        members = sorted(self.coord.members)
        X, Y = self.data_fn(step)
        batch = int(len(X))
        rows = partition_ranges([1] * batch, len(members))
        rlo, rhi = rows[members.index(self.rank)]
        loss_sum, grad_sums = self.grad_fn(self.params, X[rlo:rhi],
                                           Y[rlo:rhi])
        blob = encode({"loss": float(loss_sum),
                       "grads": [np.asarray(g, np.float32)
                                 for g in grad_sums]})
        got = self._gather("g", step, blob)
        loss = sum(got[r]["loss"] for r in members) / batch
        grads: List[np.ndarray] = []
        for j in range(len(self.params)):
            tot = got[members[0]]["grads"][j].astype(np.float32).copy()
            for r in members[1:]:
                tot += got[r]["grads"][j]
            grads.append(tot / batch)
        lo, hi = self._my_range()
        new_slice, self.opt_shard = self.optimizer.update(
            [np.asarray(self.params[k], np.float32)
             for k in range(lo, hi)],
            grads[lo:hi], self.opt_shard, lr=self.lr)
        pblob = encode({"range": (lo, hi),
                        "params": [np.asarray(p, np.float32)
                                   for p in new_slice]})
        pg = self._gather("p", step, pblob)
        for r in members:
            plo, phi = pg[r]["range"]
            for k, arr in zip(range(plo, phi), pg[r]["params"]):
                self.params[k] = arr
        return float(loss)

    def run(self, total_steps: int) -> List[float]:
        while self.steps_done < int(total_steps):
            try:
                if not self._booted:
                    self._bootstrap()
                    continue
                step = self.steps_done + 1
                self.coord.refresh_pending()
                self.coord.poll()
                act = _faults.check("engine.step")
                if act is not None:
                    _faults.apply(act)
                t0 = time.perf_counter()
                loss = self._train_one(step)
                step_ms = (time.perf_counter() - t0) * 1000.0
                self.steps_done = step
                self.history.append(loss)
                self.coord.heartbeat(step, step_ms)
                self.replicator.maybe_push(step, self.coord.members,
                                           self._snapshot_payload)
                # step-synchronous membership scan: joiners are folded
                # in HERE (not by the timer thread), so the expansion
                # step is pinned by the gate alone
                self.coord.watch_once()
            except EpochChanged as e:
                self._post_abort_marker()
                self._recover(e)
        return self.history

    # ------------------------------------------------------- recovery
    def _post_abort_marker(self) -> None:
        """Before recovering, leave a tombstone in the next step's
        gather slot (only if no real payload is there): a peer already
        waiting inside that collective reads it and escapes immediately
        and at the SAME step, instead of burning the full deadline."""
        key = self._xkey(self.coord.epoch, "g", self.steps_done + 1,
                         self.rank)
        try:
            if not self.store.check(key):
                self.store.set(key, encode({"__epoch_abort__": True}))
        except Exception:
            pass

    def _recover(self, exc: EpochChanged) -> None:
        t0 = time.monotonic()
        while True:
            rec = self.coord.join()
            if self.rank in rec["members"]:
                break
            # excluded (hang/demotion): drop state, rejoin as fresh
            self.coord.clear_hang()
            self.coord.request_join()
            time.sleep(0.05)
        source = self._adopt(rec)
        dt_ms = (time.monotonic() - t0) * 1000.0
        self.recoveries.append({"epoch": rec["epoch"],
                                "source": source,
                                "resume_step": self.steps_done + 1,
                                "latency_ms": dt_ms,
                                "reason": str(exc)})
        o = _obs()
        if o:
            o.registry.counter("elastic.recoveries",
                               tags={"source": source}).inc()
            o.registry.histogram("elastic.recovery_ms").observe(dt_ms)

    def _adopt(self, rec: Dict) -> str:
        """Restore params + resharded optimizer state for the committed
        epoch ``rec``; returns the recovery source ("peer" or "disk")."""
        o = _obs()
        span = o.span("elastic.reshard",
                      args={"epoch": rec["epoch"]}) if o else None
        if span:
            span.__enter__()
        try:
            prev = self.coord.read_epoch(int(rec.get("prev") or 0))
            old_members = sorted(prev["members"]) if prev else \
                sorted(rec["members"])
            try:
                source = self._adopt_from_peers(rec, old_members)
            except (SnapshotCorrupt, KeyError, ValueError) as e:
                import sys

                print(f"[elastic] peer recovery unavailable ({e}); "
                      "falling back to disk", file=sys.stderr)
                source = self._adopt_from_disk(rec)
            self._log_epoch(rec)
            # re-seed the ring under the new membership immediately so
            # a second failure before the next snap stays recoverable
            self.replicator.push(self.steps_done, rec["members"],
                                 self._snapshot_payload())
            return source
        finally:
            if span:
                span.__exit__(None, None, None)

    def _adopt_from_peers(self, rec: Dict,
                          old_members: List[int]) -> str:
        snaps: Dict[int, Dict] = {}
        for src in old_members:
            got = fetch_best(self.store, self.ns, src,
                             self.cfg.max_nodes)
            if got is None:
                raise KeyError(f"no peer snapshot for old rank {src}")
            snaps[src] = got
        steps = {s["step"] for s in snaps.values()}
        if len(steps) != 1:
            raise ValueError(
                f"peer snapshots disagree on step: {sorted(steps)}")
        step = steps.pop()
        self._adopt_payloads(rec, old_members, snaps)
        self.steps_done = int(step)
        self.history = self.history[:int(step)]
        return "peer"

    def _adopt_payloads(self, rec: Dict, old_members: List[int],
                        snaps: Dict[int, Dict]) -> None:
        self.params = [np.asarray(p) for p in
                       snaps[min(old_members)]["params"]]
        sizes = self._sizes()
        n = len(self.params)
        old_parts = [tuple(snaps[src]["range"]) for src in old_members]
        new_members = sorted(rec["members"])
        new_parts = partition_ranges(sizes, len(new_members))
        plan = plan_remap(old_parts, new_parts)
        pieces = plan[new_members.index(self.rank)]
        shard: Dict = {}
        for oi, lo, hi in pieces:
            src = old_members[oi]
            olo, _ = old_parts[oi]
            part = shard_opt_state(snaps[src]["opt_shard"],
                                   lo - olo, hi - olo,
                                   old_parts[oi][1] - olo)
            for k, v in part.items():
                if isinstance(v, list):
                    shard.setdefault(k, []).extend(v)
                else:
                    shard[k] = v
        if not pieces:
            # empty new range: scalars from any old shard, empty lists
            any_shard = snaps[min(old_members)]["opt_shard"]
            shard = {k: ([] if isinstance(v, (list, tuple)) else v)
                     for k, v in any_shard.items()}
        self.opt_shard = shard

    def _adopt_from_disk(self, rec: Dict) -> str:
        if self.ckpt_mgr is None:
            raise RuntimeError(
                "peer replication insufficient and no CheckpointManager "
                "configured for disk fallback")
        found = self.ckpt_mgr.latest_valid()
        if found is None:
            raise RuntimeError(
                "peer replication insufficient and no valid disk "
                "checkpoint to fall back to")
        _, path = found
        state = {"__elastic_state__": None}
        self.ckpt_mgr.load(state, path)
        payload = state["__elastic_state__"]
        self.params = [np.asarray(p) for p in payload["params"]]
        lo, hi = range_for_rank(self._sizes(), rec["members"],
                                self.rank)
        self.opt_shard = shard_opt_state(payload["opt"], lo, hi,
                                         len(self.params))
        self.steps_done = int(payload["step"])
        self.history = self.history[:self.steps_done]
        return "disk"

    # ----------------------------------------------------- disk saves
    def save_disk(self, step: int) -> None:
        """Gather the full optimizer state and have the lowest member
        write one CRC-manifested disk checkpoint — the PR 3 fallback
        tier under the in-memory replication."""
        if self.ckpt_mgr is None:
            return
        members = sorted(self.coord.members)
        lo, hi = self._my_range()
        blob = encode({"range": (lo, hi),
                       "opt_shard": self._snapshot_payload()
                       ["opt_shard"]})
        got = self._gather("opt", step, blob)
        if self.rank != min(members):
            return
        from .resharding import merge_opt_shards

        full = merge_opt_shards(
            [(tuple(got[r]["range"]), got[r]["opt_shard"])
             for r in members], len(self.params))
        self.ckpt_mgr.save(
            {"__elastic_state__": {
                "params": [np.asarray(p) for p in self.params],
                "opt": full, "step": int(step)}},
            step, blocking=True)

    def shutdown(self) -> None:
        self.coord.deregister()
