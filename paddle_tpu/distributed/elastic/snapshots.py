"""Peer-replicated in-memory checkpoints.

Each rank pushes its shard of the training state (full params + its
slice of the optimizer state) to its right neighbor's mailbox key
``elastic/snap/{from}/{to}`` every ``PADDLE_TPU_ELASTIC_SNAP_FREQ``
steps, over the same store transport the host p2p path uses. The
payload is CRC-tagged (header ``ELSN`` + crc32 + length, the same
integrity discipline as the CheckpointManager manifest), so recovery
after a kill is a mailbox read + CRC check — no disk involved. Only
when replication is insufficient (missing mailboxes, CRC mismatch, no
common step) does recovery fall back to the PR 3 disk manifest.

Fault site ``elastic.reshard``: ``truncate`` / ``bitflip`` corrupt a
fetched snapshot payload deterministically, driving the disk-fallback
path in tests.
"""
from __future__ import annotations

import pickle
import struct
import zlib
from typing import Dict, List, Optional

from ..control_plane import keyspace as _ks
from ..resilience import faults as _faults

__all__ = ["SnapshotCorrupt", "encode", "decode", "PeerReplicator",
           "fetch_best", "mailbox_key"]

_MAGIC = b"ELSN"
_HEADER = struct.Struct(">4sIQ")     # magic, crc32, payload length


class SnapshotCorrupt(RuntimeError):
    """A peer snapshot failed its CRC/framing check."""


def encode(obj) -> bytes:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _HEADER.pack(_MAGIC, zlib.crc32(payload) & 0xFFFFFFFF,
                        len(payload)) + payload


def decode(blob: bytes):
    if len(blob) < _HEADER.size:
        raise SnapshotCorrupt(
            f"snapshot too short ({len(blob)} bytes)")
    magic, crc, length = _HEADER.unpack_from(blob)
    payload = blob[_HEADER.size:]
    if magic != _MAGIC:
        raise SnapshotCorrupt(f"bad snapshot magic {magic!r}")
    if len(payload) != length:
        raise SnapshotCorrupt(
            f"snapshot truncated: {len(payload)} != {length}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise SnapshotCorrupt("snapshot CRC mismatch")
    return pickle.loads(payload)


def mailbox_key(ns: str, src: int, dst: int) -> str:
    return _ks.snap(ns, src, dst)


def _corrupt(blob: bytes, kind: str) -> bytes:
    if kind == "truncate":
        return blob[:max(_HEADER.size, len(blob) // 2)]
    if kind == "bitflip" and blob:
        b = bytearray(blob)
        b[len(b) // 2] ^= 0x40
        return bytes(b)
    return blob


def fetch(store, ns: str, src: int, dst: int):
    """Decode the snapshot ``src`` pushed to ``dst``'s mailbox, or None
    when the mailbox is empty. Raises :class:`SnapshotCorrupt` on CRC
    failure (including injected ``elastic.reshard`` corruption)."""
    key = mailbox_key(ns, src, dst)
    from .membership import try_get

    blob = try_get(store, key)
    if blob is None:
        return None
    act = _faults.check("elastic.reshard")
    if act is not None:
        if act.kind in ("truncate", "bitflip"):
            blob = _corrupt(blob, act.kind)
        else:
            _faults.apply(act)
    return decode(blob)


def fetch_best(store, ns: str, src: int, max_nodes: int = 16):
    """Newest decodable snapshot of ``src`` across every mailbox it may
    have pushed to (the receiver set changes across epochs). Returns
    the decoded payload or None; CRC failures propagate so the caller
    can fall back to disk."""
    best = None
    for dst in range(max_nodes):
        got = fetch(store, ns, src, dst)
        if got is not None and (best is None
                                or got["step"] > best["step"]):
            best = got
    return best


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


class PeerReplicator:
    """The push side: serialize + CRC-tag this rank's shard and mail it
    to the right neighbor of the current epoch's ring."""

    def __init__(self, store, rank: int, namespace: str = "elastic",
                 snap_freq: int = 10):
        self.store = store
        self.rank = int(rank)
        self.ns = namespace
        self.snap_freq = max(int(snap_freq), 1)
        self.last_step: Optional[int] = None
        self.last_bytes = 0

    def neighbor(self, members: List[int]) -> int:
        ms = sorted(members)
        i = ms.index(self.rank)
        return ms[(i + 1) % len(ms)]

    def push(self, step: int, members: List[int], payload: Dict) -> int:
        """Unconditionally snapshot ``payload`` at ``step``. Returns
        the encoded size in bytes."""
        payload = dict(payload)
        payload["step"] = int(step)
        payload["members"] = sorted(int(m) for m in members)
        payload["src"] = self.rank
        blob = encode(payload)
        dst = self.neighbor(members)
        self.store.set(mailbox_key(self.ns, self.rank, dst), blob)
        self.last_step = int(step)
        self.last_bytes = len(blob)
        o = _obs()
        if o:
            o.registry.counter("elastic.snapshots").inc()
            o.registry.gauge("elastic.snapshot_bytes").set(len(blob))
        return len(blob)

    def maybe_push(self, step: int, members: List[int],
                   make_payload) -> bool:
        """Snapshot when ``step`` hits the configured frequency;
        ``make_payload()`` is only called when a push happens, so the
        state gather costs nothing on off-steps."""
        if step % self.snap_freq != 0:
            return False
        self.push(step, members, make_payload())
        return True
