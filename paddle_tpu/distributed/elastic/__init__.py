"""Elastic self-healing training (reference layer: the fleet elastic
controller, python/paddle/distributed/fleet/elastic/ — rebuilt
TPU-native over the job's own TCPStore).

Four pieces, composable and individually testable:

- :mod:`.membership` — generation-numbered group epochs: heartbeat
  leases, missed-beat / hang detection, barrier-with-deadline epoch
  commits, the typed :class:`EpochChanged` escape for in-flight work;
- :mod:`.snapshots` — CRC-tagged peer-replicated in-memory
  checkpoints over the store mailbox transport;
- :mod:`.resharding` — deterministic param->rank remap for
  shrink/expand (contiguous interval partition + intersection plan,
  the 1-D form of the distributed/checkpoint shard math);
- :mod:`.straggler` — rolling p50 step-time policy.

:class:`ElasticDataParallel` composes them into a ZeRO-1 elastic
trainer (the chaos-drill subject); :class:`ElasticContext` attaches
the same membership + snapshot tiers to ``Engine.fit``.

Env knobs: ``PADDLE_TPU_ELASTIC`` (Engine.fit opt-in),
``PADDLE_TPU_ELASTIC_TIMEOUT`` (failure->recovery budget),
``PADDLE_TPU_ELASTIC_SNAP_FREQ``, ``PADDLE_TPU_ELASTIC_BEAT``,
``PADDLE_TPU_ELASTIC_STRAGGLER_FACTOR`` / ``_POLICY``,
``PADDLE_TPU_ELASTIC_MAX_NODES``.
"""
from .context import ElasticContext
from .data_parallel import ElasticDataParallel
from .membership import ElasticConfig, EpochChanged, \
    MembershipCoordinator
from .resharding import merge_opt_shards, partition_ranges, \
    plan_remap, range_for_rank, shard_opt_state
from .snapshots import PeerReplicator, SnapshotCorrupt
from .straggler import StragglerDetector

__all__ = [
    "ElasticConfig", "ElasticContext", "ElasticDataParallel",
    "EpochChanged", "MembershipCoordinator", "PeerReplicator",
    "SnapshotCorrupt", "StragglerDetector", "merge_opt_shards",
    "partition_ranges", "plan_remap", "range_for_rank",
    "shard_opt_state",
]
