"""Membership coordinator: generation-numbered group epochs over the
TCPStore (reference: python/paddle/distributed/fleet/elastic/manager.py
ElasticManager etcd registry + CollectiveElasticController, rebuilt
TPU-native on the job's own KV store — no etcd dependency).

Protocol (all keys under one namespace, default ``elastic``):

- every member heartbeats a *lease* (``beat/{rank}`` = JSON ``{t, step,
  step_ms}``) every ``ElasticConfig.beat_interval`` seconds; a lease
  older than ``ElasticConfig.timeout`` is expired;
- the **acting coordinator** is the lowest-ranked member with a fresh
  lease — when it dies, the next-lowest member's scan takes over
  automatically (deputy failover, no election round needed);
- membership changes are **epochs**: the coordinator allocates a
  monotone epoch number from the ``seq`` counter (store ADD — the same
  primitive the restart-generation channel uses), publishes the member
  list at ``epoch/{n}`` and advertises it at ``propose``; members ack
  (``epoch/{n}/ack/{rank}``), the lowest member of the NEW list commits
  (``epoch/{n}/commit`` + ``cur``) once every ack has landed. Each wait
  in that handshake carries a deadline, so a member that dies mid-join
  shrinks the proposal instead of wedging it;
- in-flight training work observes a pending epoch through
  :meth:`MembershipCoordinator.poll`, which raises the typed
  :class:`EpochChanged` — collectives built on the store poll it inside
  their wait loops, so a membership change surfaces as a catchable
  error, never a hang;
- a watchdog-reported hang (``hang/{rank}``, fed by the
  ``emergency.abort`` interceptor installed by
  :meth:`install_watchdog_hook`) and a straggler demotion
  (``demote/{rank}``) are treated like missed beats at the next scan.

Fault sites: ``elastic.heartbeat`` (``drop`` skips one beat) and
``elastic.epoch_commit`` (``delay`` holds the commit past a member's
deadline) make membership races injectable and deterministic.

The mechanics — beat writes, the atomic ``try_get``, the
propose/ack/commit epoch keys, and the typed :class:`EpochChanged` —
live in :mod:`paddle_tpu.distributed.control_plane` (the substrate the
PS and serving-cluster tiers share); this module keeps the elastic
POLICY (who acts, when to propose, the join barrier) and re-exports
the shared names so existing importers keep working. Keys, payloads,
and write order are unchanged: the drills stay bit-exact.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional

from ..control_plane import keyspace as _ks
from ..control_plane.epochs import EpochChanged, EpochRegistry
from ..control_plane.lease import read_beat, scan_beats, write_beat
from ..control_plane.store_util import try_get
from ...config import knobs
from ..resilience import faults as _faults
from .straggler import StragglerDetector

__all__ = ["ElasticConfig", "EpochChanged", "MembershipCoordinator",
           "read_beat", "scan_beats", "try_get"]


class ElasticConfig:
    """Env-tunable knobs (``PADDLE_TPU_ELASTIC_*``)."""

    def __init__(self, beat_interval: Optional[float] = None,
                 timeout: Optional[float] = None,
                 snap_freq: Optional[int] = None,
                 straggler_factor: Optional[float] = None,
                 straggler_policy: Optional[str] = None,
                 max_nodes: Optional[int] = None):
        self.beat_interval = (
            float(beat_interval) if beat_interval is not None
            else knobs.get_float("PADDLE_TPU_ELASTIC_BEAT"))
        # the whole failure->recovery budget. Derived deadlines nest
        # inside it: leases expire at 0.5x (so the coordinator can
        # already propose by the time a collective gives up at 0.75x),
        # join-barrier waits get the full budget.
        self.timeout = (
            float(timeout) if timeout is not None
            else knobs.get_float("PADDLE_TPU_ELASTIC_TIMEOUT"))
        self.snap_freq = (
            int(snap_freq) if snap_freq is not None
            else knobs.get_int("PADDLE_TPU_ELASTIC_SNAP_FREQ"))
        self.straggler_factor = (
            float(straggler_factor) if straggler_factor is not None
            else knobs.get_float("PADDLE_TPU_ELASTIC_STRAGGLER_FACTOR"))
        # "flag" records telemetry only; "demote" drops flagged ranks
        # from the next epoch
        self.straggler_policy = (
            straggler_policy if straggler_policy is not None
            else knobs.get_str("PADDLE_TPU_ELASTIC_STRAGGLER_POLICY"))
        self.max_nodes = (
            int(max_nodes) if max_nodes is not None
            else knobs.get_int("PADDLE_TPU_ELASTIC_MAX_NODES"))

    @property
    def lease_timeout(self) -> float:
        return 0.5 * self.timeout

    @property
    def collective_deadline(self) -> float:
        return 0.75 * self.timeout


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


class MembershipCoordinator:
    """One per rank. Every rank runs the same scan logic; acting as THE
    coordinator is a property of the current lease table (lowest fresh
    rank), not a fixed role — that is what makes failover free."""

    def __init__(self, store, rank: int, world_hint: int,
                 config: Optional[ElasticConfig] = None,
                 clock: Callable[[], float] = time.time,
                 namespace: str = "elastic"):
        self.store = store
        self.rank = int(rank)
        self.world_hint = int(world_hint)
        self.cfg = config or ElasticConfig()
        self.clock = clock
        self.ns = namespace
        self._epochs = EpochRegistry(store, namespace, clock)
        self.epoch = 0
        self.members: List[int] = []
        self.on_fault: Optional[Callable[[List[int]], None]] = None
        self.on_straggler: Optional[Callable[[List[int]], None]] = None
        self.detector = StragglerDetector(
            factor=self.cfg.straggler_factor)
        self._pending = 0           # highest proposal number seen
        self._hang: Optional[str] = None
        self._last_step = 0
        self._last_step_ms: Optional[float] = None
        self._expand_gate = 0       # joiners admitted once step >= gate
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._abort_token: Optional[int] = None
        self._lock = threading.Lock()

    # ----------------------------------------------------------- lease
    def register(self, start_threads: bool = True) -> None:
        try:
            # returning after a clean leave: clear the departure marker
            self.store.delete(_ks.left(self.ns, self.rank))
        except Exception:
            pass
        self.store.set(_ks.node(self.ns, self.rank),
                       json.dumps({"pid": os.getpid(),
                                   "t": self.clock()}).encode())
        self.beat()
        if start_threads:
            for fn in (self._beat_loop, self._watch_loop):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
                self._threads.append(t)

    def deregister(self) -> None:
        """Stop the background threads (joined with a timeout) and
        delete this rank's registry + lease keys so a clean exit is not
        reported as a fault. A ``left`` marker tells the survivors this
        was a planned departure: they shrink immediately with reason
        ``left`` instead of waiting out the lease and calling it a
        missed beat."""
        try:
            self.store.set(_ks.left(self.ns, self.rank),
                           json.dumps({"t": self.clock()}).encode())
        except Exception:
            pass
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2 * self.cfg.beat_interval + 1.0)
        self._threads = []
        if self._abort_token is not None:
            from ..resilience import emergency

            emergency.unregister_abort(self._abort_token)
            self._abort_token = None
        for key in (_ks.node(self.ns, self.rank),
                    _ks.beat(self.ns, self.rank)):
            try:
                self.store.delete(key)
            except Exception:
                pass

    def beat(self) -> None:
        """Write one lease beat through the control-plane substrate.
        Fault site ``elastic.heartbeat``: ``drop`` skips the write (a
        lost beat on the wire); the substrate's own ``cp.lease`` site
        can drop it one layer down."""
        act = _faults.check("elastic.heartbeat")
        if act is not None:
            if act.kind == "drop":
                return
            _faults.apply(act)
        with self._lock:
            payload = {"t": self.clock(), "step": self._last_step,
                       "step_ms": self._last_step_ms}
        if not write_beat(self.store, self.ns, self.rank, payload):
            return                       # dropped at cp.lease
        o = _obs()
        if o:
            o.registry.counter("elastic.heartbeats").inc()

    def heartbeat(self, step: int,
                  step_ms: Optional[float] = None) -> None:
        """Training-loop beat: records progress + step-time telemetry
        on top of the background lease."""
        with self._lock:
            self._last_step = int(step)
            self._last_step_ms = step_ms
        if step_ms is not None:
            o = _obs()
            if o:
                o.registry.histogram("elastic.step_ms").observe(
                    float(step_ms))
        self.beat()

    def _beat_loop(self):
        while not self._stop.wait(self.cfg.beat_interval):
            try:
                self.beat()
            except Exception:
                pass    # a store blip must not kill the lease thread

    # ----------------------------------------------------------- watch
    def _registered(self) -> List[int]:
        out = []
        for r in range(self.cfg.max_nodes):
            try:
                if self.store.check(_ks.node(self.ns, r)):
                    out.append(r)
            except Exception:
                pass
        return out

    def _candidates(self) -> List[int]:
        return self.members if self.epoch > 0 else self._registered()

    def i_am_acting(self, now: Optional[float] = None) -> bool:
        """True when this rank is the lowest candidate with a fresh
        lease (or no candidate at all has one — then the lowest rank
        overall acts, so a fully-stale table can still make progress)."""
        now = self.clock() if now is None else now
        cands = self._candidates()
        if self.rank not in cands:
            cands = sorted(set(cands) | {self.rank})
        beats = scan_beats(self.store, self.ns, cands, now,
                           self.cfg.lease_timeout)
        alive = [r for r in cands if beats[r] is not None
                 or r == self.rank]
        return self.rank == min(alive) if alive else True

    def lease_fresh(self, rank: int, now: Optional[float] = None) -> bool:
        """True while ``rank`` holds an unexpired heartbeat lease. The
        safe early-escape test for collective waits: a stale lease means
        the peer cannot post its key, so every waiter escapes on the
        same evidence — unlike a pending proposal, which a live group
        may drain past at different times."""
        now = self.clock() if now is None else now
        beat = read_beat(self.store, self.ns, rank)
        return beat is not None and \
            now - float(beat.get("t", 0.0)) <= self.cfg.lease_timeout

    def refresh_pending(self) -> int:
        n = self._epochs.pending()
        with self._lock:
            if n > self._pending:
                self._pending = n
            return self._pending

    def poll(self, hang_only: bool = False) -> None:
        """Raise :class:`EpochChanged` if a newer epoch than the one we
        joined has been proposed, or if this rank's own watchdog
        reported a hang. Cheap (reads cached state maintained by the
        watch thread). Call with ``hang_only=False`` only at STEP
        BOUNDARIES: reacting to a merely-pending proposal mid-collective
        would tear the step on some ranks but not others (whoever drains
        their gather first never polls again) and desynchronise the
        snapshot ring. Inside collective wait loops pass
        ``hang_only=True`` — a live group drains regardless of pending
        proposals, and a dead peer is escaped by the collective
        deadline, not by this check."""
        with self._lock:
            hang, pending = self._hang, self._pending
        if hang is not None:
            raise EpochChanged(pending, f"hang reported: {hang}")
        if not hang_only and pending > self.epoch:
            raise EpochChanged(pending, "newer epoch proposed")

    def suspect(self, rank: int, why: str = "") -> None:
        """A peer looked dead from this rank's side (e.g. a collective
        deadline expired waiting on it). Recorded for the coordinator;
        the lease table stays the ground truth."""
        try:
            self.store.set(_ks.member_flag(self.ns, "suspect", rank),
                           json.dumps({"from": self.rank, "t":
                                       self.clock(), "why": why}).encode())
        except Exception:
            pass

    def report_hang(self, reason: str) -> None:
        """Watchdog abort interceptor target: mark this rank hung so
        the coordinator excludes it at the next scan, and make the next
        :meth:`poll` raise instead of letting the process be killed."""
        with self._lock:
            self._hang = reason
        try:
            self.store.set(_ks.member_flag(self.ns, "hang", self.rank),
                           reason.encode())
        except Exception:
            pass
        o = _obs()
        if o:
            o.registry.counter("elastic.hangs").inc()

    def install_watchdog_hook(self) -> None:
        """Route ``watchdog`` aborts into membership: instead of
        ``os._exit`` the process reports the hang, survives, and rejoins
        at the next epoch."""
        from ..resilience import emergency

        if self._abort_token is None:
            self._abort_token = emergency.register_abort(
                lambda reason: (self.report_hang(reason), True)[1])

    def clear_hang(self) -> None:
        with self._lock:
            self._hang = None
        try:
            self.store.delete(_ks.member_flag(self.ns, "hang", self.rank))
        except Exception:
            pass

    def set_expand_gate(self, step: int) -> None:
        """Joiners are folded into a new epoch only once the local step
        has reached ``step`` — pins the expansion point so recovery
        trajectories are replayable."""
        self._expand_gate = int(step)

    def _flagged_keys(self, kind: str, ranks) -> List[int]:
        out = []
        for r in ranks:
            key = _ks.left(self.ns, r) if kind == "left" \
                else _ks.member_flag(self.ns, kind, r)
            try:
                if self.store.check(key):
                    out.append(r)
            except Exception:
                pass
        return out

    def watch_once(self, now: Optional[float] = None,
                   admit_joins: bool = True) -> Optional[int]:
        """One scan: refresh the pending proposal; when acting
        coordinator, detect missed beats / hangs / demotions / join
        requests and propose a new epoch. Returns the proposal number
        when one was made (None otherwise). Pure with respect to time —
        tests drive it with a fake clock.

        ``admit_joins=False`` (the background watch thread) restricts
        the scan to failure handling: folding joiners in is left to the
        step-synchronous scan the trainer runs at step boundaries, so
        WHICH step an expansion lands on is a function of the expand
        gate, not of timer jitter — that is what keeps two drill runs'
        membership schedules identical."""
        now = self.clock() if now is None else now
        self.refresh_pending()
        if not self.i_am_acting(now):
            return None
        members = sorted(self._candidates())
        if not members:
            return None
        beats = scan_beats(self.store, self.ns, members, now,
                           self.cfg.lease_timeout)
        # planned departures (deregister marker): shrink right away,
        # and never report a clean leave as a missed beat
        left = self._flagged_keys(
            "left", [r for r in members if r != self.rank])
        dead = [r for r in members
                if r != self.rank and beats[r] is None
                and r not in left]
        hung = self._flagged_keys("hang",
                                  [r for r in members if r != self.rank])
        o = _obs()
        if dead:
            if o:
                o.registry.counter("elastic.missed_beats").inc(len(dead))
            if self.on_fault is not None:
                try:
                    self.on_fault(list(dead))
                except Exception:
                    pass
        # straggler telemetry from the lease payloads
        for r in members:
            b = beats.get(r)
            if b and b.get("step_ms") is not None:
                self.detector.record(r, float(b["step_ms"]))
        flagged = [r for r in self.detector.flagged() if r != self.rank]
        if o:
            o.registry.gauge("elastic.stragglers").set(len(flagged))
        if flagged and self.on_straggler is not None:
            try:
                self.on_straggler(list(flagged))
            except Exception:
                pass
        demoted = self._flagged_keys(
            "demote", [r for r in members if r != self.rank])
        if self.cfg.straggler_policy == "demote":
            demoted = sorted(set(demoted) | set(flagged))
        gone = set(dead) | set(hung) | set(demoted) | set(left)
        with self._lock:
            if self._hang is not None:
                # a hung coordinator proposes its own exclusion; the
                # lowest SURVIVOR commits and the hung rank rejoins
                gone.add(self.rank)
        joins = []
        if admit_joins and self._last_step >= self._expand_gate:
            joins = [r for r in self._flagged_keys(
                "join", range(self.cfg.max_nodes))
                if r not in members and r not in gone]
        survivors = [r for r in members if r not in gone]
        new_members = sorted(set(survivors) | set(joins))
        if self.epoch > 0 and new_members == members:
            return None
        with self._lock:
            pending = self._pending
        if pending > self.epoch:
            # an uncommitted proposal for this same change is already
            # out — don't burn another epoch on it
            pend = self.read_epoch(pending)
            if pend and sorted(pend["members"]) == new_members:
                return None
        if self.epoch == 0 and not (gone or joins):
            return None     # initial formation is form_initial()'s job
        if not new_members:
            return None
        reason = []
        if dead:
            reason.append(f"missed beats: {dead}")
        if left:
            reason.append(f"left: {sorted(left)}")
        if hung:
            reason.append(f"hangs: {hung}")
        if demoted:
            reason.append(f"demoted: {demoted}")
        if joins:
            reason.append(f"joins: {joins}")
        n = self.propose(new_members, "; ".join(reason) or "scan")
        for r in joins:
            try:
                self.store.delete(_ks.member_flag(self.ns, "join", r))
            except Exception:
                pass
        for r in demoted:
            try:
                self.store.delete(_ks.member_flag(self.ns, "demote", r))
            except Exception:
                pass
        return n

    def _watch_loop(self):
        while not self._stop.wait(self.cfg.beat_interval):
            try:
                self.watch_once(admit_joins=False)
            except Exception:
                pass

    # ----------------------------------------------------------- epoch
    def propose(self, members: List[int], reason: str) -> int:
        """Allocate the next epoch number and publish its member list
        through the substrate registry. Monotone by construction: the
        number comes from a store ADD."""
        n = self._epochs.propose(sorted(int(m) for m in members),
                                 reason, proposer=self.rank,
                                 prev=self.epoch)
        with self._lock:
            if n > self._pending:
                self._pending = n
        return n

    def read_epoch(self, n: int) -> Optional[dict]:
        return self._epochs.read(n)

    def current_commit(self) -> Optional[dict]:
        """The last committed epoch record published at ``cur`` (what a
        cold-started joiner reads to find the group)."""
        return self._epochs.current()

    def request_join(self) -> None:
        self.store.set(_ks.member_flag(self.ns, "join", self.rank),
                       json.dumps({"t": self.clock()}).encode())

    def form_initial(self) -> dict:
        """Rendezvous of the first epoch: rank 0 (or the lowest rank
        that showed up within the deadline) proposes every registered
        rank; everyone joins. Elastic from step one — a rank that never
        registers is simply left out."""
        deadline = time.monotonic() + self.cfg.timeout
        while time.monotonic() < deadline:
            regs = self._registered()
            if len(regs) >= self.world_hint:
                break
            time.sleep(0.02)
        regs = sorted(self._registered())
        if regs and self.rank == min(regs):
            self.propose(regs, "initial formation")
        return self.join()

    def join(self) -> dict:
        """Barrier-with-deadline: converge on the newest proposal,
        ack it, and wait for the commit. Every wait is bounded by
        ``cfg.timeout``; a member that fails to ack in time is shrunk
        out of a follow-up proposal instead of wedging the group.
        Returns the committed epoch record (the caller must check
        whether it is still a member)."""
        o = _obs()
        span = o.span("elastic.epoch", args={"rank": self.rank}) if o \
            else None
        try:
            if span:
                span.__enter__()
            return self._join_inner()
        finally:
            if span:
                span.__exit__(None, None, None)

    def _join_inner(self) -> dict:
        overall = time.monotonic() + 10 * self.cfg.timeout
        acked: set = set()
        while True:
            if time.monotonic() > overall:
                raise TimeoutError(
                    f"elastic join did not converge within "
                    f"{10 * self.cfg.timeout:.1f}s (rank {self.rank})")
            n = self.refresh_pending()
            if n <= self.epoch:
                # entered join() with no proposal out yet (e.g. via a
                # collective deadline): the acting coordinator builds
                # one from the lease table as soon as a change is
                # visible; everyone else waits for it
                now = self.clock()
                if self.i_am_acting(now):
                    made = self.watch_once(now)
                    if made is None:
                        time.sleep(min(0.05, self.cfg.beat_interval))
                        continue
                    n = made
                else:
                    time.sleep(min(0.05, self.cfg.beat_interval))
                    continue
            rec = self.read_epoch(n)
            if rec is None:
                time.sleep(0.01)
                continue
            members = rec["members"]
            if self.rank not in members:
                return rec      # demoted/excluded: caller rejoins
            if n not in acked:
                self._epochs.ack(n, self.rank)
                acked.add(n)
            committer = min(members)
            if committer == self.rank:
                done = self._commit_as_leader(n, members)
                if not done:
                    continue    # shrunk proposal published; next round
            else:
                if not self._await_commit(n):
                    continue    # deadline or superseded; next round
            self.epoch = n
            self.members = list(members)
            self.clear_hang()
            o = _obs()
            if o:
                o.registry.counter("elastic.epochs").inc()
                o.registry.gauge("elastic.members").set(len(members))
                o.flight_recorder.record(
                    "elastic.epoch_commit", epoch=n, members=members,
                    reason=rec.get("reason"))
            return rec

    def _commit_as_leader(self, n: int, members: List[int]) -> bool:
        deadline = time.monotonic() + self.cfg.timeout
        missing = [r for r in members if r != self.rank]
        while missing and time.monotonic() < deadline:
            missing = [r for r in missing
                       if not self._epochs.acked(n, r)]
            if missing:
                if self.refresh_pending() > n:
                    return False
                time.sleep(0.01)
        if missing:
            self.propose([r for r in members if r not in missing],
                         f"ack deadline: dropped {missing}")
            return False
        act = _faults.check("elastic.epoch_commit")
        if act is not None:
            _faults.apply(act)
        self._epochs.commit(n)
        return True

    def _await_commit(self, n: int) -> bool:
        deadline = time.monotonic() + self.cfg.timeout
        while time.monotonic() < deadline:
            if self._epochs.committed(n):
                return True
            if self.refresh_pending() > n:
                return False
            time.sleep(0.01)
        # committer missed its deadline: it is either dead (the next
        # scan will shrink it out) or slow — re-enter the loop either way
        return False
