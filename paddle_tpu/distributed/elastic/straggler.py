"""Straggler detection from per-rank step-time telemetry.

Every lease beat carries the rank's last step wall time
(``elastic.step_ms``); the acting coordinator feeds those samples into
one :class:`StragglerDetector` and flags ranks whose rolling median
exceeds the group's rolling p50 by a configurable factor
(``PADDLE_TPU_ELASTIC_STRAGGLER_FACTOR``). The policy hook decides what
a flag means: ``flag`` (default) is telemetry-only
(``elastic.stragglers`` gauge + ``on_straggler`` callback), ``demote``
drops the rank from the next membership epoch — the elastic analog of
the reference's slow-node blacklisting.
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List

__all__ = ["StragglerDetector"]


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class StragglerDetector:
    """Rolling per-rank step-time windows; pure (no clock, no store) so
    the policy is unit-testable with synthetic samples."""

    def __init__(self, factor: float = 3.0, window: int = 8,
                 min_samples: int = 3):
        self.factor = float(factor)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self._samples: Dict[int, Deque[float]] = {}

    def record(self, rank: int, step_ms: float) -> None:
        q = self._samples.setdefault(
            int(rank), deque(maxlen=self.window))
        q.append(float(step_ms))

    def forget(self, rank: int) -> None:
        self._samples.pop(int(rank), None)

    def medians(self) -> Dict[int, float]:
        return {r: _median(list(q)) for r, q in self._samples.items()
                if len(q) >= self.min_samples}

    def p50(self) -> float:
        meds = list(self.medians().values())
        return _median(meds) if meds else 0.0

    def flagged(self) -> List[int]:
        """Ranks whose rolling median exceeds ``factor`` x the group
        p50. A factor <= 0 disables detection. Needs at least two
        ranks with full windows — a lone rank cannot straggle behind
        itself."""
        if self.factor <= 0:
            return []
        meds = self.medians()
        if len(meds) < 2:
            return []
        p50 = _median(list(meds.values()))
        if p50 <= 0:
            return []
        return sorted(r for r, m in meds.items()
                      if m > self.factor * p50)
