"""Engine.fit elastic mode: membership + peer snapshots around the
existing training loop.

``Engine.fit(elastic=...)`` (or ``PADDLE_TPU_ELASTIC=1`` with a
multi-rank launch) attaches an :class:`ElasticContext`: each step
heartbeats the rank's lease with its step time, pushes a CRC-tagged
in-memory snapshot of the full per-rank train state every
``PADDLE_TPU_ELASTIC_SNAP_FREQ`` steps, and observes membership
changes at step boundaries as the typed ``EpochChanged`` — which the
Engine handles by re-joining the group and re-adopting the newest
snapshot (peer mailbox, falling back to the fit ``save_dir`` disk
manifest when replication is insufficient).

The Engine path replicates *full per-rank state* (its optimizer state
is already per-rank); the shard-remapped ZeRO recovery lives in
:mod:`.data_parallel`. ``resume=`` interaction: a disk resume
(``Engine.fit(resume=True)``) restores first, then elastic snapshots
start from the restored step — the two tiers compose, they don't
compete.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

from .membership import ElasticConfig, EpochChanged, \
    MembershipCoordinator
from .snapshots import PeerReplicator, SnapshotCorrupt, fetch_best

__all__ = ["ElasticContext"]


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


def _to_host(state: Dict) -> Dict:
    """Tensor-valued state dicts -> plain numpy for pickling."""
    out = {}
    for k, v in state.items():
        if hasattr(v, "_data"):
            out[k] = np.asarray(v._data)
        else:
            out[k] = v
    return out


class ElasticContext:
    """Bound to one ``fit`` call via :meth:`bind`; the Engine drives
    :meth:`step_begin` / :meth:`step_end` and routes ``EpochChanged``
    to :meth:`handle_epoch_change`."""

    def __init__(self, store, rank: int, world: int,
                 config: Optional[ElasticConfig] = None,
                 namespace: str = "elastic",
                 watchdog_hook: bool = True):
        self.cfg = config or ElasticConfig()
        self.rank = int(rank)
        self.coord = MembershipCoordinator(
            store, self.rank, int(world), config=self.cfg,
            namespace=namespace)
        self.replicator = PeerReplicator(
            store, self.rank, namespace=namespace,
            snap_freq=self.cfg.snap_freq)
        self._watchdog_hook = bool(watchdog_hook)
        self._collect: Optional[Callable[[], Dict]] = None
        self._adopt: Optional[Callable[[Dict], int]] = None
        self._started = False

    @classmethod
    def from_env(cls) -> "ElasticContext":
        import os

        from ..store import create_or_get_global_tcp_store

        return cls(create_or_get_global_tcp_store(),
                   int(os.environ.get("PADDLE_TRAINER_ID", "0")),
                   int(os.environ.get("PADDLE_TRAINERS_NUM", "1")))

    # ----------------------------------------------------------- wiring
    def bind(self, collect: Callable[[], Dict],
             adopt: Callable[[Dict], int]) -> None:
        """``collect() -> state_dict`` snapshots the live train state;
        ``adopt(state_dict) -> step`` installs one."""
        self._collect = collect
        self._adopt = adopt

    def start(self) -> None:
        if self._started:
            return
        self.coord.register()
        if self._watchdog_hook:
            self.coord.install_watchdog_hook()
        self.coord.form_initial()
        self._started = True

    def stop(self) -> None:
        if self._started:
            self.coord.deregister()
            self._started = False

    # ------------------------------------------------------------ steps
    def step_begin(self, step: int) -> None:
        if not self._started:
            self.start()
        self.coord.refresh_pending()
        self.coord.poll()

    def step_end(self, step: int, step_ms: float) -> None:
        self.coord.heartbeat(step, step_ms)
        self.maybe_snapshot(step)
        # step-synchronous scan: joiners are admitted here (not by the
        # timer thread) so expansions land on a gate-determined step
        self.coord.watch_once()

    # --------------------------------------------------------- recovery
    def handle_epoch_change(self, exc: EpochChanged,
                            disk_restore: Optional[Callable[[], int]]
                            = None) -> Optional[int]:
        """Re-join the group and re-adopt the newest snapshot of THIS
        rank (own mailbox push; ``disk_restore()`` — e.g. the Engine's
        manifest restore — as the fallback tier). Returns the step to
        resume from, or None when no snapshot had to be re-adopted."""
        t0 = time.monotonic()
        while True:
            rec = self.coord.join()
            if self.rank in rec["members"]:
                break
            self.coord.clear_hang()
            self.coord.request_join()
            time.sleep(0.05)
        source, step = "none", None
        prev_rec = None
        try:
            prev_rec = self.coord.read_epoch(int(rec.get("prev", 0)))
        except Exception:
            prev_rec = None
        if prev_rec is not None and \
                self.rank in prev_rec.get("members", ()):
            # continuing member of the previous epoch: the live train
            # state is NEWER than any snapshot — the epoch change only
            # re-scoped the group around this rank (a peer died or
            # left). Rewinding here would replay steps for nothing.
            source = "live"
        elif self._adopt is not None:
            try:
                snap = fetch_best(self.coord.store, self.coord.ns,
                                  self.rank, self.cfg.max_nodes)
                if snap is not None:
                    step = self._adopt(snap["state"])
                    source = "peer"
            except SnapshotCorrupt:
                snap = None
            if step is None and disk_restore is not None:
                step = disk_restore()
                source = "disk"
        o = _obs()
        if o:
            o.registry.counter("elastic.recoveries",
                               tags={"source": source}).inc()
            o.registry.histogram("elastic.recovery_ms").observe(
                (time.monotonic() - t0) * 1000.0)
        return step

    def snapshot_now(self, step: int) -> None:
        if self._collect is not None:
            self.replicator.push(step, self.coord.members,
                                 {"state": _to_host(self._collect())})

    def maybe_snapshot(self, step: int) -> None:
        if self._collect is not None:
            self.replicator.maybe_push(
                step, self.coord.members,
                lambda: {"state": _to_host(self._collect())})
