"""One control plane: the lease/membership/failover substrate every
resilience stack in this tree shares.

Before this package, three tiers each carried their own copy of the
same machinery — elastic DP membership
(``distributed/elastic/membership.py``), PS shard failover
(``distributed/ps/replication.py``), and the serving cluster's manual
``fail_all()`` crash path (``serving/cluster/``). The shared pieces now
live here, once:

- :mod:`store_util` — the atomic get-or-None ``try_get`` (formerly
  duplicated) and :class:`LocalStore`, the in-process store for
  single-host consumers and tests;
- :mod:`lease` — store-backed heartbeat leases (``{ns}/beat/{member}``)
  with clean-leave markers and, via :class:`LeaseTable`, generation
  fencing (stale-generation beats are rejected, not written);
- :mod:`epochs` — propose/ack/commit membership epochs with monotone
  numbers from a store ADD, plus the typed :class:`EpochChanged`
  failover event.

The elastic and PS tiers are thin consumers: same keys, same payloads,
same write order — their multi-process drills stay bit-exact. The
serving cluster is the first NEW consumer
(:class:`paddle_tpu.serving.cluster.ClusterControlPlane`): replicas
hold leases the router discovers and evicts on, and the autoscaler
scales the pool through the same epochs.

Fault sites: ``cp.lease`` (``drop`` loses one beat on the wire) and
``cp.epoch`` (``delay`` holds a commit open) make substrate races
injectable with the standard ``PADDLE_TPU_FAULT_PLAN`` plans.

:func:`snapshot_all` feeds the flight-recorder debug bundle's
``control_plane.json`` section: every live lease table, epoch registry,
and registered plane (e.g. the cluster's), best-effort.
"""
from __future__ import annotations

import weakref
from typing import List

from .epochs import EpochChanged, EpochRegistry  # noqa: F401
from .lease import (LeaseTable, lease_fresh, read_beat,  # noqa: F401
                    scan_beats, write_beat)
from .store_util import LocalStore, try_get  # noqa: F401

__all__ = ["try_get", "LocalStore", "LeaseTable", "EpochRegistry",
           "EpochChanged", "write_beat", "read_beat", "scan_beats",
           "lease_fresh", "register_plane", "snapshot_all"]

# weak registry of composite control planes (objects exposing a
# .snapshot() with epoch+members+leases+transitions, like the serving
# cluster's) — the bundle's richest section when one is live
_planes: "weakref.WeakSet" = weakref.WeakSet()


def register_plane(plane) -> None:
    """Register a composite control plane for :func:`snapshot_all`
    (weakly held — no lifecycle management needed)."""
    _planes.add(plane)


def snapshot_all() -> dict:
    """Best-effort snapshot of every live substrate object — what
    ``dump_debug_bundle`` writes as ``control_plane.json``."""
    from . import epochs as _epochs
    from . import lease as _lease

    def _collect(objs) -> List[dict]:
        out: List[dict] = []
        for obj in list(objs):
            try:
                out.append(obj.snapshot())
            except Exception:
                continue
        return out

    return {"planes": _collect(_planes),
            "leases": _collect(_lease._live),
            "epochs": _collect(_epochs._live)}
