"""Store primitives shared by every control-plane consumer.

``try_get`` used to live twice — once in ``elastic/membership.py`` and
once (implicitly, via that import) behind ``ps/replication.py`` — and
both copies existed for the same reason: deletable keys (leases,
registries, mailboxes) must be read get-or-None ATOMICALLY, because
check-then-get races a concurrent delete and the blocking ``get`` then
stalls for the full store timeout. This module is now the one home of
that helper; the elastic and PS modules re-export it.

:class:`LocalStore` is the substrate's store for single-process
consumers — the serving cluster's in-process replica pool, and the
deterministic control-plane tests. It implements the same client
surface the lease/epoch layers use on ``TCPStore`` (``set`` / ``get`` /
``add`` / ``check`` / ``delete`` / ``try_get``), with ``add`` atomic
under one lock — the monotone-counter primitive generation fencing and
epoch numbering are built on.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

__all__ = ["try_get", "LocalStore"]


def try_get(store, key: str) -> Optional[bytes]:
    """Atomic get-or-None through the store's ``try_get`` when it has
    one (``TCPStore``/``PrefixStore``); check-then-get otherwise (fake
    stores in tests). Deletable keys — leases, registries, mailboxes —
    MUST be read this way: check-then-get races a concurrent delete and
    the blocking ``get`` then stalls for the full store timeout."""
    fn = getattr(store, "try_get", None)
    if fn is not None:
        return fn(key)
    if not store.check(key):
        return None
    return store.get(key)


class LocalStore:
    """Thread-safe in-process KV store with the TCPStore client
    surface. No blocking ``get``-with-timeout semantics: every consumer
    in this tree reads deletable keys through :func:`try_get`, and a
    missing key on a plain ``get`` is a programming error (KeyError)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, bytes] = {}  # guarded by: _lock

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = bytes(value)

    def get(self, key: str) -> bytes:
        with self._lock:
            return self._data[key]

    def try_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def check(self, key: str) -> bool:
        with self._lock:
            return key in self._data

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._data.pop(key, None) is not None

    def add(self, key: str, n: int) -> int:
        """Atomic counter bump; returns the new value (``add(k, 0)``
        reads without bumping — the TCPStore idiom)."""
        with self._lock:
            cur = int(self._data.get(key, b"0"))
            cur += int(n)
            self._data[key] = str(cur).encode()
            return cur

    def num_keys(self) -> int:
        with self._lock:
            return len(self._data)

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))
