"""Propose/ack/commit membership epochs over any store.

The epoch protocol the elastic tier built (PR 13), extracted so every
membership consumer speaks the same keys:

- epoch numbers are monotone by construction — allocated from the
  ``{ns}/seq`` counter with a store ADD;
- the proposal record lives at ``{ns}/epoch/{n}`` (``epoch`` /
  ``members`` / ``reason`` / ``proposer`` / ``prev``) and is advertised
  at ``{ns}/propose``;
- members ack at ``{ns}/epoch/{n}/ack/{member}``;
- the committer publishes ``{ns}/epoch/{n}/commit`` and repoints the
  ``{ns}/cur`` pointer — what a cold joiner reads to find the group.

WHO proposes, WHO must ack, and WHO commits stay consumer policy (the
elastic tier elects the lowest fresh rank; the serving cluster's router
is the sole committer) — this module only owns the key layout and the
write order, which is what keeps the refactored consumers bit-exact.

:class:`EpochChanged` is the typed failover event raised into in-flight
work when membership moves; it moved here from ``elastic/membership.py``
(which re-exports it, so every existing ``except EpochChanged`` keeps
catching the same class).

Fault site ``cp.epoch``: checked at commit time (``delay`` holds the
commit past a member's deadline, the classic split-window race).
"""
from __future__ import annotations

import json
import threading
import time
import weakref
from collections import deque
from typing import Callable, List, Optional

from ..resilience import faults as _faults
from . import keyspace as _ks
from .store_util import try_get

__all__ = ["EpochChanged", "EpochRegistry"]


class EpochChanged(RuntimeError):
    """The group membership changed while work was in flight. Carries
    the highest epoch proposal seen; callers re-join via their
    coordinator and resume under the new epoch.
    """

    def __init__(self, epoch: int, reason: str = ""):
        super().__init__(
            f"group epoch changed (epoch={epoch}): {reason}")
        self.epoch = epoch
        self.reason = reason


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


# weak registry of live epoch registries for the flight-recorder bundle
_live: "weakref.WeakSet[EpochRegistry]" = weakref.WeakSet()


class EpochRegistry:
    """One namespace's epoch log. Stateless with respect to membership
    policy: it allocates numbers, stores records, and tracks the
    propose/ack/commit keys."""

    def __init__(self, store, namespace: str,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.ns = str(namespace)
        self.clock = clock
        self._lock = threading.Lock()
        self._transitions: deque = deque(maxlen=32)  # guarded by: _lock
        _live.add(self)

    def _note(self, kind: str, n: int, **fields) -> None:
        with self._lock:
            self._transitions.append(
                {"t": self.clock(), "kind": kind, "epoch": n, **fields})

    # ---------------------------------------------------------- propose
    def propose(self, members: List, reason: str, proposer=None,
                prev: int = 0) -> int:
        """Allocate the next epoch number and publish its member list.
        Monotone by construction: the number comes from a store ADD.
        ``members`` is stored as given — callers normalize (the elastic
        tier sorts int ranks; the cluster sorts replica names)."""
        n = self.store.add(_ks.epoch_seq(self.ns), 1)
        rec = {"epoch": n, "members": list(members), "reason": reason,
               "proposer": proposer, "prev": prev}
        self.store.set(_ks.epoch(self.ns, n), json.dumps(rec).encode())
        self.store.set(_ks.propose(self.ns), str(n).encode())
        self._note("propose", n, members=list(members), reason=reason)
        return n

    def pending(self) -> int:
        """Highest advertised proposal number (0 when none)."""
        try:
            raw = try_get(self.store, _ks.propose(self.ns))
            return int(raw.decode()) if raw is not None else 0
        except Exception:
            return 0

    def read(self, n: int) -> Optional[dict]:
        try:
            raw = try_get(self.store, _ks.epoch(self.ns, n))
            return None if raw is None else json.loads(raw.decode())
        except Exception:
            return None

    # -------------------------------------------------------------- ack
    def ack(self, n: int, member) -> None:
        self.store.set(_ks.epoch_ack(self.ns, n, member), b"1")

    def acked(self, n: int, member) -> bool:
        try:
            return self.store.check(_ks.epoch_ack(self.ns, n, member))
        except Exception:
            return False

    # ------------------------------------------------------------ commit
    def commit(self, n: int) -> None:
        """Publish the commit marker and repoint ``cur``. Fault site
        ``cp.epoch`` fires here — a delayed commit is how the
        split-epoch races are injected."""
        act = _faults.check("cp.epoch")
        if act is not None:
            _faults.apply(act)
        self.store.set(_ks.epoch_commit(self.ns, n), b"1")
        self.store.set(_ks.epoch_cur(self.ns), str(n).encode())
        rec = self.read(n) or {}
        self._note("commit", n, members=rec.get("members"),
                   reason=rec.get("reason"))
        o = _obs()
        if o:
            o.registry.counter("cp.epochs").inc()
            if rec.get("members") is not None:
                o.registry.gauge("cp.members").set(
                    len(rec["members"]))

    def committed(self, n: int) -> bool:
        try:
            return self.store.check(_ks.epoch_commit(self.ns, n))
        except Exception:
            return False

    def current(self) -> Optional[dict]:
        """The last committed epoch record published at ``cur``."""
        try:
            raw = try_get(self.store, _ks.epoch_cur(self.ns))
            return None if raw is None else self.read(int(raw.decode()))
        except Exception:
            return None

    # --------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        with self._lock:
            transitions = list(self._transitions)
        return {"kind": "epoch_registry", "ns": self.ns,
                "pending": self.pending(), "current": self.current(),
                "transitions": transitions}
