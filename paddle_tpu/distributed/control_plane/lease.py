"""Store-backed heartbeat leases with generation fencing.

The lease discipline every resilience stack in this tree converged on
(elastic DP membership, PS shard failover, and now the serving
cluster's replica pool): each member periodically writes a JSON beat at
``{ns}/beat/{member}`` carrying at least ``{"t": clock()}``; a beat
older than the namespace's lease timeout is EXPIRED and the member is
presumed dead. A member that leaves on purpose writes a ``left`` marker
first, so survivors can tell a planned departure from a crash — the
clean-leave vs missed-beat disambiguation the drills assert on.

Module-level primitives (``write_beat`` / ``read_beat`` /
``scan_beats`` / ``lease_fresh``) operate on any store with the
TCPStore client surface and keep the exact key/payload layout the
elastic and PS tiers already speak, so those tiers delegate here
without changing a byte on the wire.

:class:`LeaseTable` adds **generation fencing** on top: ``grant``
bumps a per-member monotone counter (store ADD at
``{ns}/lease_gen/{member}``) and every fenced ``beat`` presents its
generation — a beat carrying a stale generation (a zombie that was
already replaced) is REJECTED, never written. That is the same fencing
idea the PS shard map uses (``ps/gen``), lifted to the lease layer.

Fault site ``cp.lease``: ``drop`` skips one beat write (a lost beat on
the wire — peers see a missed-beat expiry); generic kinds go through
``faults.apply``.
"""
from __future__ import annotations

import json
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, List, Optional

from ..resilience import faults as _faults
from . import keyspace as _ks
from .store_util import try_get

__all__ = ["write_beat", "read_beat", "scan_beats", "lease_fresh",
           "LeaseTable"]


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


def write_beat(store, ns: str, member, payload: dict) -> bool:
    """Write one lease beat (the caller builds the payload, including
    ``t``). Returns False when the beat was dropped by fault site
    ``cp.lease`` — callers that count their own beats (the elastic
    tier's ``elastic.heartbeats``) must not count a dropped one."""
    act = _faults.check("cp.lease")
    if act is not None:
        if act.kind == "drop":
            return False
        _faults.apply(act)
    # blessed low-level writer: the payload is assembled (and gen-
    # fenced) one hop up in LeaseTable.beat; this function is the one
    # wire-format point for unfenced module-level callers too
    store.set(_ks.beat(ns, member),  # ptlint: disable=fence-discipline
              json.dumps(payload).encode())
    o = _obs()
    if o:
        o.registry.counter("cp.beats").inc()
    return True


def read_beat(store, ns: str, member) -> Optional[dict]:
    """Decode one member's lease, or None (never set / undecodable)."""
    try:
        raw = try_get(store, _ks.beat(ns, member))
        if raw is None:
            return None
        return json.loads(raw.decode())
    except Exception:
        return None


def scan_beats(store, ns: str, members, now: float,
               timeout: float) -> Dict:
    """``{member: beat_or_None}`` where expired leases map to None."""
    out: Dict = {}
    for m in members:
        b = read_beat(store, ns, m)
        if b is not None and now - float(b.get("t", 0.0)) > timeout:
            b = None
        out[m] = b
    return out


def lease_fresh(store, ns: str, member, now: float,
                timeout: float) -> bool:
    b = read_beat(store, ns, member)
    return b is not None and now - float(b.get("t", 0.0)) <= timeout


# weak registry of live lease tables so the flight-recorder bundle can
# dump every namespace's lease view without plumbing handles
_live: "weakref.WeakSet[LeaseTable]" = weakref.WeakSet()


class LeaseTable:
    """One namespace's lease view with generation fencing. Purely
    store-backed and clock-injectable: tests drive it with ManualClock
    and zero sleeps — freshness is a function of (beats, now), never of
    wall time."""

    def __init__(self, store, namespace: str, timeout: float,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.ns = str(namespace)
        self.timeout = float(timeout)
        self.clock = clock
        self._lock = threading.Lock()
        self._seen: List = []       # grant order, guarded by: _lock
        _live.add(self)

    def _note(self, member) -> None:
        with self._lock:
            if member not in self._seen:
                self._seen.append(member)

    # ------------------------------------------------------------ grant
    def grant(self, member, **fields) -> int:
        """Admit ``member``: bump its fencing generation, clear any
        stale clean-leave marker, and write the first beat. Returns the
        generation the member must present on every subsequent fenced
        beat — an older holder of the same name is now a zombie whose
        writes get rejected."""
        gen = self.store.add(_ks.lease_gen(self.ns, member), 1)
        try:
            self.store.delete(_ks.left(self.ns, member))
        except Exception:
            pass
        self._note(member)
        self.beat(member, gen=gen, **fields)
        return gen

    def generation(self, member) -> int:
        return self.store.add(_ks.lease_gen(self.ns, member), 0)

    # ------------------------------------------------------------- beat
    def beat(self, member, gen: Optional[int] = None, **fields) -> bool:
        """One fenced lease beat. A beat presenting a generation older
        than the member's current one is rejected (returns False,
        nothing written) — the stale writer was replaced and must not
        resurrect its lease. ``gen=None`` writes unfenced (the caller
        manages fencing elsewhere)."""
        if gen is not None and int(gen) < self.generation(member):
            o = _obs()
            if o:
                o.registry.counter("cp.fenced_rejects").inc()
            return False
        self._note(member)
        payload = {"t": self.clock(), **fields}
        if gen is not None:
            payload["gen"] = int(gen)
        return write_beat(self.store, self.ns, member, payload)

    def read(self, member) -> Optional[dict]:
        return read_beat(self.store, self.ns, member)

    def fresh(self, member, now: Optional[float] = None) -> bool:
        now = self.clock() if now is None else now
        return lease_fresh(self.store, self.ns, member, now,
                           self.timeout)

    def scan(self, members: Iterable,
             now: Optional[float] = None) -> Dict:
        now = self.clock() if now is None else now
        return scan_beats(self.store, self.ns, members, now,
                          self.timeout)

    def missed(self, members: Iterable,
               now: Optional[float] = None) -> List:
        """Members whose lease EXPIRED without a clean-leave marker —
        the presumed-dead set. A member that ``leave()``d is never
        reported here: that is the clean-leave vs missed-beat
        disambiguation."""
        beats = self.scan(members, now)
        return [m for m, b in beats.items()
                if b is None and not self.left(m)]

    # ------------------------------------------------------------ leave
    def leave(self, member) -> None:
        """Planned departure: publish the ``left`` marker FIRST (so a
        scan between the two writes still sees a clean leave), then
        drop the beat."""
        try:
            self.store.set(_ks.left(self.ns, member),
                           json.dumps({"t": self.clock()}).encode())
        except Exception:
            pass
        try:
            self.store.delete(_ks.beat(self.ns, member))
        except Exception:
            pass

    def left(self, member) -> bool:
        try:
            return self.store.check(_ks.left(self.ns, member))
        except Exception:
            return False

    def forget(self, member) -> None:
        """Drop every key of a member whose departure has been fully
        processed (evicted or cleanly left) so the namespace does not
        accumulate tombstones."""
        for key in (_ks.beat(self.ns, member),
                    _ks.left(self.ns, member)):
            try:
                self.store.delete(key)
            except Exception:
                pass
        with self._lock:
            if member in self._seen:
                self._seen.remove(member)

    # --------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """JSON-able lease view (the ``control_plane.json`` bundle
        section): every member this table has seen, with its last beat,
        freshness, fencing generation, and leave marker."""
        now = self.clock()
        with self._lock:
            seen = list(self._seen)
        members = {}
        for m in seen:
            b = self.read(m)
            members[str(m)] = {
                "beat": b,
                "fresh": b is not None and
                now - float(b.get("t", 0.0)) <= self.timeout,
                "generation": self.generation(m),
                "left": self.left(m),
            }
        return {"kind": "lease_table", "ns": self.ns,
                "timeout": self.timeout, "now": now,
                "members": members}
