"""The declared keyspace of the control-plane store.

Every key written to or read from the control-plane store (leases,
epochs, elastic membership, PS failover/replication, the cluster KV
index) MUST be built by one of the helpers below — ptlint's
``store-keys`` pass rejects inline f-strings/concats at store call
sites in the protocol tiers, and :func:`check_collisions` proves no
two namespaces can ever produce the same key string.

stdlib-only and import-cycle-free: loaded standalone by ptlint via
``importlib.util.spec_from_file_location``.

Scope note: rendezvous/bootstrap keys (``distributed/rpc.py``,
``process_group.py``, ``launch/``, ``fleet/``) are deliberately NOT in
this registry — they live on the per-job init store, are written once
before any failover machinery starts, and are never subject to the
lease/epoch delete races this keyspace exists to police.

Each namespace declares two protocol flags the ``fence-discipline``
pass enforces:

* ``deletable`` — keys in this namespace may be absent or concurrently
  deleted; reads must go through ``try_get`` (never raw ``store.get``,
  the PR 13 check-then-get race class).
* ``fenced`` — written payloads must carry the writer's lease
  generation (obtained from ``LeaseTable.grant``/``generation()``) so
  stale owners are rejected by readers, not trusted.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, NamedTuple, Tuple

__all__ = [
    "KeyNamespace", "NAMESPACES", "HELPERS", "check_collisions",
    "beat", "lease_gen", "left",
    "epoch_seq", "epoch", "propose", "epoch_ack", "epoch_commit",
    "epoch_cur",
    "node", "member_flag", "xchg", "snap",
    "ps_primary", "ps_gen", "ps_repl", "ps_replack",
    "kvidx",
]

# placeholder marker inside a pattern; literals are plain strings.
# "<ns>" is the caller's namespace prefix (e.g. "cluster", "elastic");
# every other placeholder is a single key segment (no "/").
_P = "<ns>"


class KeyNamespace(NamedTuple):
    name: str               # registry id == the helper function name
    pattern: Tuple[str, ...]  # key segments; "<...>" = placeholder
    deletable: bool         # reads must use try_get
    fenced: bool            # written payloads must carry a lease gen
    doc: str


_N = KeyNamespace

NAMESPACES: Tuple[KeyNamespace, ...] = (
    # ---------------------------------------------------------- lease
    _N("beat", (_P, "beat", "<member>"), True, True,
       "Heartbeat lease doc {t, gen}; expiry = death, so deletable; "
       "gen-fenced so a stale owner's beat is rejected."),
    _N("lease_gen", (_P, "lease_gen", "<member>"), False, False,
       "Monotone lease generation counter (store ADD only)."),
    _N("left", (_P, "left", "<member>"), True, False,
       "Clean-leave marker; deleted on re-grant."),
    # --------------------------------------------------------- epochs
    _N("epoch_seq", (_P, "seq"), False, False,
       "Monotone epoch number source (store ADD only)."),
    _N("epoch", (_P, "epoch", "<n>"), True, False,
       "Immutable epoch record; absent until proposed."),
    _N("propose", (_P, "propose"), True, False,
       "Latest proposed epoch number; absent before first proposal."),
    _N("epoch_ack", (_P, "epoch", "<n>", "ack", "<member>"), False,
       False, "Per-member epoch ack flag (check/set only)."),
    _N("epoch_commit", (_P, "epoch", "<n>", "commit"), False, False,
       "Epoch commit flag (check/set only)."),
    _N("epoch_cur", (_P, "cur"), True, False,
       "Latest committed epoch number; absent before first commit."),
    # ---------------------------------------------- elastic membership
    _N("node", (_P, "nodes", "<rank>"), True, False,
       "Elastic member registration doc; deleted on leave."),
    _N("member_flag", (_P, "<kind>", "<rank>"), True, False,
       "Member condition flags: suspect|hang|join|demote; set and "
       "deleted by the watch loops."),
    _N("xchg", (_P, "x", "<epoch>", "<tag>", "<step>", "<rank>"),
       True, False,
       "Epoch-scoped payload exchange slots (peer snapshots, CRCs)."),
    _N("snap", (_P, "snap", "<src>", "<dst>"), True, False,
       "Ring-neighbor peer snapshot blobs."),
    # ------------------------------------------------------------- ps
    _N("ps_primary", ("ps", "primary", "<shard>"), True, False,
       "Current primary server index of one PS shard."),
    _N("ps_gen", ("ps", "gen"), False, False,
       "PS primary-map generation counter (store ADD only)."),
    _N("ps_repl", ("ps", "repl", "<shard>", "<n>"), True, False,
       "Ordered replication log record n of one shard."),
    _N("ps_replack", ("ps", "replack", "<shard>"), True, False,
       "Backup ack high-water mark of one shard."),
    # ------------------------------------------------------- kv index
    _N("kvidx", (_P, "kvidx", "<hash>"), True, True,
       "Cluster KV prefix-index doc per chain hash; entries carry the "
       "registering replica's lease gen; deleted when empty."),
)

_BY_NAME: Dict[str, KeyNamespace] = {n.name: n for n in NAMESPACES}
assert len(_BY_NAME) == len(NAMESPACES), "duplicate namespace"

# the helper names the store-keys pass accepts at store call sites
HELPERS = frozenset(_BY_NAME)

# member_flag's <kind> placeholder is constrained — an open kind would
# collide with sibling namespaces (beat, nodes, ...)
FLAG_KINDS = ("suspect", "hang", "join", "demote")


def _seg(v) -> str:
    s = str(v)
    if "/" in s or not s:
        raise ValueError("bad key segment %r (empty or contains '/')"
                         % (s,))
    return s


def _join(ns: str, *parts) -> str:
    return "/".join([_seg(ns)] + [_seg(p) for p in parts])


# ------------------------------------------------------------- lease
def beat(ns: str, member) -> str:
    return _join(ns, "beat", member)


def lease_gen(ns: str, member) -> str:
    return _join(ns, "lease_gen", member)


def left(ns: str, member) -> str:
    return _join(ns, "left", member)


# ------------------------------------------------------------ epochs
def epoch_seq(ns: str) -> str:
    return _join(ns, "seq")


def epoch(ns: str, n) -> str:
    return _join(ns, "epoch", int(n))


def propose(ns: str) -> str:
    return _join(ns, "propose")


def epoch_ack(ns: str, n, member) -> str:
    return _join(ns, "epoch", int(n), "ack", member)


def epoch_commit(ns: str, n) -> str:
    return _join(ns, "epoch", int(n), "commit")


def epoch_cur(ns: str) -> str:
    return _join(ns, "cur")


# ------------------------------------------------ elastic membership
def node(ns: str, rank) -> str:
    return _join(ns, "nodes", rank)


def member_flag(ns: str, kind: str, rank) -> str:
    if kind not in FLAG_KINDS:
        raise ValueError("unknown member flag kind %r (want one of %r)"
                         % (kind, FLAG_KINDS))
    return _join(ns, kind, rank)


def xchg(ns: str, epoch_n, tag, step, rank) -> str:
    return _join(ns, "x", epoch_n, tag, step, rank)


def snap(ns: str, src, dst) -> str:
    return _join(ns, "snap", src, dst)


# ---------------------------------------------------------------- ps
def ps_primary(shard) -> str:
    return _join("ps", "primary", shard)


def ps_gen() -> str:
    return _join("ps", "gen")


def ps_repl(shard, n) -> str:
    return _join("ps", "repl", shard, n)


def ps_replack(shard) -> str:
    return _join("ps", "replack", shard)


# ---------------------------------------------------------- kv index
def kvidx(ns: str, h) -> str:
    return _join(ns, "kvidx", int(h))


# ------------------------------------------------ collision analysis
def _expand(n: KeyNamespace) -> Iterable[Tuple[str, ...]]:
    """Concrete pattern variants: member_flag's <kind> is a closed
    enum, so expand it — collision math then treats every remaining
    placeholder as matching any single segment."""
    if n.name != "member_flag":
        yield n.pattern
        return
    for kind in FLAG_KINDS:
        yield tuple(kind if s == "<kind>" else s for s in n.pattern)


def _may_collide(a: Tuple[str, ...], b: Tuple[str, ...]) -> bool:
    if len(a) != len(b):
        return False            # segments never contain "/" (_seg)
    for sa, sb in zip(a, b):
        wild_a = sa.startswith("<")
        wild_b = sb.startswith("<")
        if not wild_a and not wild_b and sa != sb:
            return False
    return True


def check_collisions() -> List[str]:
    """Pairs of namespaces that could produce the same key string.
    Empty list == the keyspace is collision-free (asserted by ptlint
    and the unit tests)."""
    problems: List[str] = []
    names = sorted(_BY_NAME)
    for i, na in enumerate(names):
        for nb in names[i + 1:]:
            for pa in _expand(_BY_NAME[na]):
                for pb in _expand(_BY_NAME[nb]):
                    if _may_collide(pa, pb):
                        problems.append(
                            "%s (%s) may collide with %s (%s)"
                            % (na, "/".join(pa), nb, "/".join(pb)))
    return problems


assert not check_collisions(), check_collisions()
