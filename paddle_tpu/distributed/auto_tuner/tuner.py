"""Parallelism auto-tuner: grid + prune search over dp/mp/pp/sharding/
micro-batch configs (reference: python/paddle/distributed/auto_tuner/ —
tuner.py:21 AutoTuner, search.py GridSearch, prune.py rules).

The reference launches a trial job per candidate; here each trial runs a
user-supplied ``run_fn(cfg) -> metric`` (typically wrapping a jit-compiled
few-step benchmark on the target mesh), which maps better onto the
single-controller TPU model — trials reuse the warm process instead of
re-spawning a cluster.
"""
from __future__ import annotations

import itertools
import json
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

__all__ = ["Config", "AutoTuner", "default_candidates", "prune_by_memory",
           "estimate_memory_bytes", "launch_trial_run_fn"]


@dataclass
class Config:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sharding_stage: int = 1
    micro_batch_size: int = 1
    use_recompute: bool = False
    extra: Dict = field(default_factory=dict)

    def degree_product(self) -> int:
        return self.dp_degree * self.mp_degree * self.pp_degree \
            * self.sharding_degree

    def to_dict(self):
        return asdict(self)


def default_candidates(num_devices: int, global_batch_size: int,
                       num_layers: Optional[int] = None,
                       vocab_divisor: int = 1) -> List[Config]:
    """Grid generation + hard pruning (reference: search.py GridSearch +
    prune.py _prune_by_* rules)."""
    out = []

    def divisors(n):
        return [d for d in range(1, n + 1) if n % d == 0]

    for dp, mp, pp in itertools.product(divisors(num_devices), repeat=3):
        for shard in divisors(num_devices):
            base = dp * mp * pp * shard
            if base != num_devices:
                continue
            # prune: pp must divide layer count (reference prune.py)
            if num_layers is not None and pp > 1 and num_layers % pp:
                continue
            # prune: mp must divide the vocab/hidden divisor
            if vocab_divisor > 1 and vocab_divisor % mp:
                continue
            # prune: dp*shard must divide global batch
            if global_batch_size % (dp * shard):
                continue
            local_batch = global_batch_size // (dp * shard)
            for mbs in divisors(local_batch):
                for rc in (False, True):
                    out.append(Config(
                        dp_degree=dp, mp_degree=mp, pp_degree=pp,
                        sharding_degree=shard, micro_batch_size=mbs,
                        use_recompute=rc))
    return out


def prune_by_memory(candidates: List[Config], model_bytes: int,
                    hbm_bytes: int, optimizer_multiplier: float = 3.0
                    ) -> List[Config]:
    """Drop configs whose estimated per-chip weight+state footprint
    exceeds HBM (reference: prune.py memory rules; estimate only — real
    activation memory is measured by the trial itself)."""
    keep = []
    for c in candidates:
        shards = c.mp_degree * c.pp_degree * (
            c.sharding_degree if c.sharding_stage >= 1 else 1)
        est = model_bytes * (1 + optimizer_multiplier) / max(shards, 1)
        if c.use_recompute:
            est *= 0.9
        if est <= hbm_bytes:
            keep.append(c)
    return keep


def estimate_memory_bytes(cfg: Config, *, num_layers: int, hidden: int,
                          vocab: int, seq_len: int,
                          ffn_mult: int = 4, param_bytes: int = 2,
                          moment_bytes: int = 6, grad_bytes: int = 2
                          ) -> int:
    """Per-chip memory cost model (reference: auto_tuner cost models,
    prune.py memory rules): weights + optimizer states sharded by
    mp*pp*sharding, plus activation stash for the 1F1B steady state
    (pp in-flight micro-batches; remat reduces the stash to block
    boundaries)."""
    per_layer = (4 + 2 * ffn_mult) * hidden * hidden
    n_params = num_layers * per_layer + vocab * hidden
    shards = cfg.mp_degree * cfg.pp_degree * max(cfg.sharding_degree, 1)
    state = n_params * (param_bytes + moment_bytes + grad_bytes) / shards
    # activations: per-microbatch per-layer stash, pp micro-batches deep
    act_per_tok = hidden * (2 if cfg.use_recompute else (10 + 2 * ffn_mult))
    layers_here = num_layers / max(cfg.pp_degree, 1)
    act = (cfg.micro_batch_size * seq_len * act_per_tok * layers_here
           * max(cfg.pp_degree, 1) * param_bytes / max(cfg.mp_degree, 1))
    return int(state + act)


def launch_trial_run_fn(script: str, nproc_per_node: int = 1,
                        timeout: float = 600.0, log_dir: str = "tuner_log",
                        metric_key: str = "metric"):
    """Trial-JOB mode (reference: the auto-tuner launching one
    distributed job per candidate via paddle.distributed.launch): returns
    a ``run_fn(cfg) -> float`` that launches ``script`` through the
    launch CLI with the candidate exported as ``AUTO_TUNER_CONFIG``
    (json) and reads the metric the trial writes to
    ``$AUTO_TUNER_METRIC_FILE`` (json: {"metric": <float>})."""
    import os
    import subprocess
    import sys
    import tempfile

    def run_fn(cfg: Config) -> float:
        with tempfile.TemporaryDirectory() as td:
            metric_file = os.path.join(td, "metric.json")
            env = dict(os.environ)
            env["AUTO_TUNER_CONFIG"] = json.dumps(cfg.to_dict())
            env["AUTO_TUNER_METRIC_FILE"] = metric_file
            out = subprocess.run(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--nproc_per_node", str(nproc_per_node),
                 "--max_restart", "0", "--log_dir", log_dir, script],
                env=env, capture_output=True, text=True, timeout=timeout)
            if out.returncode != 0:
                raise RuntimeError(
                    f"trial failed rc={out.returncode}: "
                    f"{(out.stdout + out.stderr)[-400:]}")
            with open(metric_file) as f:
                return float(json.load(f)[metric_key])

    return run_fn


class AutoTuner:
    """reference: auto_tuner/tuner.py:21."""

    def __init__(self, candidates: List[Config],
                 run_fn: Callable[[Config], float],
                 mode: str = "max", max_trials: Optional[int] = None,
                 log_path: Optional[str] = None):
        self.candidates = list(candidates)
        self.run_fn = run_fn
        self.mode = mode
        self.max_trials = max_trials
        self.log_path = log_path
        self.history: List[Dict] = []

    def search(self) -> Optional[Config]:
        best_cfg = None
        best_metric = None
        trials = self.candidates if self.max_trials is None \
            else self.candidates[: self.max_trials]
        for cfg in trials:
            t0 = time.time()
            try:
                metric = self.run_fn(cfg)
                err = None
            except Exception as e:  # OOM / invalid config: record + skip
                metric = None
                err = str(e)
            rec = {"config": cfg.to_dict(), "metric": metric,
                   "error": err, "time": time.time() - t0}
            self.history.append(rec)
            if self.log_path:
                with open(self.log_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            if metric is None:
                continue
            better = (best_metric is None
                      or (self.mode == "max" and metric > best_metric)
                      or (self.mode == "min" and metric < best_metric))
            if better:
                best_metric, best_cfg = metric, cfg
        return best_cfg
