from .tuner import AutoTuner, Config, default_candidates, prune_by_memory

__all__ = ["AutoTuner", "Config", "default_candidates", "prune_by_memory"]
