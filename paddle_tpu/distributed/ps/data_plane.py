"""Parameter-Server data plane over the rpc agent (reference:
python/paddle/distributed/ps/the_one_ps.py TheOnePSRuntime,
paddle/fluid/distributed/ps/table/memory_sparse_table.cc — there the
tables live behind brpc with rocksdb shards; here they live in server
process memory behind the in-repo rpc transport
(distributed/rpc.py), which is the same redesign the FleetExecutor's
cross-rank bus uses).

Roles follow the reference env contract (TRAINING_ROLE=TRAINER|PSERVER,
PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_TRAINERS_NUM). All trainers and
servers join ONE rpc world: trainers are ranks [0, T), servers ranks
[T, T+S). Sparse rows shard across servers by `id % server_num`.

The data plane is HOST-side by design: sparse tables are a CPU-memory
construct (the reference's too — rocksdb/brpc), while dense training on
TPU stays collective-first per SURVEY §2.4.17. SparseEmbedding is an
eager layer: forward pulls rows, backward pushes per-row grads with a
registered tape hook.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["SparseTable", "DenseTable", "PSServer", "PSWorker",
           "SparseEmbedding"]


class SparseTable:
    """In-memory sparse table with lazy row init + per-row optimizer
    state (reference: memory_sparse_table.cc + the sparse accessors
    ctr_accessor.cc — sgd/adagrad/adam rules per embedding row)."""

    def __init__(self, dim: int, optimizer: str = "adagrad",
                 lr: float = 0.01, initializer: str = "uniform",
                 init_scale: float = 0.01, seed: int = 0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8):
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unsupported sparse optimizer {optimizer}")
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.initializer = initializer
        self.init_scale = float(init_scale)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self._rows: Dict[int, np.ndarray] = {}  # guarded by: _lock
        self._state: Dict[int, list] = {}  # guarded by: _lock
        self._step: Dict[int, int] = {}  # guarded by: _lock
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    def _init_row(self, rid: int) -> np.ndarray:
        if self.initializer == "zeros":
            return np.zeros(self.dim, np.float32)
        return self._rng.uniform(-self.init_scale, self.init_scale,
                                 self.dim).astype(np.float32)

    def pull(self, ids) -> np.ndarray:
        """Rows for ids [n] -> [n, dim]; missing rows are created
        (reference: pull_sparse with create-on-miss)."""
        with self._lock:
            out = np.empty((len(ids), self.dim), np.float32)
            for i, rid in enumerate(ids):
                rid = int(rid)
                row = self._rows.get(rid)
                if row is None:
                    row = self._rows[rid] = self._init_row(rid)
                out[i] = row
            return out

    def push(self, ids, grads) -> None:
        """Apply per-row optimizer updates; duplicate ids in one push
        are accumulated first (the embedding-bag contract)."""
        grads = np.asarray(grads, np.float32)
        uniq: Dict[int, np.ndarray] = {}
        for rid, g in zip(ids, grads):
            rid = int(rid)
            if rid in uniq:
                uniq[rid] = uniq[rid] + g
            else:
                uniq[rid] = g.copy()
        with self._lock:
            for rid, g in uniq.items():
                row = self._rows.get(rid)
                if row is None:
                    row = self._rows[rid] = self._init_row(rid)
                if self.optimizer == "sgd":
                    row -= self.lr * g
                elif self.optimizer == "adagrad":
                    st = self._state.setdefault(
                        rid, [np.zeros(self.dim, np.float32)])
                    st[0] += g * g
                    row -= self.lr * g / (np.sqrt(st[0]) + self.eps)
                else:  # adam
                    st = self._state.setdefault(
                        rid, [np.zeros(self.dim, np.float32),
                              np.zeros(self.dim, np.float32)])
                    t = self._step.get(rid, 0) + 1
                    self._step[rid] = t
                    st[0] = self.beta1 * st[0] + (1 - self.beta1) * g
                    st[1] = self.beta2 * st[1] + (1 - self.beta2) * g * g
                    mhat = st[0] / (1 - self.beta1 ** t)
                    vhat = st[1] / (1 - self.beta2 ** t)
                    row -= self.lr * mhat / (np.sqrt(vhat) + self.eps)

    def state_dict(self) -> dict:
        with self._lock:
            return {"dim": self.dim, "optimizer": self.optimizer,
                    "rows": {k: v.copy() for k, v in self._rows.items()},
                    "state": {k: [s.copy() for s in v]
                              for k, v in self._state.items()},
                    "step": dict(self._step)}

    def load_state_dict(self, sd: dict) -> None:
        with self._lock:
            self._rows = {int(k): np.asarray(v, np.float32)
                          for k, v in sd["rows"].items()}
            self._state = {int(k): [np.asarray(s, np.float32) for s in v]
                           for k, v in sd.get("state", {}).items()}
            self._step = {int(k): int(v)
                          for k, v in sd.get("step", {}).items()}

    def __len__(self):
        with self._lock:
            return len(self._rows)


class DenseTable:
    """Dense parameter vector with server-side SGD (reference:
    memory_dense_table.cc)."""

    def __init__(self, shape, lr: float = 0.01, seed: int = 0):
        self.lr = float(lr)
        self._value = np.random.default_rng(seed).uniform(  # guarded by: _lock
            -0.01, 0.01, shape).astype(np.float32)
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._value.copy()

    def push(self, grad) -> None:
        with self._lock:
            self._value -= self.lr * np.asarray(grad, np.float32)

    def set(self, value) -> None:
        with self._lock:
            self._value = np.asarray(value, np.float32).copy()

    def state_dict(self) -> dict:
        with self._lock:
            return {"value": self._value.copy(), "lr": self.lr}

    def load_state_dict(self, sd: dict) -> None:
        with self._lock:
            self._value = np.asarray(sd["value"], np.float32).copy()

    def __len__(self):
        with self._lock:
            return int(self._value.size)


# ---------------------------------------------------------------- server
# rpc entry points are module-level (the transport ships the function by
# reference); the hosting process keeps its tables in this registry
_TABLES: Dict[int, object] = {}


def _ps_pull_sparse(table_id: int, ids):
    return _TABLES[table_id].pull(ids)


def _ps_push_sparse(table_id: int, ids, grads):
    _TABLES[table_id].push(ids, grads)
    return True


def _ps_pull_dense(table_id: int):
    return _TABLES[table_id].pull()


def _ps_push_dense(table_id: int, grad):
    _TABLES[table_id].push(grad)
    return True


def _ps_table_size(table_id: int):
    return len(_TABLES[table_id])


def _ps_save(table_id: int, path: str):
    sd = _TABLES[table_id].state_dict()
    np.save(path, np.array([sd], dtype=object), allow_pickle=True)
    return True


def _ps_load(table_id: int, path: str):
    sd = np.load(path, allow_pickle=True)[0]
    _TABLES[table_id].load_state_dict(sd)
    return True


class PSServer:
    """One parameter-server process: hosts its table shards behind the
    rpc agent (reference: the_one_ps.py _init_server/_run_server)."""

    def __init__(self, server_index: Optional[int] = None):
        self.server_index = server_index if server_index is not None \
            else int(os.environ.get("PADDLE_PSERVER_ID", "0"))

    def add_sparse_table(self, table_id: int, dim: int, **kw):
        _TABLES[table_id] = SparseTable(dim,
                                        seed=1000 + self.server_index,
                                        **kw)

    def add_dense_table(self, table_id: int, shape, **kw):
        _TABLES[table_id] = DenseTable(shape, **kw)

    def run(self):
        """Serve until every trainer has called stop (the rpc shutdown
        barrier is the serving loop — dispatchers answer pulls/pushes
        while this blocks)."""
        from .. import rpc

        rpc.shutdown()  # barriers with the trainers' stop_worker()

    def save(self, table_id: int, path: str):
        _ps_save(table_id, path)

    def load(self, table_id: int, path: str):
        _ps_load(table_id, path)


class PSWorker:
    """Trainer-side client: shards requests over the server ranks by
    `id % n_servers` (reference: the worker side of the_one_ps +
    fleet.init_worker)."""

    def __init__(self, n_trainers: int, n_servers: int):
        self.n_trainers = n_trainers
        self.n_servers = n_servers

    def _server_name(self, s: int) -> str:
        return f"pserver{s}"

    def pull_sparse(self, table_id: int, ids,
                    dim: Optional[int] = None) -> np.ndarray:
        from .. import rpc

        ids = np.asarray(ids, np.int64).ravel()
        if len(ids) == 0:
            return np.zeros((0, dim or 0), np.float32)
        parts: List[np.ndarray] = [None] * self.n_servers  # type: ignore
        for s in range(self.n_servers):
            mask = (ids % self.n_servers) == s
            if mask.any():
                parts[s] = rpc.rpc_sync(
                    self._server_name(s), _ps_pull_sparse,
                    args=(table_id, ids[mask].tolist()))
        dim = next(p.shape[1] for p in parts if p is not None)
        out = np.empty((len(ids), dim), np.float32)
        for s in range(self.n_servers):
            if parts[s] is not None:
                out[(ids % self.n_servers) == s] = parts[s]
        return out

    def push_sparse(self, table_id: int, ids, grads) -> None:
        from .. import rpc

        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32)
        futs = []
        for s in range(self.n_servers):
            mask = (ids % self.n_servers) == s
            if mask.any():
                futs.append(rpc.rpc_async(
                    self._server_name(s), _ps_push_sparse,
                    args=(table_id, ids[mask].tolist(),
                          grads[mask])))
        for f in futs:
            f.result(timeout=60)

    def pull_dense(self, table_id: int) -> np.ndarray:
        from .. import rpc

        return rpc.rpc_sync(self._server_name(table_id
                                              % self.n_servers),
                            _ps_pull_dense, args=(table_id,))

    def push_dense(self, table_id: int, grad) -> None:
        from .. import rpc

        rpc.rpc_sync(self._server_name(table_id % self.n_servers),
                     _ps_push_dense, args=(table_id, np.asarray(grad)))

    def table_size(self, table_id: int) -> int:
        from .. import rpc

        return sum(rpc.rpc_sync(self._server_name(s), _ps_table_size,
                                args=(table_id,))
                   for s in range(self.n_servers))

    def stop(self):
        """Symmetric with PSServer.run(): barriers everyone out."""
        from .. import rpc

        rpc.shutdown()


class SparseEmbedding:
    """Eager PS-backed embedding (reference:
    python/paddle/static/nn/common.py sparse_embedding): forward pulls
    rows from the sparse table, backward pushes the per-row grads. The
    TPU compute graph sees a plain dense gather result; the PS hop is
    host-side, exactly like the reference's heter pipeline."""

    def __init__(self, worker: PSWorker, table_id: int, dim: int):
        self.worker = worker
        self.table_id = table_id
        self.dim = dim
        # Tensor is __slots__-ed, so the pending pull's ids are tracked
        # here. Keys are id(out) DISAMBIGUATED by a weakref to the exact
        # tensor: a finalizer drops the entry when the output dies
        # (eval loops that never apply_grad must not leak, and a reused
        # CPython id must not push grads onto someone else's rows).
        self._pending: Dict[int, tuple] = {}

    def __call__(self, ids):
        import weakref

        from ...core.tensor import Tensor

        ids_np = np.asarray(
            ids.numpy() if isinstance(ids, Tensor) else ids, np.int64)
        flat = ids_np.ravel()
        rows = self.worker.pull_sparse(self.table_id, flat,
                                       dim=self.dim)
        out = Tensor(rows.reshape(ids_np.shape + (self.dim,)),
                     stop_gradient=False)
        key = id(out)
        ref = weakref.ref(out, lambda _r, _k=key, _p=self._pending:
                          _p.pop(_k, None))
        self._pending[key] = (ref, flat)
        return out

    def apply_grad(self, out):
        """Push `out.grad` (set by backward()) to the table."""
        if out.grad is None:
            raise ValueError("backward() has not produced a grad")
        entry = self._pending.get(id(out))
        if entry is None or entry[0]() is not out:
            raise ValueError("apply_grad: tensor was not produced by "
                             "this SparseEmbedding (or already applied)")
        del self._pending[id(out)]
        flat = entry[1]
        self.worker.push_sparse(
            self.table_id, flat,
            np.asarray(out.grad.numpy(), np.float32)
            .reshape(len(flat), -1))
