"""Parameter-Server data plane over the rpc agent (reference:
python/paddle/distributed/ps/the_one_ps.py TheOnePSRuntime,
paddle/fluid/distributed/ps/table/memory_sparse_table.cc — there the
tables live behind brpc with rocksdb shards; here they live in server
process memory behind the in-repo rpc transport
(distributed/rpc.py), which is the same redesign the FleetExecutor's
cross-rank bus uses).

Roles follow the reference env contract (TRAINING_ROLE=TRAINER|PSERVER,
PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_TRAINERS_NUM). All trainers and
servers join ONE rpc world: trainers are ranks [0, T), servers ranks
[T, T+S). Sparse rows shard across servers by `id % server_num`.

Fault tolerance (this file + replication.py + tables.py):

* **Replication.** With >= 2 servers, shard ``s`` is PRIMARY on server
  ``s`` and BACKUP on server ``(s+1) % S``. The primary applies a push,
  then forwards the record through the store-backed per-shard
  replication log and blocks on the backup's ack — an acked push exists
  on both replicas. Pulls are served by the primary only; pull-created
  rows are never replicated because row init is a pure function of
  (table seed, id) (see tables.py).
* **Failover.** Servers beat heartbeat leases on the job TCPStore
  (``elastic/membership.py`` discipline). The backup watches its
  primary's lease; on expiry it drains the log, takes the shard over in
  the ``ps/primary/{shard}`` map and bumps the map generation. Workers
  detect the move (typed :class:`PSFailover`), re-resolve, replay their
  unacked in-flight window and retry.
* **Exactly-once pushes.** The rpc layer is at-least-once (PR 3
  retransmit), and failover replays re-send whole batches — so every
  push carries a per-(worker, shard, table) monotonic sequence number
  and servers keep a per-worker high-water mark (replicated with the
  shard): stale seqs are acked without re-applying
  (``ps.push_dedup_hits``).
* **Retries + fault injection.** Every worker-side op runs under the
  shared ``resilience.retry`` policy with a ``PADDLE_TPU_PS_TIMEOUT``
  whole-op deadline; ``ps.pull``/``ps.push`` (worker) and ``ps.server``
  (handler entry) are fault-injection sites.

The data plane is HOST-side by design: sparse tables are a CPU-memory
construct (the reference's too — rocksdb/brpc), while dense training on
TPU stays collective-first per SURVEY §2.4.17. SparseEmbedding is an
eager layer: forward pulls rows, backward pushes per-row grads with a
registered tape hook.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..control_plane import keyspace as _ks
from ..resilience import faults as _faults
from ..resilience.retry import call_with_retry
from . import checkpoint as ps_ckpt
from .replication import (PSConfig, PSFailover, ReplicationLog, beat,
                          lease_fresh, primary_of, set_primary)
from .tables import DenseTable, SparseTable

__all__ = ["SparseTable", "DenseTable", "PSServer", "PSWorker",
           "SparseEmbedding", "PSConfig", "PSFailover",
           "RpcTransport", "LocalTransport"]


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


def span(name: str, o, **args):  # name first: ptlint reads args[0]
    return o.span(name, cat="ps", args=args) if o \
        else contextlib.nullcontext()


# ---------------------------------------------------------------- server
# rpc entry points are module-level (the transport ships the function by
# reference); the hosting process keeps its PSServer instances in this
# registry keyed by server index, and every handler routes through it —
# two servers in one process (tests, in-process drills) never share or
# clobber each other's tables.
_SERVERS: Dict[int, "PSServer"] = {}


def _server(server_index: int) -> "PSServer":
    srv = _SERVERS.get(server_index)
    if srv is None:
        # unreachable-peer semantics so LocalTransport callers retry /
        # fail over exactly like an rpc caller with a dead server would
        raise ConnectionError(
            f"no PSServer with index {server_index} in this process")
    return srv


def _ps_pull_sparse(server_index: int, shard: int, table_id: int, ids):
    return _server(server_index).handle_pull_sparse(shard, table_id, ids)


def _ps_push_sparse(server_index: int, shard: int, table_id: int, ids,
                    grads, worker: str = "", seq: int = 0):
    return _server(server_index).handle_push_sparse(
        shard, table_id, ids, grads, worker, seq)


def _ps_pull_dense(server_index: int, shard: int, table_id: int):
    return _server(server_index).handle_pull_dense(shard, table_id)


def _ps_push_dense(server_index: int, shard: int, table_id: int, grad,
                   worker: str = "", seq: int = 0):
    return _server(server_index).handle_push_dense(
        shard, table_id, grad, worker, seq)


def _ps_table_size(server_index: int, shard: int, table_id: int):
    return _server(server_index).handle_table_size(shard, table_id)


def _ps_save(server_index: int, shard: int, table_id: int, path: str):
    return _server(server_index).handle_save(shard, table_id, path)


def _ps_load(server_index: int, shard: int, table_id: int, path: str):
    return _server(server_index).handle_load(shard, table_id, path)


def _ps_stats(server_index: int):
    return _server(server_index).stats()


def _ps_digest(server_index: int, shard: int, table_id: int):
    return _server(server_index).handle_digest(shard, table_id)


# ------------------------------------------------------------ transports

class RpcTransport:
    """Default transport: ships handler calls over the in-repo rpc
    agent to ``pserver{index}``."""

    def call(self, server_index: int, fn, args,
             timeout: Optional[float] = None):
        from .. import rpc

        return rpc.rpc_sync(f"pserver{server_index}", fn,
                            args=(server_index,) + tuple(args),
                            timeout=timeout if timeout is not None
                            else 60.0)

    @property
    def store(self):
        from .. import rpc

        return getattr(rpc._agent, "store", None) \
            if rpc._agent is not None else None


class LocalTransport:
    """In-process transport for tests and bench: dispatches handler
    functions directly against the PSServer registry — no rpc world
    needed. A deregistered server raises ConnectionError exactly like a
    dead rpc peer, so retry/failover paths are exercisable in one
    process (pass a live ``store`` to enable the shard-map plane)."""

    def __init__(self, servers=None, store=None):
        self.store = store
        # servers self-register in _SERVERS at construction; the arg
        # exists to make ownership explicit at the call site
        self.servers = list(servers) if servers else None

    def call(self, server_index: int, fn, args,
             timeout: Optional[float] = None):
        return fn(server_index, *args)


class PSServer:
    """One parameter-server process: hosts its PRIMARY shard (and, when
    replication is on, a BACKUP replica of its neighbor's shard) behind
    the rpc agent (reference: the_one_ps.py _init_server/_run_server +
    the table replicas brpc keeps per shard)."""

    def __init__(self, server_index: Optional[int] = None,
                 n_servers: Optional[int] = None,
                 config: Optional[PSConfig] = None,
                 replicated: Optional[bool] = None):
        self.server_index = int(server_index) if server_index is not None \
            else int(os.environ.get("PADDLE_PSERVER_ID", "0"))
        if n_servers is None:
            eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            n_servers = len([e for e in eps.split(",")
                             if e.strip()]) or 1
        self.n_servers = int(n_servers)
        self.cfg = config or PSConfig()
        if replicated is None:
            # bare-constructed servers (unit tests, LocalTransport
            # fleets without a store) replicate only on explicit opt-in;
            # TheOnePSRuntime resolves the "auto" policy for real jobs
            replicated = self.cfg.replication == "on"
        self.replicated = bool(replicated) and self.n_servers >= 2
        self._lock = threading.RLock()
        self._tables: Dict[Tuple[int, int], object] = {}  # guarded by: _lock
        self._hwm: Dict[Tuple[int, int, str], int] = {}  # guarded by: _lock
        self._counters: Dict[str, int] = {  # guarded by: _lock
            "pulls": 0, "pushes": 0, "push_dedup_hits": 0,
            "repl_records": 0, "repl_degraded": 0, "promotions": 0}
        self._primary_shards = {self.server_index}  # guarded by: _lock
        self._repl_to: Dict[int, Optional[int]] = {}  # guarded by: _lock
        self._dead: set = set()  # guarded by: _lock
        self._logs: Dict[int, ReplicationLog] = {}
        self.store = None
        self._world: Optional[int] = None
        self._grace_end = 0.0
        self._stop_evt = threading.Event()
        self._promote_evt = threading.Event()
        self._threads: List[threading.Thread] = []
        _SERVERS[self.server_index] = self

    # -------------------------------------------------------- topology
    @property
    def backup_shard(self) -> Optional[int]:
        if not self.replicated:
            return None
        return (self.server_index - 1) % self.n_servers

    def hosted_shards(self):
        shards = {self.server_index}
        b = self.backup_shard
        if b is not None:
            shards.add(b)
        return shards

    def add_sparse_table(self, table_id: int, dim: int, **kw):
        # the seed is per-TABLE (not per-server): every shard and every
        # replica of a table must initialize row `rid` identically so
        # sharded == local and primary == backup bit-exactly
        kw.setdefault("seed", 1000 + int(table_id))
        with self._lock:
            for shard in self.hosted_shards():
                self._tables[(shard, table_id)] = SparseTable(dim, **kw)

    def add_dense_table(self, table_id: int, shape, **kw):
        shard = int(table_id) % self.n_servers
        with self._lock:
            if shard in self.hosted_shards():
                self._tables[(shard, table_id)] = DenseTable(shape, **kw)

    def _table(self, shard: int, table_id: int):
        with self._lock:
            tbl = self._tables.get((shard, int(table_id)))
        if tbl is None:
            raise KeyError(f"server {self.server_index} hosts no table "
                           f"{table_id} for shard {shard}")
        return tbl

    # ---------------------------------------------------- control plane
    def start(self, store=None, world_size: Optional[int] = None):
        """Attach the job store and (when replicated) start the beat /
        applier / watch threads. Call after init_rpc; idempotent."""
        self.store = store
        if world_size is not None:
            self._world = int(world_size)
        if store is None or not self.replicated:
            return
        self._grace_end = time.monotonic() + self.cfg.failover_timeout
        b = self.backup_shard
        with self._lock:
            self._repl_to = {self.server_index:
                             (self.server_index + 1) % self.n_servers}
        self._logs = {self.server_index:
                      ReplicationLog(store, self.server_index),
                      b: ReplicationLog(store, b)}
        beat(store, self.server_index)
        store.set(_ks.ps_primary(self.server_index),
                  str(self.server_index).encode())
        for fn in (self._beat_loop, self._applier_loop,
                   self._watch_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def _stale(self, index: int) -> bool:
        """Dead-peer verdict with a startup grace window: a peer that
        has never beaten is only 'dead' once the initial failover
        budget has passed (it may simply still be booting)."""
        if lease_fresh(self.store, index, self.cfg.lease_timeout):
            return False
        from ..elastic.membership import read_beat

        if read_beat(self.store, "ps", index) is None \
                and time.monotonic() < self._grace_end:
            return False
        return True

    def _beat_loop(self):
        while not self._stop_evt.wait(self.cfg.beat_interval):
            try:
                beat(self.store, self.server_index)
            except Exception:
                return

    def _applier_loop(self):
        """Backup side: apply the primary's replication records in
        order and ack the high-water mark. On promotion request, drain
        whatever the dead primary managed to post, then take over."""
        shard = self.backup_shard
        log = self._logs[shard]
        while not self._stop_evt.is_set():
            if self._promote_evt.is_set():
                self._promote(shard, log)
                return
            try:
                rec = log.take_next()
            except Exception:
                if self._stop_evt.is_set():
                    return
                self._stop_evt.wait(0.05)
                continue
            if rec is None:
                self._stop_evt.wait(0.004)
                continue
            self._apply_record(shard, rec)
            log.ack()

    def _watch_loop(self):
        while not self._stop_evt.wait(self.cfg.beat_interval):
            try:
                self._watch_once()
            except Exception:
                continue

    def _watch_once(self):
        b = self.backup_shard
        with self._lock:
            serving_backup = b in self._primary_shards
            own_target = self._repl_to.get(self.server_index)
        if not serving_backup:
            p = primary_of(self.store, b, b)
            if p != self.server_index and self._stale(p):
                self._promote_evt.set()
        if own_target is not None and self._stale(own_target):
            self._degrade(self.server_index, own_target)

    def _promote(self, shard: int, log: ReplicationLog):
        """Runs on the applier thread (it owns the log cursor): drain,
        then publish ourselves as the shard's primary."""
        o = _obs()
        with span("ps.promote", o, shard=shard,
                  server=self.server_index):
            while True:
                rec = log.take_next()
                if rec is None:
                    break
                self._apply_record(shard, rec)
            log.ack()
            log.resume_as_primary()
            old = primary_of(self.store, shard, shard)
            with self._lock:
                self._primary_shards.add(shard)
                if old != self.server_index:
                    self._dead.add(old)
                # the shard's natural backup is ourselves now — serve
                # it unreplicated until a replacement joins
                self._repl_to[shard] = None
                self._counters["promotions"] += 1
            set_primary(self.store, shard, self.server_index)
        if o:
            o.registry.counter("ps.promotions").inc()

    def _degrade(self, shard: int, target: int):
        o = _obs()
        with self._lock:
            if self._repl_to.get(shard) != target:
                return
            self._repl_to[shard] = None
            self._dead.add(target)
            self._counters["repl_degraded"] += 1
        if o:
            o.registry.counter("ps.repl_degraded").inc()

    def _replicate(self, shard: int, rec: dict):
        """Chain step: post the applied record and block on the
        backup's ack — only then does the worker's push succeed, so an
        acked push survives this process dying. Degrades (and stops
        blocking) when the backup's lease goes stale."""
        with self._lock:
            target = self._repl_to.get(shard)
        if target is None or not self._logs:
            return
        n = self._logs[shard].post(rec)
        last_check = [0.0]

        def alive() -> bool:
            now = time.monotonic()
            if now - last_check[0] < self.cfg.beat_interval:
                return True
            last_check[0] = now
            return not self._stale(target)

        ok = self._logs[shard].wait_acked(
            n, self.cfg.failover_timeout, alive=alive)
        if not ok:
            self._degrade(shard, target)

    def _apply_record(self, shard: int, rec: dict):
        key = (shard, int(rec["table"]), rec["worker"])
        seq = int(rec["seq"])
        with self._lock:
            if seq and seq <= self._hwm.get(key, 0):
                return
        tbl = self._table(shard, rec["table"])
        if rec["kind"] == "sparse":
            tbl.push(rec["ids"], rec["grads"])
        else:
            tbl.push(rec["grad"])
        with self._lock:
            if seq:
                self._hwm[key] = seq
            self._counters["repl_records"] += 1
        o = _obs()
        if o:
            o.registry.counter("ps.repl_records").inc()

    # --------------------------------------------------------- handlers
    def _fault_gate(self):
        act = _faults.check("ps.server")
        if act is None:
            return
        if act.kind in ("drop", "loss"):
            raise ConnectionError(
                f"fault-injected ps.server {act.kind} "
                f"(invocation {act.invocation})")
        _faults.apply(act)  # delay / kill / raise

    def _check_primary(self, shard: int):
        with self._lock:
            local = shard in self._primary_shards
        if not local:
            raise RuntimeError(
                f"PSNotPrimary: server {self.server_index} is not "
                f"primary for shard {shard}")
        if self.replicated and self.store is not None:
            p = primary_of(self.store, shard, shard)
            if p != self.server_index:
                # fencing: the map moved away from us (we were deposed
                # while suspected dead) — stop serving the shard so two
                # primaries can't diverge
                with self._lock:
                    self._primary_shards.discard(shard)
                raise RuntimeError(
                    f"PSNotPrimary: shard {shard} moved to server {p}")

    def handle_pull_sparse(self, shard: int, table_id: int, ids):
        self._fault_gate()
        self._check_primary(shard)
        rows = self._table(shard, table_id).pull(ids)
        with self._lock:
            self._counters["pulls"] += len(ids)
        o = _obs()
        if o:
            o.registry.counter("ps.pulls").inc(len(ids))
        return rows

    def handle_push_sparse(self, shard: int, table_id: int, ids, grads,
                           worker: str = "", seq: int = 0):
        self._fault_gate()
        self._check_primary(shard)
        key = (shard, int(table_id), worker)
        seq = int(seq)
        with self._lock:
            dedup = bool(seq) and seq <= self._hwm.get(key, 0)
            if dedup:
                self._counters["push_dedup_hits"] += 1
        o = _obs()
        if dedup:
            # at-least-once delivery (rpc retransmit, failover replay,
            # lost acks) re-sends batches; the high-water mark makes
            # re-application a no-op instead of a double optimizer step
            if o:
                o.registry.counter("ps.push_dedup_hits").inc()
            return {"ok": True, "dedup": True}
        self._table(shard, table_id).push(ids, grads)
        with self._lock:
            if seq:
                self._hwm[key] = seq
            self._counters["pushes"] += len(ids)
        self._replicate(shard, {"kind": "sparse", "table": int(table_id),
                                "ids": ids, "grads": grads,
                                "worker": worker, "seq": seq})
        if o:
            o.registry.counter("ps.pushes").inc(len(ids))
        return {"ok": True, "dedup": False}

    def handle_pull_dense(self, shard: int, table_id: int):
        self._fault_gate()
        self._check_primary(shard)
        value = self._table(shard, table_id).pull()
        with self._lock:
            self._counters["pulls"] += 1
        o = _obs()
        if o:
            o.registry.counter("ps.pulls").inc()
        return value

    def handle_push_dense(self, shard: int, table_id: int, grad,
                          worker: str = "", seq: int = 0):
        self._fault_gate()
        self._check_primary(shard)
        key = (shard, int(table_id), worker)
        seq = int(seq)
        with self._lock:
            dedup = bool(seq) and seq <= self._hwm.get(key, 0)
            if dedup:
                self._counters["push_dedup_hits"] += 1
        o = _obs()
        if dedup:
            if o:
                o.registry.counter("ps.push_dedup_hits").inc()
            return {"ok": True, "dedup": True}
        self._table(shard, table_id).push(grad)
        with self._lock:
            if seq:
                self._hwm[key] = seq
            self._counters["pushes"] += 1
        self._replicate(shard, {"kind": "dense", "table": int(table_id),
                                "grad": np.asarray(grad, np.float32),
                                "worker": worker, "seq": seq})
        if o:
            o.registry.counter("ps.pushes").inc()
        return {"ok": True, "dedup": False}

    def handle_table_size(self, shard: int, table_id: int) -> int:
        self._check_primary(shard)
        return len(self._table(shard, table_id))

    def handle_digest(self, shard: int, table_id: int) -> str:
        return self._table(shard, table_id).digest()

    def handle_save(self, shard: int, table_id: int, path: str) -> str:
        tbl = self._table(shard, table_id)
        with self._lock:
            hwm = {w: s for (sh, t, w), s in self._hwm.items()
                   if sh == shard and t == int(table_id)}
        return ps_ckpt.write_table(
            path, {"table": tbl.state_dict(), "hwm": hwm})

    def handle_load(self, shard: int, table_id: int, path: str) -> bool:
        sd = ps_ckpt.read_table(path)
        if "table" in sd:  # current format: state + dedup high-water marks
            self._table(shard, table_id).load_state_dict(sd["table"])
            with self._lock:
                for w, s in sd.get("hwm", {}).items():
                    self._hwm[(shard, int(table_id), w)] = int(s)
        else:  # legacy raw state_dict
            self._table(shard, table_id).load_state_dict(sd)
        return True

    def stats(self) -> dict:
        """Plain-int counter snapshot (drills assert on this without
        needing the observability registry enabled)."""
        with self._lock:
            d = dict(self._counters)
            d["primary_shards"] = sorted(self._primary_shards)
            d["dead"] = sorted(self._dead)
            tables = list(self._tables.items())
        d["server_index"] = self.server_index
        d["evictions"] = 0
        d["admission_denied"] = 0
        d["rows"] = 0
        for (_s, _t), tbl in tables:
            c = getattr(tbl, "counters", None)
            if c is None:
                continue
            tc = c()
            d["evictions"] += tc["evictions"]
            d["admission_denied"] += tc["admission_denied"]
            d["rows"] += tc["rows"]
        return d

    # ---------------------------------------------------------- serving
    def run(self):
        """Serve until every live rank has called stop (the rpc
        shutdown barrier is the serving loop — dispatchers answer
        pulls/pushes while this blocks). Peers this server observed die
        are subtracted from the barrier's expected count."""
        from .. import rpc

        if self._world is None and rpc._agent is not None:
            self._world = rpc._agent.world_size

        def dead_ranks() -> set:
            # server index s is rpc rank (n_trainers + s)
            if self._world is None:
                return set()
            t = self._world - self.n_servers
            with self._lock:
                return {t + d for d in self._dead}

        try:
            rpc.shutdown(dead_ranks=dead_ranks)
        finally:
            self.shutdown_local()

    def shutdown_local(self):
        """Stop control-plane threads and deregister from the handler
        registry (in-process death for tests/drills)."""
        self._stop_evt.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        if _SERVERS.get(self.server_index) is self:
            del _SERVERS[self.server_index]

    def save(self, table_id: int, path: str):
        self.handle_save(self.server_index, table_id, path)

    def load(self, table_id: int, path: str):
        self.handle_load(self.server_index, table_id, path)


class PSWorker:
    """Trainer-side client: shards requests over the server ranks by
    `id % n_servers` (reference: the worker side of the_one_ps +
    fleet.init_worker), resolving each shard's current PRIMARY through
    the store map.

    Every sharded op runs under the shared retry policy with the
    ``PADDLE_TPU_PS_TIMEOUT`` whole-op deadline. Pushes carry monotonic
    per-(shard, table) sequence numbers and sit in an in-flight window
    until acked; on a typed :class:`PSFailover` (shard map moved) the
    window is replayed against the new primary — server-side seq dedup
    makes the replay + retry exactly-once. The client is synchronous,
    so the window holds at most the op currently in flight per shard;
    the replay path does not depend on that, but ordering does (window
    entries replay oldest-first before the current op retries)."""

    def __init__(self, n_trainers: int, n_servers: int,
                 worker_id: Optional[str] = None, transport=None,
                 config: Optional[PSConfig] = None):
        self.n_trainers = n_trainers
        self.n_servers = n_servers
        self.cfg = config or PSConfig()
        self.worker_id = worker_id if worker_id is not None else \
            f"trainer{os.environ.get('PADDLE_TRAINER_ID', '0')}"
        self.transport = transport if transport is not None \
            else RpcTransport()
        self._lock = threading.Lock()
        self._seq: Dict[Tuple[int, int], int] = {}  # guarded by: _lock
        self._window: Dict[int, list] = {}  # guarded by: _lock
        self._primary: Dict[int, int] = {}  # guarded by: _lock
        self._dead: set = set()  # guarded by: _lock
        # observed failover events (the drill asserts on these):
        # {shard, old, new, latency_s, replayed}
        self.failovers: List[dict] = []

    def _server_name(self, s: int) -> str:
        return f"pserver{s}"

    # ------------------------------------------------------- shard map
    def primary_for(self, shard: int, refresh: bool = False) -> int:
        store = getattr(self.transport, "store", None)
        if store is None or self.n_servers < 2:
            return shard
        if not refresh:
            with self._lock:
                p = self._primary.get(shard)
            if p is not None:
                return p
        p = primary_of(store, shard, shard)
        with self._lock:
            self._primary[shard] = p
        return p

    def _next_seq(self, shard: int, table_id: int) -> int:
        with self._lock:
            n = self._seq.get((shard, table_id), 0) + 1
            self._seq[(shard, table_id)] = n
        return n

    def _ack(self, shard: int, rec: dict):
        with self._lock:
            w = self._window.get(shard)
            if w and rec in w:
                w.remove(rec)

    # ------------------------------------------------------- core call
    def _shard_call(self, site: str, shard: int, fn, args,
                    window_rec: Optional[dict] = None):
        """One sharded op: per-attempt fault injection + shared retry
        policy inside, typed PSFailover adoption + window replay
        outside, the whole thing bounded by ``cfg.timeout``."""
        deadline = time.monotonic() + self.cfg.timeout
        detect = [None]

        def attempt():
            p_known = self.primary_for(shard)
            p_now = self.primary_for(shard, refresh=True)
            if p_now != p_known:
                raise PSFailover(shard, p_known, p_now,
                                 "shard map moved")
            act = _faults.check(site)
            call_args = args
            if act is not None:
                if act.kind in ("drop", "loss"):
                    raise ConnectionError(
                        f"fault-injected {site} {act.kind} "
                        f"(invocation {act.invocation})")
                if act.kind == "bitflip":
                    call_args = _bitflip_args(args)
                elif act.kind != "raise":  # raise fires AFTER the call
                    _faults.apply(act)  # delay / kill
            try:
                out = self.transport.call(
                    p_now, fn, (shard,) + tuple(call_args),
                    timeout=self.cfg.rpc_timeout)
            except RuntimeError as e:
                msg = str(e)
                if isinstance(e, PSFailover):
                    raise
                if "PSNotPrimary" in msg or "fault-injected" in msg:
                    # shipped server-side errors: retryable
                    raise ConnectionError(msg)
                raise
            if act is not None and act.kind == "raise":
                # lost-ack: the server applied the op but the reply
                # never arrives; the retried send (same seq) must hit
                # the server's dedup table, not re-apply
                raise ConnectionError(
                    f"fault-injected {site} lost ack "
                    f"(invocation {act.invocation})")
            return out

        def on_retry(err):
            if detect[0] is None:
                detect[0] = time.monotonic()

        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise PSFailover(
                    shard, self.primary_for(shard), None,
                    f"{site} budget exhausted "
                    f"(PADDLE_TPU_PS_TIMEOUT={self.cfg.timeout}s)")
            policy = self.cfg.retry_policy().with_deadline(remaining)
            try:
                out = call_with_retry(attempt, policy=policy, site=site,
                                      on_retry=on_retry)
            except PSFailover as fo:
                if fo.new_primary is None:
                    raise  # budget exhausted (raised above)
                self._adopt(fo, detect)
                continue
            except (ConnectionError, TimeoutError, OSError):
                # retry budget spent but the op deadline hasn't passed:
                # keep knocking (the promotion may still be in flight)
                if detect[0] is None:
                    detect[0] = time.monotonic()
                continue
            if window_rec is not None:
                self._ack(shard, window_rec)
            return out

    def _adopt(self, fo: PSFailover, detect):
        """Adopt a moved shard map: mark the old primary dead, replay
        the unacked window against the new one, record the event."""
        o = _obs()
        new = fo.new_primary
        with self._lock:
            if fo.old_primary is not None and fo.old_primary != new:
                self._dead.add(fo.old_primary)
            self._primary[fo.shard] = new
        replayed = self._replay(fo.shard)
        now = time.monotonic()
        t0 = detect[0] if detect[0] is not None else now
        self.failovers.append({
            "shard": fo.shard, "old": fo.old_primary, "new": new,
            "latency_s": now - t0, "replayed": replayed})
        detect[0] = None
        if o:
            o.registry.counter("ps.failovers").inc()

    def _replay(self, shard: int) -> int:
        with self._lock:
            pending = list(self._window.get(shard, ()))
        if not pending:
            return 0
        o = _obs()
        with span("ps.replay", o, shard=shard, n=len(pending)):
            for rec in pending:
                try:
                    p = self.primary_for(shard)
                    self.transport.call(p, rec["fn"], rec["args"],
                                        timeout=self.cfg.rpc_timeout)
                    self._ack(shard, rec)
                except (ConnectionError, TimeoutError, OSError,
                        RuntimeError):
                    # still unreachable: the entry stays in the window;
                    # the op retry loop (same seq -> dedup) covers it
                    break
        if o:
            o.registry.counter("ps.replays").inc(len(pending))
        return len(pending)

    # ------------------------------------------------------ sparse ops
    def pull_sparse(self, table_id: int, ids,
                    dim: Optional[int] = None) -> np.ndarray:
        ids = np.asarray(ids, np.int64).ravel()
        if len(ids) == 0:
            return np.zeros((0, dim or 0), np.float32)
        o = _obs()
        t0 = time.perf_counter()
        parts: List[Optional[np.ndarray]] = [None] * self.n_servers
        with span("ps.pull", o, table=int(table_id),
                  rows=int(len(ids))):
            for s in range(self.n_servers):
                mask = (ids % self.n_servers) == s
                if mask.any():
                    parts[s] = np.asarray(self._shard_call(
                        "ps.pull", s, _ps_pull_sparse,
                        (table_id, ids[mask].tolist())), np.float32)
        dim = next(p.shape[1] for p in parts if p is not None)
        out = np.empty((len(ids), dim), np.float32)
        for s in range(self.n_servers):
            if parts[s] is not None:
                out[(ids % self.n_servers) == s] = parts[s]
        if o:
            o.registry.histogram("ps.pull_time").observe(
                time.perf_counter() - t0)
        return out

    def push_sparse(self, table_id: int, ids, grads) -> None:
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32)
        o = _obs()
        t0 = time.perf_counter()
        with span("ps.push", o, table=int(table_id),
                  rows=int(len(ids))):
            for s in range(self.n_servers):
                mask = (ids % self.n_servers) == s
                if not mask.any():
                    continue
                seq = self._next_seq(s, table_id)
                call_args = (table_id, ids[mask].tolist(), grads[mask],
                             self.worker_id, seq)
                rec = {"fn": _ps_push_sparse,
                       "args": (s,) + call_args, "seq": seq}
                with self._lock:
                    self._window.setdefault(s, []).append(rec)
                self._shard_call("ps.push", s, _ps_push_sparse,
                                 call_args, window_rec=rec)
        if o:
            o.registry.histogram("ps.push_time").observe(
                time.perf_counter() - t0)

    # ------------------------------------------------------- dense ops
    def pull_dense(self, table_id: int) -> np.ndarray:
        shard = table_id % self.n_servers
        return np.asarray(self._shard_call(
            "ps.pull", shard, _ps_pull_dense, (table_id,)), np.float32)

    def push_dense(self, table_id: int, grad) -> None:
        shard = table_id % self.n_servers
        grad = np.asarray(grad, np.float32)
        seq = self._next_seq(shard, table_id)
        call_args = (table_id, grad, self.worker_id, seq)
        rec = {"fn": _ps_push_dense, "args": (shard,) + call_args,
               "seq": seq}
        with self._lock:
            self._window.setdefault(shard, []).append(rec)
        self._shard_call("ps.push", shard, _ps_push_dense, call_args,
                         window_rec=rec)

    # ------------------------------------------------------------ misc
    def table_size(self, table_id: int) -> int:
        return sum(int(self._shard_call("ps.pull", s, _ps_table_size,
                                        (table_id,)))
                   for s in range(self.n_servers))

    def server_stats(self, server_index: int) -> dict:
        return self.transport.call(server_index, _ps_stats, ())

    def save_table(self, shard: int, table_id: int, path: str) -> str:
        return self._shard_call("ps.push", shard, _ps_save,
                                (table_id, path))

    def stop(self, timeout: float = 120.0):
        """Symmetric with PSServer.run(): barriers everyone out (minus
        the peers this worker observed die)."""
        from .. import rpc

        if not isinstance(self.transport, RpcTransport) \
                or rpc._agent is None:
            return

        def dead_ranks() -> set:
            with self._lock:
                return {self.n_trainers + d for d in self._dead}

        rpc.shutdown(timeout=timeout, dead_ranks=dead_ranks)


def _bitflip_args(args):
    """Site-specific 'bitflip' payload corruption: flip one mantissa
    bit of the first float32 ndarray in the op's args (push grads); a
    pull has none and comes back clean — the corruption there is
    observable as the wrong gradient landing in the table."""
    out = []
    flipped = False
    for a in args:
        if not flipped and isinstance(a, np.ndarray) \
                and a.dtype == np.float32 and a.size:
            a = a.copy()
            v = a.view(np.uint32)
            v.flat[0] ^= np.uint32(1 << 20)
            flipped = True
        out.append(a)
    return tuple(out)


class SparseEmbedding:
    """Eager PS-backed embedding (reference:
    python/paddle/static/nn/common.py sparse_embedding): forward pulls
    rows from the sparse table, backward pushes the per-row grads. The
    TPU compute graph sees a plain dense gather result; the PS hop is
    host-side, exactly like the reference's heter pipeline."""

    def __init__(self, worker: PSWorker, table_id: int, dim: int):
        self.worker = worker
        self.table_id = table_id
        self.dim = dim
        # Tensor is __slots__-ed, so the pending pull's ids are tracked
        # here. Keys are id(out) DISAMBIGUATED by a weakref to the exact
        # tensor: a finalizer drops the entry when the output dies
        # (eval loops that never apply_grad must not leak, and a reused
        # CPython id must not push grads onto someone else's rows).
        self._pending: Dict[int, tuple] = {}

    def __call__(self, ids):
        import weakref

        from ...core.tensor import Tensor

        ids_np = np.asarray(
            ids.numpy() if isinstance(ids, Tensor) else ids, np.int64)
        flat = ids_np.ravel()
        rows = self.worker.pull_sparse(self.table_id, flat,
                                       dim=self.dim)
        out = Tensor(rows.reshape(ids_np.shape + (self.dim,)),
                     stop_gradient=False)
        key = id(out)
        ref = weakref.ref(out, lambda _r, _k=key, _p=self._pending:
                          _p.pop(_k, None))
        self._pending[key] = (ref, flat)
        return out

    def apply_grad(self, out):
        """Push `out.grad` (set by backward()) to the table."""
        if out.grad is None:
            raise ValueError("backward() has not produced a grad")
        entry = self._pending.get(id(out))
        if entry is None or entry[0]() is not out:
            raise ValueError("apply_grad: tensor was not produced by "
                             "this SparseEmbedding (or already applied)")
        del self._pending[id(out)]
        flat = entry[1]
        self.worker.push_sparse(
            self.table_id, flat,
            np.asarray(out.grad.numpy(), np.float32)
            .reshape(len(flat), -1))
