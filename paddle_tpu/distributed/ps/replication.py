"""PS replication/failover control plane over the job TCPStore.

Same lease discipline as ``elastic/membership.py`` (PR 13), now spoken
through the shared substrate (``distributed/control_plane/``): servers
beat ``ps/beat/{index}`` JSON timestamps via
``control_plane.lease.write_beat``; a lease is fresh within
``0.5 * failover_timeout``. The authoritative shard map lives at
``ps/primary/{shard}`` with a generation counter at ``ps/gen`` —
workers cache it and re-resolve when an op fails or the generation
moves.

Replication itself rides the store, NOT a nested rpc: each rpc agent
has ONE dispatcher thread, so a push handler that rpc'd its backup
synchronously would deadlock the moment the backup pushed back (or
simply saturate under symmetric load). Instead the primary appends
pickled records to an ordered per-shard log (``ps/repl/{shard}/{n}``)
and blocks on the backup's ack high-water mark (``ps/replack/{shard}``)
which the backup's applier thread advances after applying in order.
An acked push is therefore applied on BOTH replicas before the worker
sees success — that, plus seq-number dedup, is what makes failover
bit-exact.
"""
from __future__ import annotations

import os
import pickle
import time
from typing import Callable, Optional

from ...config import knobs

from ..control_plane import keyspace as _ks
from ..control_plane.lease import read_beat, write_beat
from ..control_plane.store_util import try_get
from ..resilience.retry import RetryPolicy, default_policy

__all__ = ["PSConfig", "PSFailover", "ReplicationLog", "beat",
           "lease_fresh", "primary_of", "set_primary", "map_generation"]


class PSFailover(RuntimeError):
    """A shard's primary moved (promotion) or died while an op was in
    flight. Workers catch this, adopt the new shard map, replay their
    unacked in-flight window (dedup makes the replay exactly-once) and
    retry; it escapes to the caller only when the op deadline
    (``PADDLE_TPU_PS_TIMEOUT``) is exhausted."""

    def __init__(self, shard: int, old_primary: Optional[int] = None,
                 new_primary: Optional[int] = None, reason: str = ""):
        self.shard = shard
        self.old_primary = old_primary
        self.new_primary = new_primary
        super().__init__(
            f"PSFailover(shard={shard}, old={old_primary}, "
            f"new={new_primary}): {reason}")


class PSConfig:
    """PS tier knobs (env-overridable, ctor args win):

    - ``PADDLE_TPU_PS_TIMEOUT`` — whole-op deadline for one sharded
      pull/push including retries, failover wait and replay (s).
    - ``PADDLE_TPU_PS_RPC_TIMEOUT`` — per-attempt rpc timeout (s).
    - ``PADDLE_TPU_PS_BEAT`` — server heartbeat interval (s).
    - ``PADDLE_TPU_PS_FAILOVER_TIMEOUT`` — budget from primary death to
      promoted service; the lease expires at half of it (the
      ``ElasticConfig.lease_timeout`` discipline).
    - ``PADDLE_TPU_PS_REPLICATION`` — on|off|auto (auto: replicate
      whenever the job runs >= 2 servers).
    """

    def __init__(self, timeout: Optional[float] = None,
                 rpc_timeout: Optional[float] = None,
                 beat_interval: Optional[float] = None,
                 failover_timeout: Optional[float] = None,
                 replication: Optional[str] = None):
        self.timeout = timeout if timeout is not None \
            else knobs.get_float("PADDLE_TPU_PS_TIMEOUT")
        self.rpc_timeout = rpc_timeout if rpc_timeout is not None \
            else knobs.get_float("PADDLE_TPU_PS_RPC_TIMEOUT")
        self.beat_interval = beat_interval if beat_interval is not None \
            else knobs.get_float("PADDLE_TPU_PS_BEAT")
        self.failover_timeout = failover_timeout \
            if failover_timeout is not None \
            else knobs.get_float("PADDLE_TPU_PS_FAILOVER_TIMEOUT")
        self.replication = (replication or knobs.get_str(
            "PADDLE_TPU_PS_REPLICATION")).lower()

    @property
    def lease_timeout(self) -> float:
        return 0.5 * self.failover_timeout

    def retry_policy(self) -> RetryPolicy:
        """Per-op policy: many cheap attempts under one deadline, so a
        worker keeps knocking right through the promotion window
        instead of exhausting 5 attempts before the lease even
        expires."""
        return default_policy(deadline=self.timeout, max_attempts=64,
                              base_delay=0.02, max_delay=0.25)

    def replicated(self, n_servers: int) -> bool:
        if self.replication == "on":
            return True
        if self.replication == "off":
            return False
        return n_servers >= 2


# ------------------------------------------------------------ store keys

def beat(store, index: int) -> None:
    write_beat(store, "ps", index, {"t": time.time()})


def lease_fresh(store, index: int, lease_timeout: float) -> bool:
    b = read_beat(store, "ps", index)
    return b is not None and (time.time() - b.get("t", 0.0)
                              ) <= lease_timeout


def primary_of(store, shard: int, default: int) -> int:
    raw = try_get(store, _ks.ps_primary(shard))
    return int(raw) if raw else default


def set_primary(store, shard: int, index: int) -> None:
    store.set(_ks.ps_primary(shard), str(index).encode())
    store.add(_ks.ps_gen(), 1)  # workers watch this to re-resolve eagerly


def map_generation(store) -> int:
    return store.add(_ks.ps_gen(), 0)


class ReplicationLog:
    """Ordered per-shard update log through the store. The primary
    ``post``s records and ``wait_acked``s; the backup's applier thread
    ``take_next``s in order and ``ack``s after applying. Handler calls
    are serialized by the rpc dispatcher, so the sequence number is a
    plain local counter on each side."""

    def __init__(self, store, shard: int, next_seq: int = 1):
        self.store = store
        self.shard = shard
        self._next_post = next_seq  # primary side
        self._next_apply = next_seq  # backup side

    def post(self, record: dict) -> int:
        n = self._next_post
        self._next_post += 1
        self.store.set(_ks.ps_repl(self.shard, n),
                       pickle.dumps(record, protocol=4))
        return n

    def acked(self) -> int:
        raw = try_get(self.store, _ks.ps_replack(self.shard))
        return int(raw) if raw else 0

    def wait_acked(self, n: int, deadline_s: float,
                   alive: Callable[[], bool]) -> bool:
        """Block until the backup acked record ``n``; gives up (so the
        primary can degrade to unreplicated) when the backup's lease
        goes stale or ``deadline_s`` passes."""
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            if self.acked() >= n:
                return True
            if not alive():
                return False
            time.sleep(0.003)
        return False

    def take_next(self) -> Optional[dict]:
        key = _ks.ps_repl(self.shard, self._next_apply)
        raw = try_get(self.store, key)
        if raw is None:
            return None
        rec = pickle.loads(raw)
        try:
            self.store.delete(key)
        except Exception:
            pass
        self._next_apply += 1
        return rec

    def ack(self) -> None:
        self.store.set(_ks.ps_replack(self.shard),
                       str(self._next_apply - 1).encode())

    def applied_count(self) -> int:
        return self._next_apply - 1

    def resume_as_primary(self) -> None:
        """After promotion the drained backup becomes the shard's
        writer: continue the post counter where the applier stopped."""
        self._next_post = self._next_apply
