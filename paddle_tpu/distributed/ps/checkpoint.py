"""Durable PS table checkpoints (reference: the table ``save``/``load``
RPCs behind the_one_ps.py ``save_persistables`` — there shards write
rocksdb SST files; here each shard writes one ``.npy`` payload).

Same discipline as ``resilience/checkpoint_manager.py``: payloads go to
a tmp name then ``os.replace``; a CRC32+size manifest sidecar is
written (atomically) only AFTER the payload is durable, so the manifest
is the commit marker; readers verify the CRC and a step-directory scan
(``ShardCheckpointManager.latest_valid``) skips torn or bit-rotted
checkpoints instead of restoring garbage.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["PSCheckpointError", "write_table", "read_table",
           "validate_table_file", "ShardCheckpointManager"]

_STEP_FMT = "step_%08d"


class PSCheckpointError(RuntimeError):
    """A table checkpoint failed validation (missing manifest, size or
    CRC mismatch) — the caller must fall back, not restore it."""


def _normalize(path: str) -> str:
    # np.save appends ".npy" when missing; normalize up front so save
    # and load agree on the real filename (the historical bug was
    # save("t0") writing "t0.npy" and load("t0") then failing).
    return path if path.endswith(".npy") else path + ".npy"


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc


def _manifest_path(path: str) -> str:
    return path + ".manifest.json"


def write_table(path: str, sd: dict) -> str:
    """Atomically write one table state_dict; returns the real path."""
    path = _normalize(path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, np.array([sd], dtype=object), allow_pickle=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    manifest = {"size": os.path.getsize(path),
                "crc32": _crc32_file(path)}
    mtmp = _manifest_path(path) + ".tmp"
    with open(mtmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(mtmp, _manifest_path(path))
    return path


def validate_table_file(path: str) -> Tuple[bool, str]:
    path = _normalize(path)
    if not os.path.exists(path):
        return False, f"missing payload {path}"
    mpath = _manifest_path(path)
    if not os.path.exists(mpath):
        return False, f"missing manifest {mpath}"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest {mpath}: {e}"
    size = os.path.getsize(path)
    if size != manifest.get("size"):
        return False, (f"size mismatch for {path}: "
                       f"{size} != {manifest.get('size')}")
    crc = _crc32_file(path)
    if crc != manifest.get("crc32"):
        return False, (f"crc mismatch for {path}: "
                       f"{crc:#x} != {manifest.get('crc32', 0):#x}")
    return True, "ok"


def read_table(path: str, verify: bool = True) -> dict:
    """Load one table state_dict, verifying the manifest CRC when one
    exists (pre-manifest checkpoints still load with verify=False)."""
    path = _normalize(path)
    if verify and os.path.exists(_manifest_path(path)):
        ok, detail = validate_table_file(path)
        if not ok:
            raise PSCheckpointError(detail)
    return np.load(path, allow_pickle=True)[0]


class ShardCheckpointManager:
    """Step-directory checkpoints for a set of table shards, with
    corruption-skipping restore (the PS analog of
    ``resilience.CheckpointManager.latest_valid``)."""

    def __init__(self, root: str, keep_last: int = 2):
        self.root = root
        self.keep_last = int(keep_last)
        os.makedirs(root, exist_ok=True)

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, _STEP_FMT % step)

    @staticmethod
    def _file(table_id: int, shard: int) -> str:
        return f"table{table_id}_shard{shard}.npy"

    def save(self, step: int,
             tables: Dict[Tuple[int, int], dict]) -> str:
        """``tables`` maps (shard, table_id) -> state_dict. The step
        directory's MANIFEST.json (written last, atomically) is the
        commit marker listing every member file."""
        d = self._dir(step)
        tmp_d = d + ".tmp"
        os.makedirs(tmp_d, exist_ok=True)
        files = []
        for (shard, table_id), sd in sorted(tables.items()):
            name = self._file(table_id, shard)
            write_table(os.path.join(tmp_d, name), sd)
            files.append(name)
        os.replace(tmp_d, d)
        manifest = {"step": step, "files": files}
        mtmp = os.path.join(d, "MANIFEST.json.tmp")
        with open(mtmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(mtmp, os.path.join(d, "MANIFEST.json"))
        self._gc()
        return d

    def _steps(self):
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    out.append(int(name.split("_", 1)[1]))
                except ValueError:
                    continue
        return sorted(out)

    def validate_dir(self, step: int) -> Tuple[bool, str]:
        d = self._dir(step)
        mpath = os.path.join(d, "MANIFEST.json")
        if not os.path.exists(mpath):
            return False, f"missing commit marker {mpath}"
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"unreadable commit marker: {e}"
        for name in manifest.get("files", []):
            ok, detail = validate_table_file(os.path.join(d, name))
            if not ok:
                return False, detail
        return True, "ok"

    def latest_valid(self) -> Optional[Tuple[int, str]]:
        """Newest step directory that passes full validation; corrupt
        or torn steps are skipped (and counted) on the way down."""
        skipped = 0
        found = None
        for step in reversed(self._steps()):
            ok, _detail = self.validate_dir(step)
            if ok:
                found = (step, self._dir(step))
                break
            skipped += 1
        if skipped:
            try:
                from ... import observability as obs

                if obs.enabled():
                    obs.registry.counter(
                        "resilience.corrupt_checkpoints").inc(skipped)
            except Exception:
                pass
        return found

    def load(self, d: str) -> Dict[Tuple[int, int], dict]:
        with open(os.path.join(d, "MANIFEST.json")) as f:
            manifest = json.load(f)
        out: Dict[Tuple[int, int], dict] = {}
        for name in manifest["files"]:
            stem = name[:-len(".npy")]
            table_id = int(stem.split("_")[0][len("table"):])
            shard = int(stem.split("_shard")[1])
            out[(shard, table_id)] = read_table(os.path.join(d, name))
        return out

    def _gc(self) -> None:
        steps = self._steps()
        for step in steps[:-self.keep_last]:
            d = self._dir(step)
            for name in os.listdir(d):
                os.unlink(os.path.join(d, name))
            os.rmdir(d)
