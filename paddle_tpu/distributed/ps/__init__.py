"""Parameter-Server API surface — collective-first stubs (SURVEY §2.4.17;
reference: python/paddle/distributed/ps/the_one_ps.py, fleet role makers
python/paddle/distributed/fleet/base/role_maker.py).

Design decision (SURVEY-sanctioned): this TPU-native framework is
collective-first — dense training scales via GSPMD/ICI collectives, and
the brpc/rocksdb PS transport is intentionally not ported. This package
keeps the reference's PS-mode *API shape* so PS-style user code imports,
role-detects, and fails at the server boundary with actionable guidance
instead of AttributeError.
"""
from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "TheOnePSRuntime", "Table", "Accessor",
           "PSGuidanceError"]

_GUIDE = (
    "parameter-server mode is not supported by this TPU-native framework: "
    "the PS transport (brpc/rocksdb tables, reference "
    "fluid/distributed/ps/) is replaced by the collective-first design — "
    "dense parameters scale with sharding/GSPMD over ICI (see "
    "paddle_tpu.distributed.fleet and paddle_tpu.distributed.sharding). "
    "Migrate: fleet.init(is_collective=True); for huge embeddings use "
    "sharded embedding tables over the 'mp' mesh axis."
)


class PSGuidanceError(NotImplementedError):
    """Raised by every PS-runtime entry point, with migration guidance."""

    def __init__(self, what: str):
        super().__init__(f"{what}: {_GUIDE}")


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    """reference: fleet/base/role_maker.py RoleMakerBase."""

    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._server_num = 0

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id

    def server_index(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return self._worker_num

    def server_num(self) -> int:
        return self._server_num

    def get_trainer_endpoints(self) -> List[str]:
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    def get_pserver_endpoints(self) -> List[str]:
        return os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "").split(",")


class PaddleCloudRoleMaker(RoleMakerBase):
    """Role detection from the reference's env contract
    (TRAINING_ROLE / PADDLE_PORT / PADDLE_TRAINERS_NUM...)."""

    def __init__(self, is_collective: bool = False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        self._current_id = int(os.environ.get(
            "PADDLE_TRAINER_ID" if self._role == Role.WORKER
            else "PADDLE_PSERVER_ID", "0"))
        self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_num = len([e for e in eps.split(",") if e])


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints: Optional[List[str]] = None, **kwargs):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_eps = server_endpoints or []
        self._server_num = len(self._server_eps)

    def get_pserver_endpoints(self):
        return self._server_eps


class Accessor:
    """Table accessor schema stub (reference: the_one_ps.py Accessor)."""

    def __init__(self):
        self.accessor_class = ""
        self.optimizer = None
        self.feature_dim = 0
        self.embedding_dim = 0


class Table:
    """PS table stub (reference: the_one_ps.py Table): holds schema only;
    any data-plane call raises with guidance."""

    def __init__(self):
        self.id = -1
        self.table_class = ""
        self.shard_num = -1
        self.accessor = Accessor()

    def pull(self, *a, **k):
        raise PSGuidanceError("Table.pull")

    def push(self, *a, **k):
        raise PSGuidanceError("Table.push")


class TheOnePSRuntime:
    """reference: the_one_ps.py TheOnePSRuntime — every runtime entry
    raises PSGuidanceError so PS training scripts fail fast with a
    migration path rather than deep in missing attributes."""

    def __init__(self, role_maker=None):
        self.role_maker = role_maker or PaddleCloudRoleMaker()
        self.tables: List[Table] = []

    def _init_server(self, *a, **k):
        raise PSGuidanceError("init_server")

    init_server = _init_server

    def _run_server(self, *a, **k):
        raise PSGuidanceError("run_server")

    run_server = _run_server

    def _init_worker(self, *a, **k):
        raise PSGuidanceError("init_worker")

    init_worker = _init_worker

    def _stop_worker(self, *a, **k):
        raise PSGuidanceError("stop_worker")

    stop_worker = _stop_worker

    def save_persistables(self, *a, **k):
        raise PSGuidanceError("save_persistables (PS mode)")
