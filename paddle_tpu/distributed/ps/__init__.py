"""Parameter-Server tier (reference: python/paddle/distributed/ps/
the_one_ps.py, fleet role makers fleet/base/role_maker.py, and the
table data plane paddle/fluid/distributed/ps/table/).

TPU-native design: dense training stays collective-first (GSPMD over
ICI, SURVEY §2.4.17) — but the SPARSE data plane is real: in-memory
sparse/dense tables with server-side optimizers live behind the in-repo
rpc agent (data_plane.py replaces brpc/rocksdb), workers pull/push rows
sharded by `id % n_servers`, and TheOnePSRuntime drives the reference's
init_server/run_server/init_worker/stop_worker lifecycle over the same
env contract (TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST /
PADDLE_TRAINERS_NUM). Features outside this scope (heter workers, GPU
PS caches) raise PSGuidanceError with a migration path.
"""
from __future__ import annotations

import os
from typing import List, Optional

from .checkpoint import (  # noqa: F401
    PSCheckpointError,
    ShardCheckpointManager,
)
from .data_plane import (  # noqa: F401
    DenseTable,
    LocalTransport,
    PSConfig,
    PSFailover,
    PSServer,
    PSWorker,
    RpcTransport,
    SparseEmbedding,
    SparseTable,
)

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "TheOnePSRuntime", "Table", "Accessor",
           "PSGuidanceError", "SparseTable", "DenseTable", "PSServer",
           "PSWorker", "SparseEmbedding", "PSConfig", "PSFailover",
           "RpcTransport", "LocalTransport", "PSCheckpointError",
           "ShardCheckpointManager"]

_GUIDE = (
    "parameter-server mode is not supported by this TPU-native framework: "
    "the PS transport (brpc/rocksdb tables, reference "
    "fluid/distributed/ps/) is replaced by the collective-first design — "
    "dense parameters scale with sharding/GSPMD over ICI (see "
    "paddle_tpu.distributed.fleet and paddle_tpu.distributed.sharding). "
    "Migrate: fleet.init(is_collective=True); for huge embeddings use "
    "sharded embedding tables over the 'mp' mesh axis."
)


class PSGuidanceError(NotImplementedError):
    """Raised by every PS-runtime entry point, with migration guidance."""

    def __init__(self, what: str):
        super().__init__(f"{what}: {_GUIDE}")


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    """reference: fleet/base/role_maker.py RoleMakerBase."""

    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._server_num = 0

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id

    def server_index(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return self._worker_num

    def server_num(self) -> int:
        return self._server_num

    def get_trainer_endpoints(self) -> List[str]:
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    def get_pserver_endpoints(self) -> List[str]:
        return os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "").split(",")


class PaddleCloudRoleMaker(RoleMakerBase):
    """Role detection from the reference's env contract
    (TRAINING_ROLE / PADDLE_PORT / PADDLE_TRAINERS_NUM...)."""

    def __init__(self, is_collective: bool = False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        self._current_id = int(os.environ.get(
            "PADDLE_TRAINER_ID" if self._role == Role.WORKER
            else "PADDLE_PSERVER_ID", "0"))
        self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_num = len([e for e in eps.split(",") if e])


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints: Optional[List[str]] = None, **kwargs):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_eps = server_endpoints or []
        self._server_num = len(self._server_eps)

    def get_pserver_endpoints(self):
        return self._server_eps


class Accessor:
    """Table accessor schema stub (reference: the_one_ps.py Accessor)."""

    def __init__(self):
        self.accessor_class = ""
        self.optimizer = None
        self.feature_dim = 0
        self.embedding_dim = 0


class Table:
    """PS table schema (reference: the_one_ps.py Table). `kind` is
    "sparse" or "dense"; TheOnePSRuntime materializes the data plane
    from these on init_server."""

    def __init__(self, table_id: int = -1, kind: str = "sparse",
                 dim: int = 0, shape=None, optimizer: str = "adagrad",
                 lr: float = 0.01):
        self.id = table_id
        self.kind = kind
        self.table_class = ("MemorySparseTable" if kind == "sparse"
                            else "MemoryDenseTable")
        self.dim = dim
        self.shape = shape
        self.optimizer = optimizer
        self.lr = lr
        self.shard_num = -1
        self.accessor = Accessor()


class TheOnePSRuntime:
    """reference: the_one_ps.py TheOnePSRuntime — the PS lifecycle over
    the rpc-backed data plane. One rpc world: trainers are ranks
    [0, T), servers ranks [T, T+S), names trainer{i} / pserver{j}."""

    def __init__(self, role_maker=None):
        self.role_maker = role_maker or PaddleCloudRoleMaker()
        self.tables: List[Table] = []
        self.server: Optional[PSServer] = None
        self.worker: Optional[PSWorker] = None

    def add_table(self, table: Table):
        self.tables.append(table)
        return table

    def _world(self):
        t = self.role_maker.worker_num()
        s = self.role_maker.server_num()
        if s < 1:
            raise PSGuidanceError(
                "PS runtime needs PADDLE_PSERVERS_IP_PORT_LIST")
        return t, s

    def init_server(self, *a, timeout: Optional[float] = None, **k):
        from .. import rpc

        t, s = self._world()
        idx = self.role_maker.server_index()
        cfg = PSConfig()
        # "auto" replication turns on whenever the job runs >= 2
        # servers: each shard then has a primary and a backup replica
        self.server = PSServer(idx, n_servers=s, config=cfg,
                               replicated=cfg.replicated(s))
        for tb in self.tables:
            if tb.kind == "sparse":
                self.server.add_sparse_table(tb.id, tb.dim,
                                             optimizer=tb.optimizer,
                                             lr=tb.lr)
            else:
                # dense tables live only on the shard `id % s` — the
                # server hosts it iff it serves (or backs up) that shard
                self.server.add_dense_table(tb.id, tb.shape, lr=tb.lr)
        rpc.init_rpc(f"pserver{idx}", rank=t + idx, world_size=t + s,
                     timeout=timeout)
        self.server.start(rpc._agent.store if rpc._agent is not None
                          else None, world_size=t + s)

    def run_server(self, *a, **k):
        if self.server is None:
            raise PSGuidanceError("run_server before init_server")
        self.server.run()

    def init_worker(self, *a, timeout: Optional[float] = None, **k):
        from .. import rpc

        t, s = self._world()
        idx = self.role_maker.worker_index()
        rpc.init_rpc(f"trainer{idx}", rank=idx, world_size=t + s,
                     timeout=timeout)
        self.worker = PSWorker(t, s)
        return self.worker

    def stop_worker(self, *a, **k):
        if self.worker is not None:
            self.worker.stop()

    def save_persistables(self, dirname: str, *a, **k):
        """Ask the owning server(s) to snapshot their table shards
        (reference: the_one_ps.py _save_distributed_persistables).
        Sparse tables shard over every server; a dense table lives only
        on shard ``table_id % n_servers``. Each shard is saved by its
        CURRENT primary (which may be a promoted backup), with an
        atomic CRC-manifested write (ps/checkpoint.py)."""
        if self.worker is None:
            raise PSGuidanceError("save_persistables before init_worker")
        _, s = self._world()
        os.makedirs(dirname, exist_ok=True)
        for tb in self.tables:
            shards = range(s) if tb.kind == "sparse" else [tb.id % s]
            for si in shards:
                self.worker.save_table(
                    si, tb.id,
                    os.path.join(dirname, f"table{tb.id}_shard{si}.npy"))
