"""Sparse/dense PS tables (reference: memory_sparse_table.cc +
ctr_accessor.cc — per-row optimizer state, admission filters and
capacity-bounded eviction behind the accessor's EntryAttr config).

Two properties here carry the whole replication design in
``replication.py`` / ``data_plane.py``:

* **Per-id deterministic init.** A row's initial value depends only on
  ``(table seed, row id)`` — NOT on creation order. Every shard of a
  table and every replica of a shard constructs rows identically, so
  pull-created rows never need to be replicated and a sharded
  deployment is bit-identical to one local table.
* **Push-only mutation of admission/eviction state.** Admission counts
  and the eviction clock advance only on pushes (which the primary
  replicates); pulls leave them untouched. A primary that has served
  pulls a backup never saw still converges to the same pushed-row
  state, which is what failover promotes.
"""
from __future__ import annotations

import struct
import threading
import zlib
from typing import Dict, Optional

import numpy as np

__all__ = ["SparseTable", "DenseTable"]


def _obs():
    try:
        from ... import observability as obs

        return obs if obs.enabled() else None
    except Exception:
        return None


def _row_rng(seed: int, rid: int) -> np.random.Generator:
    return np.random.default_rng([int(seed) & 0xFFFFFFFF,
                                  int(rid) & 0xFFFFFFFFFFFFFFFF])


class SparseTable:
    """In-memory sparse table with lazy row init + per-row optimizer
    state (reference: memory_sparse_table.cc + the sparse accessors
    ctr_accessor.cc — sgd/adagrad/adam rules per embedding row).

    ``entry_attr`` (an ``extras.ProbabilityEntry`` /
    ``CountFilterEntry``, duck-typed) gates row materialization the way
    the reference accessor does: with an entry filter configured, pulls
    of unmaterialized ids return the deterministic init WITHOUT storing
    a row, and pushes admit the row only once the filter passes (denied
    gradients are dropped, counted in ``ps.admission_denied``).

    ``capacity`` bounds the number of *pushed* rows: when exceeded, the
    least-recently-pushed rows are evicted (``ps.evictions``). The
    push-recency clock is replication-safe — it only moves on pushes.
    """

    def __init__(self, dim: int, optimizer: str = "adagrad",
                 lr: float = 0.01, initializer: str = "uniform",
                 init_scale: float = 0.01, seed: int = 0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8, entry_attr=None,
                 capacity: Optional[int] = None):
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unsupported sparse optimizer {optimizer}")
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.initializer = initializer
        self.init_scale = float(init_scale)
        self.seed = int(seed)
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self.capacity = int(capacity) if capacity is not None else None
        # entry_attr is duck-typed (avoids importing extras, which
        # pulls in jax): ProbabilityEntry carries _probability,
        # CountFilterEntry carries _count_filter.
        self._admit_prob = getattr(entry_attr, "_probability", None)
        self._admit_count = getattr(entry_attr, "_count_filter", None)
        self._gated = entry_attr is not None
        self._rows: Dict[int, np.ndarray] = {}  # guarded by: _lock
        self._state: Dict[int, list] = {}  # guarded by: _lock
        self._step: Dict[int, int] = {}  # guarded by: _lock
        self._counts: Dict[int, int] = {}  # guarded by: _lock
        self._ticks: Dict[int, int] = {}  # guarded by: _lock
        self._tick = 0  # guarded by: _lock
        self.evictions = 0  # guarded by: _lock
        self.admission_denied = 0  # guarded by: _lock
        self._lock = threading.Lock()

    def _init_row(self, rid: int) -> np.ndarray:
        if self.initializer == "zeros":
            return np.zeros(self.dim, np.float32)
        return _row_rng(self.seed, rid).uniform(
            -self.init_scale, self.init_scale,
            self.dim).astype(np.float32)

    def _admits(self, rid: int, count: int) -> bool:
        """Deterministic admission decision for an unmaterialized row —
        identical on every replica (stateless hash for probability,
        replicated push count for the count filter)."""
        if self._admit_count is not None:
            return count >= self._admit_count
        if self._admit_prob is not None:
            h = zlib.crc32(struct.pack("<qq", self.seed, int(rid)))
            return (h / 0x100000000) < self._admit_prob
        return True

    def pull(self, ids) -> np.ndarray:
        """Rows for ids [n] -> [n, dim]; missing rows are created
        (reference: pull_sparse with create-on-miss) — unless an entry
        filter is configured, in which case unadmitted ids are served
        their deterministic init value without materializing."""
        with self._lock:
            out = np.empty((len(ids), self.dim), np.float32)
            for i, rid in enumerate(ids):
                rid = int(rid)
                row = self._rows.get(rid)
                if row is None:
                    if self._gated:
                        row = self._init_row(rid)
                    else:
                        row = self._rows[rid] = self._init_row(rid)
                out[i] = row
            return out

    def push(self, ids, grads) -> None:
        """Apply per-row optimizer updates; duplicate ids in one push
        are accumulated first (the embedding-bag contract)."""
        grads = np.asarray(grads, np.float32)
        uniq: Dict[int, np.ndarray] = {}
        for rid, g in zip(ids, grads):
            rid = int(rid)
            if rid in uniq:
                uniq[rid] = uniq[rid] + g
            else:
                uniq[rid] = g.copy()
        denied = 0
        with self._lock:
            for rid, g in uniq.items():
                row = self._rows.get(rid)
                if row is None:
                    count = self._counts.get(rid, 0) + 1
                    if self._admit_count is not None:
                        self._counts[rid] = count
                    if not self._admits(rid, count):
                        self.admission_denied += 1
                        denied += 1
                        continue
                    row = self._rows[rid] = self._init_row(rid)
                self._apply_locked(rid, row, g)
                self._tick += 1
                self._ticks[rid] = self._tick
            evicted = self._evict_locked()
        o = _obs()
        if o:
            if denied:
                o.registry.counter("ps.admission_denied").inc(denied)
            if evicted:
                o.registry.counter("ps.evictions").inc(evicted)

    def _apply_locked(self, rid: int, row: np.ndarray,
                      g: np.ndarray) -> None:  # ptlint: holds=_lock
        if self.optimizer == "sgd":
            row -= self.lr * g
        elif self.optimizer == "adagrad":
            st = self._state.setdefault(
                rid, [np.zeros(self.dim, np.float32)])
            st[0] += g * g
            row -= self.lr * g / (np.sqrt(st[0]) + self.eps)
        else:  # adam
            st = self._state.setdefault(
                rid, [np.zeros(self.dim, np.float32),
                      np.zeros(self.dim, np.float32)])
            t = self._step.get(rid, 0) + 1
            self._step[rid] = t
            st[0] = self.beta1 * st[0] + (1 - self.beta1) * g
            st[1] = self.beta2 * st[1] + (1 - self.beta2) * g * g
            mhat = st[0] / (1 - self.beta1 ** t)
            vhat = st[1] / (1 - self.beta2 ** t)
            row -= self.lr * mhat / (np.sqrt(vhat) + self.eps)

    def _evict_locked(self) -> int:  # ptlint: holds=_lock
        """LRU-by-push eviction down to ``capacity`` pushed rows, plus
        hygiene for pull-created rows once the table is over budget.
        Dropping a never-pushed row is a semantic no-op (per-id init
        recreates it bit-identically), so replicas need not agree on
        which pull-created rows exist."""
        if self.capacity is None:
            return 0
        evicted = 0
        if len(self._ticks) > self.capacity:
            overflow = len(self._ticks) - self.capacity
            for rid, _t in sorted(self._ticks.items(),
                                  key=lambda kv: kv[1])[:overflow]:
                self._drop_locked(rid)
                evicted += 1
        if len(self._rows) > self.capacity:
            cold = sorted(r for r in self._rows if r not in self._ticks)
            for rid in cold[:len(self._rows) - self.capacity]:
                self._drop_locked(rid)
                evicted += 1
        self.evictions += evicted
        return evicted

    def _drop_locked(self, rid: int) -> None:  # ptlint: holds=_lock
        self._rows.pop(rid, None)
        self._state.pop(rid, None)
        self._step.pop(rid, None)
        self._ticks.pop(rid, None)

    def counters(self) -> dict:
        with self._lock:
            return {"evictions": self.evictions,
                    "admission_denied": self.admission_denied,
                    "rows": len(self._rows)}

    def digest(self) -> str:
        """Order-independent CRC over the full mutable state — two
        tables with equal digests are bit-identical (rows, optimizer
        state, step counters, admission counts, eviction clock)."""
        with self._lock:
            h = zlib.crc32(struct.pack("<q", self._tick))
            for rid in sorted(self._rows):
                b = struct.pack("<q", rid) + self._rows[rid].tobytes()
                for s in self._state.get(rid, []):
                    b += s.tobytes()
                b += struct.pack("<qq", self._step.get(rid, 0),
                                 self._ticks.get(rid, 0))
                h = zlib.crc32(b, h)
            for rid in sorted(self._counts):
                h = zlib.crc32(struct.pack(
                    "<qq", rid, self._counts[rid]), h)
            return f"{h:08x}"

    def state_dict(self) -> dict:
        with self._lock:
            return {"dim": self.dim, "optimizer": self.optimizer,
                    "rows": {k: v.copy() for k, v in self._rows.items()},
                    "state": {k: [s.copy() for s in v]
                              for k, v in self._state.items()},
                    "step": dict(self._step),
                    "counts": dict(self._counts),
                    "ticks": dict(self._ticks),
                    "tick": self._tick}

    def load_state_dict(self, sd: dict) -> None:
        with self._lock:
            self._rows = {int(k): np.asarray(v, np.float32)
                          for k, v in sd["rows"].items()}
            self._state = {int(k): [np.asarray(s, np.float32) for s in v]
                           for k, v in sd.get("state", {}).items()}
            self._step = {int(k): int(v)
                          for k, v in sd.get("step", {}).items()}
            self._counts = {int(k): int(v)
                            for k, v in sd.get("counts", {}).items()}
            self._ticks = {int(k): int(v)
                           for k, v in sd.get("ticks", {}).items()}
            self._tick = int(sd.get("tick", 0))

    def __len__(self):
        with self._lock:
            return len(self._rows)


class DenseTable:
    """Dense parameter vector with server-side SGD (reference:
    memory_dense_table.cc). Init is a pure function of ``seed`` so a
    replica constructed with the same ctor args starts bit-identical."""

    def __init__(self, shape, lr: float = 0.01, seed: int = 0):
        self.lr = float(lr)
        self._value = np.random.default_rng(seed).uniform(  # guarded by: _lock
            -0.01, 0.01, shape).astype(np.float32)
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._value.copy()

    def push(self, grad) -> None:
        with self._lock:
            self._value -= self.lr * np.asarray(grad, np.float32)

    def set(self, value) -> None:
        with self._lock:
            self._value = np.asarray(value, np.float32).copy()

    def digest(self) -> str:
        with self._lock:
            return f"{zlib.crc32(self._value.tobytes()):08x}"

    def state_dict(self) -> dict:
        with self._lock:
            return {"value": self._value.copy(), "lr": self.lr}

    def load_state_dict(self, sd: dict) -> None:
        with self._lock:
            self._value = np.asarray(sd["value"], np.float32).copy()

    def __len__(self):
        with self._lock:
            return int(self._value.size)
