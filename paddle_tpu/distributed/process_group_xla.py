"""ProcessGroupXLA: collectives as compiled XLA programs over ICI/DCN
(the single most important native component per SURVEY §2.2 — the TPU
equivalent of fluid/distributed/collective/process_group_nccl.cc).

Design: each collective compiles (and caches, keyed by
(op, shape, dtype, group)) a one-collective jitted program over the global
device mesh spanning the group's processes, using shard_map + lax collective
primitives. Requires jax.distributed.initialize() (one process per host) —
done by init_parallel_env when launched multi-process.

Ordering: XLA programs on a TPU stream execute in issue order per device, so
the reference's comm-stream event chaining (process_group_nccl.cc:902-991)
maps to plain issue order here; Task.wait() is a no-op barrier on the jax
async dispatch (block_until_ready).
"""
from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .process_group import ProcessGroup, ReduceOp, Task

__all__ = ["ProcessGroupXLA"]

_LAX_REDUCE = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}


class ProcessGroupXLA(ProcessGroup):
    def __init__(self, store, rank: int, world_size: int, gid: int = 0,
                 group_ranks: Optional[List[int]] = None):
        super().__init__(rank, world_size, gid, group_ranks)
        self._store = store
        self._ranks = self._group_ranks
        # one process per host: the group's devices = all local devices of
        # the member processes
        self._mesh_cache = {}
        self._fn_cache = {}

    def _global_mesh(self):
        """1-D mesh over one device per member process (cross-host axis)."""
        key = tuple(self._ranks)
        if key not in self._mesh_cache:
            devs = []
            all_devices = jax.devices()
            for r in self._ranks:
                cand = [d for d in all_devices if d.process_index == r]
                if not cand:
                    raise RuntimeError(
                        f"no devices for process {r}; is jax.distributed "
                        "initialized with one process per host?")
                devs.append(cand[0])
            self._mesh_cache[key] = jax.sharding.Mesh(
                np.array(devs), axis_names=("x",))
        return self._mesh_cache[key]

    def _run_collective(self, tag, arr, fn_builder):
        """Execute fn over the group mesh with the local array as this
        process's shard."""
        from jax.experimental import multihost_utils

        mesh = self._global_mesh()
        cache_key = (tag, arr.shape, str(arr.dtype), tuple(self._ranks))
        if cache_key not in self._fn_cache:
            self._fn_cache[cache_key] = fn_builder(mesh)
        fn = self._fn_cache[cache_key]
        global_arr = multihost_utils.host_local_array_to_global_array(
            arr, mesh, jax.sharding.PartitionSpec("x"))
        out = fn(global_arr)
        local = multihost_utils.global_array_to_host_local_array(
            out, mesh, jax.sharding.PartitionSpec("x"))
        return np.asarray(local)

    def _all_reduce_impl(self, arr, op):
        import jax.sharding as shd
        from jax.experimental.shard_map import shard_map

        a = np.asarray(arr)[None]  # stack axis for the mesh dim

        def builder(mesh):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=shd.PartitionSpec("x"),
                out_specs=shd.PartitionSpec("x"))
            def f(x):
                if op == ReduceOp.PROD:
                    # no pprod primitive: gather contributions, reduce local
                    full = jax.lax.all_gather(x, "x", axis=0, tiled=True)
                    return jnp.prod(full, axis=0, keepdims=True)
                red = _LAX_REDUCE.get(op, jax.lax.psum)
                r = red(x, "x")
                if op == ReduceOp.AVG:
                    r = r / len(self._ranks)
                return r

            return f

        return self._run_collective(f"allreduce{int(op)}", a, builder)[0]

    def _broadcast_impl(self, arr, src):
        # src already translated to group-local by the base class
        src_idx = src
        a = np.asarray(arr)[None]
        import jax.sharding as shd
        from jax.experimental.shard_map import shard_map

        def builder(mesh):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=shd.PartitionSpec("x"),
                out_specs=shd.PartitionSpec("x"))
            def f(x):
                full = jax.lax.all_gather(x, "x", axis=0, tiled=True)
                return full[src_idx][None]

            return f

        return self._run_collective(f"broadcast{src_idx}", a, builder)[0]

    def _all_gather_impl(self, arr):
        a = np.asarray(arr)[None]
        import jax.sharding as shd
        from jax.experimental.shard_map import shard_map

        n = len(self._ranks)

        def builder(mesh):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=shd.PartitionSpec("x"),
                out_specs=shd.PartitionSpec("x"))
            def f(x):
                full = jax.lax.all_gather(x, "x", axis=0, tiled=True)
                return full[None]  # replicated result, shard dim 1

            return f

        out = self._run_collective("allgather", a, builder)
        return [out[0][i] for i in range(n)]

    def _reduce_impl(self, arr, dst, op):
        out = self._all_reduce_impl(arr, op)
        return out if self._rank == dst else arr

    def _reduce_scatter_impl(self, arrs, op):
        stacked = np.stack(arrs)  # [n, ...] local contributions
        summed = self._all_reduce_impl(stacked, op)
        return summed[self._rank]

    def _scatter_impl(self, arrs, src, shape, dtype):
        if self._rank == src:
            stacked = np.stack(arrs)
        else:
            stacked = np.zeros((len(self._ranks),) + tuple(shape),
                               dtype=dtype)
        out = self._broadcast_impl(stacked, src)
        return out[self._rank]

    def _gather_impl(self, arr, dst):
        outs = self._all_gather_impl(arr)
        return outs if self._rank == dst else []

    def _all_to_all_impl(self, arrs):
        a = np.stack(arrs)[None]  # [1, n, ...]
        import jax.sharding as shd
        from jax.experimental.shard_map import shard_map

        def builder(mesh):
            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=shd.PartitionSpec("x"),
                out_specs=shd.PartitionSpec("x"))
            def f(x):
                # x: [1, n, ...] per member; all_to_all over axis 1
                return jax.lax.all_to_all(x, "x", split_axis=1,
                                          concat_axis=1, tiled=False)

            return f

        out = self._run_collective("alltoall", a, builder)
        return [out[0][i] for i in range(len(self._ranks))]

    def _send_impl(self, arr, dst):
        # p2p over the store (control path); steady-state PP on TPU should
        # use the compiled collective_permute path in parallel/pipeline
        import pickle

        key = self._p2p_key_xla(self._rank, dst)
        self._store.set(key, pickle.dumps(np.asarray(arr), protocol=4))

    def _recv_impl(self, src, shape, dtype):
        import pickle

        key = self._p2p_key_xla(src, self._rank)
        return pickle.loads(self._store.get(key))

    def _p2p_key_xla(self, src, dst):
        if not hasattr(self, "_p2p_seq"):
            self._p2p_seq = {}
        k = (src, dst)
        self._p2p_seq[k] = self._p2p_seq.get(k, 0) + 1
        return f"pgx{self._gid}/p2p/{src}->{dst}/{self._p2p_seq[k]}"

    def _barrier_impl(self):
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"pg{self._gid}_barrier")
